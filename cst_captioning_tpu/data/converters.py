"""Dataset-format converters -> the prepro annotation contract.

The prepro CLI consumes ``{"videos": [{"id": ..., "captions": [...]}]}``
(SURVEY.md §2 "Offline prepro").  These converters map the public release
formats of the datasets the reference targets onto that shape, splitting by
the datasets' standard conventions:

- MSR-VTT ``videodatainfo.json`` (10k videos; "sentences" list with
  ``video_id``/``caption``, "videos" list with a ``split`` field),
- MSVD / Youtube2Text caption lists (``<clip_id> <caption>`` lines, one per
  caption, clip ids like vid1234 or YouTube-hash_start_end),
- ActivityNet Captions (``{vid: {"sentences": [...], "timestamps": ...}}``
  per-split JSONs).

Each returns {"train"/"val"/"test": [{"id", "captions"}]} ready for
``prepro.build_split`` — use the train vocab for val/test.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Dict, List, Mapping, Optional, Sequence

from ..resilience.integrity import atomic_json_write

Annotations = List[dict]


def _group(pairs) -> Dict[str, List[str]]:
    by_vid: Dict[str, List[str]] = defaultdict(list)
    for vid, cap in pairs:
        by_vid[str(vid)].append(str(cap))
    return by_vid


def _to_annotations(by_vid: Mapping[str, Sequence[str]]) -> Annotations:
    return [{"id": vid, "captions": list(caps)}
            for vid, caps in by_vid.items()]


def convert_msrvtt(videodatainfo: dict) -> Dict[str, Annotations]:
    """MSR-VTT ``videodatainfo.json`` -> per-split annotations.

    Uses the file's own ``split`` field ("train"/"validate"/"test");
    "validate" is renamed "val".
    """
    split_of = {str(v["video_id"]): v.get("split", "train")
                for v in videodatainfo["videos"]}
    by_vid = _group((s["video_id"], s["caption"])
                    for s in videodatainfo["sentences"])
    out: Dict[str, List[dict]] = {"train": [], "val": [], "test": []}
    for vid, caps in by_vid.items():
        split = split_of.get(vid, "train")
        split = {"validate": "val"}.get(split, split)
        out.setdefault(split, []).append({"id": vid, "captions": caps})
    return out


def convert_msvd(
    caption_lines: Sequence[str],
    splits: Optional[Mapping[str, Sequence[str]]] = None,
    train_frac: float = 1200 / 1970,
    val_frac: float = 100 / 1970,
) -> Dict[str, Annotations]:
    """MSVD ``<clip_id><ws><caption>`` lines -> per-split annotations.

    Lines split on the first whitespace run (the public caption files are
    tab-separated; space-separated variants work too).  ``splits`` maps
    split name -> clip-id list if an official split file is available;
    otherwise clips are split deterministically (sorted order) with the
    standard 1200/100/670 proportions as default fractions.
    """
    pairs = []
    for line in caption_lines:
        parts = line.strip().split(maxsplit=1)
        if len(parts) == 2:
            pairs.append((parts[0], parts[1]))
    by_vid = _group(pairs)
    if splits is not None:
        return {
            name: _to_annotations({v: by_vid[v] for v in vids if v in by_vid})
            for name, vids in splits.items()
        }
    vids = sorted(by_vid)
    n = len(vids)
    n_train = int(n * train_frac)
    n_val = int(n * val_frac)
    return {
        "train": _to_annotations({v: by_vid[v] for v in vids[:n_train]}),
        "val": _to_annotations(
            {v: by_vid[v] for v in vids[n_train:n_train + n_val]}),
        "test": _to_annotations({v: by_vid[v] for v in vids[n_train + n_val:]}),
    }


def convert_activitynet(split_files: Mapping[str, dict]) -> Dict[str, Annotations]:
    """ActivityNet Captions per-split dicts -> annotations.

    ``split_files`` maps split name -> the loaded JSON
    ({vid: {"sentences": [...]}}); ActivityNet distributes train/val_1/val_2
    separately, so the caller chooses the mapping (e.g. val_1 -> val).
    """
    out = {}
    for name, blob in split_files.items():
        out[name] = _to_annotations(
            {vid: [s.strip() for s in item["sentences"]]
             for vid, item in blob.items()}
        )
    return out


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--format", required=True,
                    choices=("msrvtt", "msvd", "activitynet"))
    ap.add_argument("--input", required=True, nargs="+",
                    help="msrvtt: videodatainfo.json | msvd: captions txt | "
                         "activitynet: train.json [val.json ...]")
    ap.add_argument("--out_prefix", required=True,
                    help="writes <out_prefix><split>_anns.json per split")
    args = ap.parse_args(argv)

    if args.format == "msrvtt":
        with open(args.input[0]) as f:
            splits = convert_msrvtt(json.load(f))
    elif args.format == "msvd":
        with open(args.input[0]) as f:
            splits = convert_msvd(f.readlines())
    else:
        names = ("train", "val", "test")[: len(args.input)]
        loaded = {}
        for name, path in zip(names, args.input):
            with open(path) as f:
                loaded[name] = json.load(f)
        splits = convert_activitynet(loaded)

    written = {}
    for split, anns in splits.items():
        if not anns:
            continue
        path = f"{args.out_prefix}{split}_anns.json"
        atomic_json_write(path, {"videos": anns})
        written[split] = path
    print(json.dumps(written, indent=2))
    return written


if __name__ == "__main__":
    main()
