"""Batch assembly + host->device streaming — the reference DataLoader, TPU-shaped.

Responsibilities (SURVEY.md §3.5, restated for XLA):

- fixed-shape batches: ``batch_size`` videos × ``seq_per_img`` captions,
  labels always (B*seq_per_img, L) — static shapes so every jit traces once;
- shuffled epoch order with wrap-around (partial final batches are filled
  from the next epoch, matching the reference's infinite get_batch stream);
- per-caption consensus weights for WXE (from the consensus pickle);
- raw ground-truth strings carried alongside for the RL reward path;
- multi-host sharding: each JAX process sees a disjoint stride of the
  video list (``process_index``/``process_count``), the TPU-native
  replacement for the reference's single-node DataParallel split;
- ``prefetch_to_device``: a one-deep background thread pipelining h5 reads
  + ``jax.device_put`` of batch t+1 under the step computation of batch t.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..resilience.faults import FaultPlan, InjectedFault
from ..telemetry.spans import NULL_SPAN
from .dataset import CaptionDataset

log = logging.getLogger("cst_captioning_tpu.loader")

#: Error classes the prefetch worker treats as TRANSIENT (retry with
#: backoff before poisoning the stream): h5py surfaces flaky NFS/FUSE
#: reads as OSError/IOError, and the chaos harness injects the same shape.
TRANSIENT_ERRORS = (OSError,)


@dataclass
class Batch:
    """One training/eval batch. Feature arrays are (B, T_m, D_m); labels and
    weights are flattened over (video, caption) -> (B*seq_per_img, ...)."""

    feats: List[np.ndarray]
    labels: np.ndarray                 # (B*S, L) int32, 0-padded
    weights: np.ndarray                # (B*S,) float32 consensus weights (1.0 = XE)
    video_ids: List[str]               # length B
    gts: Dict[str, List[str]] = field(default_factory=dict)  # refs for reward
    video_ix: Optional[np.ndarray] = None  # (B,) dataset indices

    @property
    def batch_videos(self) -> int:
        return len(self.video_ids)


class CaptionLoader:
    """Infinite shuffled batch stream over a CaptionDataset split."""

    def __init__(
        self,
        dataset: CaptionDataset,
        batch_size: int,
        seq_per_img: int = 20,
        shuffle: bool = True,
        seed: int = 0,
        consensus_weights: Optional[Dict[str, np.ndarray]] = None,
        process_index: int = 0,
        process_count: int = 1,
        include_gts: bool = False,
        include_feats: bool = True,
        fault_plan: Optional[FaultPlan] = None,
    ):
        self.ds = dataset
        # Chaos hook (resilience/faults.py): ``loader_err@batch=N`` raises
        # a transient error from batch N's feature read.  None = disarmed,
        # one host-side None-check per batch.
        self._faults = fault_plan
        self._batches_served = 0
        self.batch_size = batch_size
        self.seq_per_img = seq_per_img
        self.shuffle = shuffle
        self._rng = np.random.default_rng(seed + process_index)
        self.consensus_weights = consensus_weights
        self.include_gts = include_gts
        # include_feats=False skips the per-batch h5 feature reads entirely —
        # the --device_feats path keeps all features resident in HBM and
        # gathers them by Batch.video_ix inside the train step.
        self.include_feats = include_feats
        self._refs = dataset.references() if include_gts else None

        # Multi-host shard: strided so every process gets an equal slice
        # regardless of dataset ordering.  The stride is PUBLIC contract:
        # evaluation.gather_strided_predictions reconstructs every other
        # host's shard from (process_index, process_count, num_videos).
        self.process_index = process_index
        self.process_count = process_count
        self._my_videos = np.arange(dataset.num_videos)[process_index::process_count]
        if len(self._my_videos) == 0:
            raise ValueError("process shard is empty; dataset smaller than host count")
        self._order = self._my_videos.copy()
        self._pos = len(self._order)  # force shuffle on first batch
        self.epoch = 0

    # -- epoch bookkeeping -------------------------------------------------

    def _next_indices(self, n: int) -> np.ndarray:
        out = []
        while n > 0:
            if self._pos >= len(self._order):
                if self.shuffle:
                    self._rng.shuffle(self._order)
                self._pos = 0
                self.epoch += 1
            take = min(n, len(self._order) - self._pos)
            out.append(self._order[self._pos : self._pos + take])
            self._pos += take
            n -= take
        return np.concatenate(out)

    @property
    def batches_per_epoch(self) -> int:
        return max(1, len(self._my_videos) // self.batch_size)

    # -- batch assembly ----------------------------------------------------

    def _select_caption_rows(self, video_ix: int, n: int) -> np.ndarray:
        """The ONE place caption-row selection consumes RNG draws: used by
        ``next_batch`` (via ``_pick_captions``) and replayed draw-for-draw
        by ``skip_batches`` so a fast-forwarded stream stays bit-identical
        to one that actually served the skipped batches."""
        if n == 0:
            raise ValueError(
                f"video {self.ds.video_ids[video_ix]!r} has no captions"
            )
        if n >= self.seq_per_img:
            sel = self._rng.choice(n, self.seq_per_img, replace=False) if self.shuffle \
                else np.arange(self.seq_per_img)
        else:
            sel = self._rng.choice(n, self.seq_per_img, replace=True)
        return np.sort(sel)

    def _pick_captions(self, video_ix: int) -> Tuple[np.ndarray, np.ndarray]:
        """-> ((seq_per_img, L) caption rows, their indices within the video's
        caption list); samples with replacement if the video has fewer."""
        caps = self.ds.captions_for(video_ix)
        sel = self._select_caption_rows(video_ix, caps.shape[0])
        return caps[sel], sel

    def skip_batches(self, n: int) -> None:
        """Fast-forward the stream by ``n`` batches WITHOUT assembling them:
        replays exactly the RNG draws ``next_batch`` would have made (epoch
        shuffles + per-video caption selections) at index-bookkeeping cost
        — no h5 feature/label reads.

        This is the data half of deterministic resume: a run restored at
        step N calls ``skip_batches(N)`` so it consumes the SAME batch
        sequence from step N onward that an uninterrupted run of the same
        seed would have — without it, a resumed run replays the stream
        from batch 0 and its post-resume params diverge from the
        uninterrupted twin's."""
        if n <= 0:
            return
        log.info("fast-forwarding the batch stream by %d batch(es) "
                 "(deterministic resume alignment)", n)
        for _ in range(int(n)):
            for v in self._next_indices(self.batch_size):
                self._select_caption_rows(int(v), self.ds.num_captions(int(v)))
            self._batches_served += 1

    def next_batch(self) -> Batch:
        if (self._faults is not None
                and self._faults.fire("loader_err", self._batches_served)):
            raise InjectedFault(
                f"injected transient feature-read error at batch "
                f"{self._batches_served}")
        ix = self._next_indices(self.batch_size)
        feats = self.ds.features(ix) if self.include_feats else []
        labels = np.zeros((self.batch_size * self.seq_per_img, self.ds.seq_length),
                          dtype=np.int32)
        weights = np.ones(self.batch_size * self.seq_per_img, dtype=np.float32)
        vids = []
        for b, v in enumerate(ix):
            rows, sel = self._pick_captions(int(v))
            labels[b * self.seq_per_img : (b + 1) * self.seq_per_img] = rows
            vid = self.ds.video_ids[int(v)]
            vids.append(vid)
            if self.consensus_weights is not None and vid in self.consensus_weights:
                w = np.asarray(self.consensus_weights[vid], dtype=np.float32)
                weights[b * self.seq_per_img : (b + 1) * self.seq_per_img] = w[sel]
        gts = {}
        if self.include_gts and self._refs is not None:
            gts = {vid: self._refs[vid] for vid in vids if vid in self._refs}
        self._batches_served += 1
        return Batch(feats=feats, labels=labels, weights=weights,
                     video_ids=vids, gts=gts, video_ix=ix)

    def __iter__(self) -> Iterator[Batch]:
        while True:
            yield self.next_batch()

    # -- eval iteration (single pass, in order) ----------------------------

    def iter_eval(self) -> Iterator[Batch]:
        """One ordered, non-shuffled pass; final batch wraps (callers dedupe
        by video id).  Keeps shapes static for the jitted decode."""
        n = len(self._my_videos)
        for start in range(0, n, self.batch_size):
            ix = self._my_videos[start : start + self.batch_size]
            if len(ix) < self.batch_size:  # pad by cycling to keep shape static
                pad = np.resize(self._my_videos, self.batch_size - len(ix))
                ix = np.concatenate([ix, pad])
            feats = self.ds.features(ix)
            vids = [self.ds.video_ids[int(v)] for v in ix]
            yield Batch(
                feats=feats,
                labels=np.zeros((self.batch_size * self.seq_per_img,
                                 self.ds.seq_length), dtype=np.int32),
                weights=np.ones(self.batch_size * self.seq_per_img, dtype=np.float32),
                video_ids=vids,
                video_ix=ix,
            )


def prefetch_to_device(batches: Union[CaptionLoader, Iterator[Batch]],
                       size: int = 2, device_put=None, feat_dtype=None,
                       retries: int = 3,
                       retry_backoff_s: float = 0.05,
                       telemetry=None) -> Iterator[Batch]:
    """Run batch assembly (h5 reads, numpy packing) in a background thread,
    optionally applying ``device_put`` (e.g. a sharding-aware jax.device_put)
    to feats/labels/weights before handing the batch to the consumer.

    This is the TPU replacement for the reference's synchronous get_batch ->
    .cuda() at the call site: HBM transfer of batch t+1 overlaps step t.

    ``feat_dtype`` (e.g. ``ml_dtypes.bfloat16``) casts feature arrays on the
    HOST before the transfer, halving host->device bytes for bf16 compute —
    the features are cast to the model dtype on device anyway, so when the
    model runs bf16 this only moves the (value-preserving) cast before the
    wire.  Labels/weights are untouched.

    Transient-error policy: when ``batches`` is a loader (anything with a
    ``next_batch`` method, so the producing call can be re-issued), a
    ``TRANSIENT_ERRORS`` failure during batch assembly is retried up to
    ``retries`` times with exponential backoff before the poison-pill
    exception propagates — a single flaky NFS read must not kill a
    multi-hour run.  A retried batch redraws from the (infinite,
    wrap-around) stream, which only reorders coverage within the epoch.
    Plain iterators keep the old fail-fast contract: a generator is dead
    after it raises, so retrying it would silently end the stream instead
    of surfacing the error.

    Worker lifetime: abandoning the iterator (break / GeneratorExit) wakes
    the worker via the ``closed`` event and JOINS it, so no thread — and no
    prefetched HBM buffer it holds — outlives the consumer.

    ``telemetry`` (a ``telemetry.Telemetry``, optional): retry attempts
    count into the ``loader_retries`` counter, and when span tracing is
    armed the worker records ``prefetch_assemble`` (h5 reads + numpy
    packing) and ``prefetch_device_put`` spans on its own trace row — the
    overlap of batch t+1's IO under step t's compute becomes visible in
    the Chrome trace.  None = one is-None check per batch.
    """
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = object()
    closed = threading.Event()  # consumer gone: worker must drop its buffers
    tracer = telemetry.tracer if telemetry is not None else None
    if telemetry is not None:
        # Declared at 0 at prefetch start (cstlint:declared-counters):
        # 0 in the snapshot means the retry path was armed and unused.
        telemetry.declare("loader_retries")

    next_batch = getattr(batches, "next_batch", None)
    if next_batch is None:
        it = iter(batches)
        retries = 0  # see docstring: a raised-through generator is dead

        def produce() -> Optional[Batch]:
            try:
                return next(it)
            except StopIteration:
                return None
    else:
        def produce() -> Optional[Batch]:
            return next_batch()

    def produce_with_retry() -> Optional[Batch]:
        delay = retry_backoff_s
        for attempt in range(retries + 1):
            try:
                return produce()
            except TRANSIENT_ERRORS as e:
                if attempt >= retries or closed.is_set():
                    raise
                if telemetry is not None:
                    telemetry.inc("loader_retries")
                log.warning(
                    "transient batch-read error (%s); retry %d/%d in %.2fs",
                    e, attempt + 1, retries, delay)
                time.sleep(delay)
                delay *= 2
        return None  # unreachable; keeps type checkers honest

    def _put(item) -> bool:
        while not closed.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def work():
        try:
            while not closed.is_set():
                if tracer is None:
                    b = produce_with_retry()
                else:
                    with tracer.span("prefetch_assemble"):
                        b = produce_with_retry()
                if b is None:  # finite source exhausted
                    break
                if feat_dtype is not None:
                    b = Batch(
                        feats=[np.asarray(f).astype(feat_dtype) for f in b.feats],
                        labels=b.labels, weights=b.weights,
                        video_ids=b.video_ids, gts=b.gts, video_ix=b.video_ix,
                    )
                if device_put is not None:
                    put_span = (NULL_SPAN if tracer is None
                                else tracer.span("prefetch_device_put"))
                    with put_span:
                        b = Batch(
                            feats=[device_put(f) for f in b.feats],
                            labels=device_put(b.labels),
                            weights=device_put(b.weights),
                            video_ids=b.video_ids,
                            gts=b.gts,
                            video_ix=b.video_ix,
                        )
                if not _put(b):
                    return
        except Exception as e:  # propagate into the consumer thread
            _put(e)
        _put(stop)

    # Named so trace viewers (SpanTracer tid rows) and locksan receipts
    # can attribute this worker's spans (cstlint:thread-discipline).
    t = threading.Thread(target=work, name="loader-prefetch", daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is stop:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        # Consumers of the infinite stream exit via break/GeneratorExit:
        # wake the worker, drain whatever it already queued, and reap the
        # thread so neither it nor its prefetched buffers leak.  The reap
        # is deadline-bounded — a worker wedged inside a dead-transport
        # read must not transfer its hang to the consumer (it is a daemon
        # thread; the deadline only abandons the join, not the wake-up).
        closed.set()
        deadline = time.monotonic() + 5.0
        while True:
            try:
                q.get_nowait()
                continue  # drained one item; worker may be mid-_put
            except queue.Empty:
                pass
            if not t.is_alive() or time.monotonic() > deadline:
                break
            t.join(timeout=0.2)
