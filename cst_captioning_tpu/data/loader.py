"""Batch assembly + host->device streaming — the reference DataLoader, TPU-shaped.

Responsibilities (SURVEY.md §3.5, restated for XLA):

- fixed-shape batches: ``batch_size`` videos × ``seq_per_img`` captions,
  labels always (B*seq_per_img, L) — static shapes so every jit traces once;
- shuffled epoch order with wrap-around (partial final batches are filled
  from the next epoch, matching the reference's infinite get_batch stream);
- per-caption consensus weights for WXE (from the consensus pickle);
- raw ground-truth strings carried alongside for the RL reward path;
- multi-host sharding: each JAX process sees a disjoint stride of the
  video list (``process_index``/``process_count``), the TPU-native
  replacement for the reference's single-node DataParallel split — or,
  with an explicit :class:`~.sharding.ShardSpec`, a strided slice of a
  deterministic GLOBAL epoch shuffle (``data/sharding.py``) whose N
  shards partition every epoch exactly;
- ``prefetch_to_device``: background prefetch pipelining h5 reads +
  ``jax.device_put`` of batch t+1 under the step computation of batch t —
  one thread by default, or ``workers=N`` assembler threads feeding a
  bounded ORDERED reassembly queue (batch order bit-identical to the
  single-thread stream; the multi-worker data plane).

Threading model (enforced by cstlint-threads + the runtime lock
sanitizer): plan drawing — ALL of the loader's RNG consumption — is
sequential under ``data.loader.plan``; the reassembly buffer is guarded
by ``data.loader.queue``; the two are never nested with each other or
with the telemetry registry (metrics calls happen outside both locks).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..resilience.faults import FaultPlan, InjectedFault
from ..telemetry.spans import NULL_SPAN
from ..utils.locksan import declare_order, named_lock
from .dataset import CaptionDataset
from .sharding import ShardSpec, shard_epoch_order

log = logging.getLogger("cst_captioning_tpu.loader")

#: Declared for the static lock-order rule AND the runtime sanitizer
#: (analysis/concurrency.py grammar).  The two locks are deliberately
#: never nested — a worker draws under the plan lock, releases, then
#: deposits under the queue lock — but declaring the order makes any
#: future nesting checkable instead of silently deadlock-prone.
LOCK_ORDER = ("data.loader.plan", "data.loader.queue")
declare_order(*LOCK_ORDER)

#: Error classes the prefetch worker treats as TRANSIENT (retry with
#: backoff before poisoning the stream): h5py surfaces flaky NFS/FUSE
#: reads as OSError/IOError, and the chaos harness injects the same shape.
TRANSIENT_ERRORS = (OSError,)


@dataclass
class Batch:
    """One training/eval batch. Feature arrays are (B, T_m, D_m); labels and
    weights are flattened over (video, caption) -> (B*seq_per_img, ...)."""

    feats: List[np.ndarray]
    labels: np.ndarray                 # (B*S, L) int32, 0-padded
    weights: np.ndarray                # (B*S,) float32 consensus weights (1.0 = XE)
    video_ids: List[str]               # length B
    gts: Dict[str, List[str]] = field(default_factory=dict)  # refs for reward
    video_ix: Optional[np.ndarray] = None  # (B,) dataset indices

    @property
    def batch_videos(self) -> int:
        return len(self.video_ids)


@dataclass
class BatchPlan:
    """The RNG-determined HALF of a batch: everything ``next_batch``
    decides (which videos, which caption rows, labels/weights packed)
    EXCEPT the feature read.  Drawing a plan consumes RNG and must stay
    sequential; assembling it (``CaptionLoader.assemble``) is pure IO +
    packing and may run on any worker thread — and may be RETRIED
    bit-identically, because re-assembling the same plan redraws
    nothing."""

    seq: int                           # batch ordinal in the stream
    ix: np.ndarray                     # (B,) dataset indices
    labels: np.ndarray                 # (B*S, L) int32
    weights: np.ndarray                # (B*S,) float32
    video_ids: List[str]
    gts: Dict[str, List[str]] = field(default_factory=dict)


class CaptionLoader:
    """Infinite shuffled batch stream over a CaptionDataset split."""

    def __init__(
        self,
        dataset: CaptionDataset,
        batch_size: int,
        seq_per_img: int = 20,
        shuffle: bool = True,
        seed: int = 0,
        consensus_weights: Optional[Dict[str, np.ndarray]] = None,
        process_index: int = 0,
        process_count: int = 1,
        include_gts: bool = False,
        include_feats: bool = True,
        fault_plan: Optional[FaultPlan] = None,
        shard_spec: Optional[ShardSpec] = None,
    ):
        self.ds = dataset
        # Chaos hook (resilience/faults.py): ``loader_err@batch=N`` raises
        # a transient error from batch N's feature read.  None = disarmed,
        # one host-side None-check per batch.
        self._faults = fault_plan
        self._batches_served = 0
        self.batch_size = batch_size
        self.seq_per_img = seq_per_img
        self.shuffle = shuffle
        # Caption-draw RNG stream, per shard: the GLOBAL epoch shuffle
        # (sharding.py) never draws from it, so its replay discipline
        # (skip_batches) is shard-count-independent.
        self._shard = shard_spec
        self._shard_seed = seed
        self._epochs_drawn = 0  # epoch ordinal fed to the global shuffle
        salt = shard_spec.shard_id if shard_spec is not None else process_index
        self._rng = np.random.default_rng(seed + salt)
        self.consensus_weights = consensus_weights
        self.include_gts = include_gts
        # include_feats=False skips the per-batch h5 feature reads entirely —
        # the --device_feats path keeps all features resident in HBM and
        # gathers them by Batch.video_ix inside the train step.
        self.include_feats = include_feats
        self._refs = dataset.references() if include_gts else None

        # Multi-host shard: strided so every process gets an equal slice
        # regardless of dataset ordering.  The stride is PUBLIC contract:
        # evaluation.gather_strided_predictions reconstructs every other
        # host's shard from (process_index, process_count, num_videos).
        self.process_index = process_index
        self.process_count = process_count
        if shard_spec is not None and process_count != 1:
            raise ValueError(
                "pick ONE sharding scheme: an explicit ShardSpec "
                "(--data_shards) replaces the per-process strided split, "
                f"got shard_spec={shard_spec} AND process_count="
                f"{process_count}")
        if shard_spec is not None:
            # Same cardinality as the global-permutation slice (both are
            # positions shard_id::num_shards), so batches_per_epoch and
            # iter_eval keep their meaning; the TRAINING order itself
            # comes from shard_epoch_order at each epoch refill.
            self._my_videos = np.arange(dataset.num_videos)[
                shard_spec.shard_id::shard_spec.num_shards]
        else:
            self._my_videos = np.arange(dataset.num_videos)[
                process_index::process_count]
        if len(self._my_videos) == 0:
            raise ValueError("shard is empty; dataset smaller than shard count")
        self._order = self._my_videos.copy()
        self._pos = len(self._order)  # force shuffle on first batch
        self.epoch = 0

    # -- epoch bookkeeping -------------------------------------------------

    def _next_indices(self, n: int) -> np.ndarray:
        out = []
        while n > 0:
            if self._pos >= len(self._order):
                if self._shard is not None:
                    # Global-shuffle sharding: this shard's slice of the
                    # epoch's ONE global permutation — a pure function of
                    # (seed, epoch), consuming no caption-RNG draws
                    # (sharding.py; RESILIENCE.md "Sharded resume").
                    self._order = shard_epoch_order(
                        self.ds.num_videos, self._shard_seed,
                        self._epochs_drawn, self._shard,
                        shuffle=self.shuffle)
                    self._epochs_drawn += 1
                elif self.shuffle:
                    self._rng.shuffle(self._order)
                self._pos = 0
                self.epoch += 1
            take = min(n, len(self._order) - self._pos)
            out.append(self._order[self._pos : self._pos + take])
            self._pos += take
            n -= take
        return np.concatenate(out)

    @property
    def batches_per_epoch(self) -> int:
        return max(1, len(self._my_videos) // self.batch_size)

    # -- batch assembly ----------------------------------------------------

    def _select_caption_rows(self, video_ix: int, n: int) -> np.ndarray:
        """The ONE place caption-row selection consumes RNG draws: used by
        ``next_batch`` (via ``_pick_captions``) and replayed draw-for-draw
        by ``skip_batches`` so a fast-forwarded stream stays bit-identical
        to one that actually served the skipped batches."""
        if n == 0:
            raise ValueError(
                f"video {self.ds.video_ids[video_ix]!r} has no captions"
            )
        if n >= self.seq_per_img:
            sel = self._rng.choice(n, self.seq_per_img, replace=False) if self.shuffle \
                else np.arange(self.seq_per_img)
        else:
            sel = self._rng.choice(n, self.seq_per_img, replace=True)
        return np.sort(sel)

    def _pick_captions(self, video_ix: int) -> Tuple[np.ndarray, np.ndarray]:
        """-> ((seq_per_img, L) caption rows, their indices within the video's
        caption list); samples with replacement if the video has fewer."""
        caps = self.ds.captions_for(video_ix)
        sel = self._select_caption_rows(video_ix, caps.shape[0])
        return caps[sel], sel

    def skip_batches(self, n: int) -> None:
        """Fast-forward the stream by ``n`` batches WITHOUT assembling them:
        replays exactly the RNG draws ``next_batch`` would have made (epoch
        shuffles + per-video caption selections) at index-bookkeeping cost
        — no h5 feature/label reads.

        This is the data half of deterministic resume: a run restored at
        step N calls ``skip_batches(N)`` so it consumes the SAME batch
        sequence from step N onward that an uninterrupted run of the same
        seed would have — without it, a resumed run replays the stream
        from batch 0 and its post-resume params diverge from the
        uninterrupted twin's."""
        if n <= 0:
            return
        log.info("fast-forwarding the batch stream by %d batch(es) "
                 "(deterministic resume alignment)", n)
        # cstlint: disable=device-scalar-fetch -- host int argument, never a device array
        for _ in range(int(n)):
            for v in self._next_indices(self.batch_size):
                # cstlint: disable=device-scalar-fetch -- host numpy index rows from _next_indices, never device arrays
                self._select_caption_rows(int(v), self.ds.num_captions(int(v)))
            self._batches_served += 1

    def next_plan(self) -> BatchPlan:
        """Draw the next batch's PLAN: video indices, caption rows,
        packed labels/weights — ALL of the stream's RNG consumption, and
        none of its feature IO.  Sequential by contract: the multi-worker
        prefetcher serializes calls under ``data.loader.plan`` so the
        plan sequence is identical to the single-thread stream's."""
        ix = self._next_indices(self.batch_size)
        labels = np.zeros((self.batch_size * self.seq_per_img, self.ds.seq_length),
                          dtype=np.int32)
        weights = np.ones(self.batch_size * self.seq_per_img, dtype=np.float32)
        vids = []
        for b, v in enumerate(ix):
            # cstlint: disable=device-scalar-fetch -- host numpy index row from _next_indices, never a device array
            rows, sel = self._pick_captions(int(v))
            labels[b * self.seq_per_img : (b + 1) * self.seq_per_img] = rows
            # cstlint: disable=device-scalar-fetch -- host numpy index row from _next_indices, never a device array
            vid = self.ds.video_ids[int(v)]
            vids.append(vid)
            if self.consensus_weights is not None and vid in self.consensus_weights:
                # cstlint: disable=device-scalar-fetch -- consensus weights are a host pickle's numpy arrays, never device
                w = np.asarray(self.consensus_weights[vid], dtype=np.float32)
                weights[b * self.seq_per_img : (b + 1) * self.seq_per_img] = w[sel]
        gts = {}
        if self.include_gts and self._refs is not None:
            gts = {vid: self._refs[vid] for vid in vids if vid in self._refs}
        seq = self._batches_served
        self._batches_served += 1
        return BatchPlan(seq=seq, ix=ix, labels=labels, weights=weights,
                         video_ids=vids, gts=gts)

    def assemble(self, plan: BatchPlan) -> Batch:
        """Plan -> Batch: the feature read (the expensive, parallel-safe
        half).  No RNG — a transient failure here is retried by
        re-assembling the SAME plan, which is bit-identical by
        construction.  The ``loader_err`` chaos hook fires here (keyed on
        the plan's batch ordinal) so multi-worker drills inject the fault
        inside a worker thread, where production failures happen."""
        if (self._faults is not None
                and self._faults.fire("loader_err", plan.seq)):
            raise InjectedFault(
                f"injected transient feature-read error at batch {plan.seq}")
        feats = self.ds.features(plan.ix) if self.include_feats else []
        return Batch(feats=feats, labels=plan.labels, weights=plan.weights,
                     video_ids=plan.video_ids, gts=plan.gts,
                     video_ix=plan.ix)

    def next_batch(self) -> Batch:
        # Fault check BEFORE the plan draw (the historical single-thread
        # semantics): a retried next_batch() call then draws the same
        # plan the fault preempted, keeping the stream identical to the
        # fault-free run.  fire() is single-shot per index, so assemble's
        # own check cannot double-fire.
        if (self._faults is not None
                and self._faults.fire("loader_err", self._batches_served)):
            raise InjectedFault(
                f"injected transient feature-read error at batch "
                f"{self._batches_served}")
        return self.assemble(self.next_plan())

    def __iter__(self) -> Iterator[Batch]:
        while True:
            yield self.next_batch()

    # -- eval iteration (single pass, in order) ----------------------------

    def iter_eval(self) -> Iterator[Batch]:
        """One ordered, non-shuffled pass; final batch wraps (callers dedupe
        by video id).  Keeps shapes static for the jitted decode."""
        n = len(self._my_videos)
        for start in range(0, n, self.batch_size):
            ix = self._my_videos[start : start + self.batch_size]
            if len(ix) < self.batch_size:  # pad by cycling to keep shape static
                pad = np.resize(self._my_videos, self.batch_size - len(ix))
                ix = np.concatenate([ix, pad])
            feats = self.ds.features(ix)
            # cstlint: disable=device-scalar-fetch -- host numpy index rows (eval iteration), never device arrays
            vids = [self.ds.video_ids[int(v)] for v in ix]
            yield Batch(
                feats=feats,
                labels=np.zeros((self.batch_size * self.seq_per_img,
                                 self.ds.seq_length), dtype=np.int32),
                weights=np.ones(self.batch_size * self.seq_per_img, dtype=np.float32),
                video_ids=vids,
                video_ix=ix,
            )


def _cast_feats(b: Batch, feat_dtype) -> Batch:
    """Host-side feature cast before the wire (``--bf16_feats``): feats
    only — labels/weights keep their exact dtypes.  Shared by the
    single-thread and multi-worker prefetch paths so the Batch
    reconstruction cannot drift between them."""
    return Batch(feats=[np.asarray(f).astype(feat_dtype) for f in b.feats],
                 labels=b.labels, weights=b.weights,
                 video_ids=b.video_ids, gts=b.gts, video_ix=b.video_ix)


def _device_put_batch(b: Batch, device_put) -> Batch:
    """Apply ``device_put`` to every array field (feats/labels/weights);
    host-only fields ride along untouched."""
    return Batch(feats=[device_put(f) for f in b.feats],
                 labels=device_put(b.labels),
                 weights=device_put(b.weights),
                 video_ids=b.video_ids, gts=b.gts, video_ix=b.video_ix)


class _OrderedPrefetcher:
    """``workers=N`` assembler threads feeding a bounded ORDERED
    reassembly queue — the multi-worker data plane behind
    :func:`prefetch_to_device`.

    Contract: the emitted stream is BIT-IDENTICAL to the single-thread
    stream, batch for batch (test-pinned).  How: plan drawing — all RNG —
    stays sequential under ``data.loader.plan`` (workers take turns);
    assembly (feature IO + packing + optional host cast + device_put)
    runs in parallel; deposits land in a seq-keyed buffer guarded by
    ``data.loader.queue`` and the consumer emits strictly in seq order.
    A transient assembly error is retried by re-assembling the SAME plan
    (no RNG redraw), so a retry can neither reorder nor alter the stream.

    Backpressure: a counting-semaphore ticket pool bounds in-flight
    batches (drawn-but-not-consumed) to ``size``, so N workers cannot
    race ahead of a slow consumer and balloon host/HBM memory.

    Lifecycle: threads are named ``loader-prefetch-<i>`` (trace rows,
    locksan receipts) and daemonized; abandoning the stream joins ALL of
    them deadline-bounded — no stray ``loader-prefetch-*`` thread (or
    prefetched buffer it holds) outlives the consumer (test-pinned,
    sanitizer-armed).
    """

    def __init__(self, loader: "CaptionLoader", workers: int, size: int,
                 device_put, feat_dtype, retries: int,
                 retry_backoff_s: float, telemetry):
        self._loader = loader
        self._workers = int(workers)
        self._capacity = max(int(size), 1)
        self._device_put = device_put
        self._feat_dtype = feat_dtype
        self._retries = int(retries)
        self._backoff = float(retry_backoff_s)
        self._telemetry = telemetry
        self._tracer = telemetry.tracer if telemetry is not None else None
        self._plan_lock = named_lock("data.loader.plan")
        self._qlock = named_lock("data.loader.queue")
        self._next_draw = 0      # cstlint: guarded_by=self._plan_lock
        self._poisoned = False   # cstlint: guarded_by=self._plan_lock
        self._buffer = {}        # cstlint: guarded_by=self._qlock
        self._next_emit = 0      # cstlint: guarded_by=self._qlock
        self._avail = threading.Event()   # deposit signal (lock-free wake)
        self._closed = threading.Event()  # consumer gone: workers drain out
        self._tickets = threading.Semaphore(self._capacity)
        self._threads: List[threading.Thread] = []  # cstlint: owned_by=consumer
        if telemetry is not None:
            # Declared at 0 (cstlint:declared-counters): a snapshot showing
            # 0 means the retry path was armed and unused — per worker, so
            # a drill can assert WHICH worker absorbed the fault.
            telemetry.declare("loader_retries",
                              *(f"loader_retries_worker{i}"
                                for i in range(self._workers)))
            telemetry.registry.set_gauge("loader_queue_depth", 0)
            telemetry.registry.set_gauge("loader_queue_capacity",
                                         self._capacity)

    # -- worker side ---------------------------------------------------------

    def start(self) -> "_OrderedPrefetcher":
        for i in range(self._workers):
            t = threading.Thread(target=self._work, args=(i,),
                                 name=f"loader-prefetch-{i}", daemon=True)
            self._threads.append(t)
            t.start()
        return self

    def _assemble_with_retry(self, plan: BatchPlan, wix: int) -> Batch:
        delay = self._backoff
        for attempt in range(self._retries + 1):
            try:
                return self._loader.assemble(plan)
            except TRANSIENT_ERRORS as e:
                if attempt >= self._retries or self._closed.is_set():
                    raise
                if self._telemetry is not None:
                    self._telemetry.inc("loader_retries")
                    self._telemetry.inc(f"loader_retries_worker{wix}")
                log.warning(
                    "transient batch-read error in loader-prefetch-%d "
                    "(%s); retry %d/%d of batch %d in %.2fs", wix, e,
                    attempt + 1, self._retries, plan.seq, delay)
                time.sleep(delay)
                delay *= 2
        raise AssertionError("unreachable")  # loop always returns or raises

    def _finish(self, plan: BatchPlan, wix: int) -> Batch:
        """Assemble + host cast + device transfer, span-traced on this
        worker's own trace row (overlap becomes visible in Perfetto)."""
        if self._tracer is None:
            b = self._assemble_with_retry(plan, wix)
        else:
            with self._tracer.span("prefetch_assemble", batch=plan.seq):
                b = self._assemble_with_retry(plan, wix)
        if self._feat_dtype is not None:
            b = _cast_feats(b, self._feat_dtype)
        if self._device_put is not None:
            put_span = (NULL_SPAN if self._tracer is None
                        else self._tracer.span("prefetch_device_put",
                                               batch=plan.seq))
            with put_span:
                b = _device_put_batch(b, self._device_put)
        return b

    def _work(self, wix: int) -> None:
        while not self._closed.is_set():
            if not self._tickets.acquire(timeout=0.1):
                continue
            draw_error = None
            with self._plan_lock:
                if self._poisoned or self._closed.is_set():
                    self._tickets.release()
                    return
                seq = self._next_draw
                self._next_draw += 1
                try:
                    plan = self._loader.next_plan()
                except BaseException as e:
                    # A failed DRAW may have part-consumed RNG: the
                    # stream past this point is unknowable.  Poison so no
                    # worker draws again; the consumer raises at seq.
                    self._poisoned = True
                    draw_error = e
            if draw_error is not None:
                # Deposited OUTSIDE the plan lock: the module contract is
                # that the two loader locks (and the registry's) never
                # nest, on every path including this one.
                self._deposit(seq, draw_error)
                return
            try:
                item: object = self._finish(plan, wix)
            except BaseException as e:
                with self._plan_lock:
                    self._poisoned = True
                item = e
            self._deposit(seq, item)

    def _deposit(self, seq: int, item) -> None:
        with self._qlock:
            self._buffer[seq] = item
            depth = len(self._buffer)
        self._avail.set()
        if self._telemetry is not None:  # outside both locks (LOCK_ORDER)
            self._telemetry.registry.set_gauge("loader_queue_depth", depth)

    # -- consumer side -------------------------------------------------------

    def batches(self) -> Iterator[Batch]:
        try:
            while True:
                self._avail.clear()
                with self._qlock:
                    item = self._buffer.pop(self._next_emit, self)
                    if item is not self:
                        self._next_emit += 1
                    depth = len(self._buffer)
                if item is self:  # next-in-order batch not deposited yet
                    self._avail.wait(timeout=0.05)
                    continue
                if self._telemetry is not None:
                    self._telemetry.registry.set_gauge(
                        "loader_queue_depth", depth)
                if isinstance(item, BaseException):
                    raise item
                self._tickets.release()
                yield item
        finally:
            self.close()

    def close(self) -> None:
        """Reap every worker: wake them, join deadline-bounded (daemon
        threads — the deadline abandons the join, never the wake-up)."""
        self._closed.set()
        self._avail.set()
        deadline = time.monotonic() + 5.0
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))


def prefetch_to_device(batches: Union[CaptionLoader, Iterator[Batch]],
                       size: int = 2, device_put=None, feat_dtype=None,
                       retries: int = 3,
                       retry_backoff_s: float = 0.05,
                       telemetry=None, workers: int = 1) -> Iterator[Batch]:
    """Run batch assembly (h5 reads, numpy packing) in a background thread,
    optionally applying ``device_put`` (e.g. a sharding-aware jax.device_put)
    to feats/labels/weights before handing the batch to the consumer.

    This is the TPU replacement for the reference's synchronous get_batch ->
    .cuda() at the call site: HBM transfer of batch t+1 overlaps step t.

    ``feat_dtype`` (e.g. ``ml_dtypes.bfloat16``) casts feature arrays on the
    HOST before the transfer, halving host->device bytes for bf16 compute —
    the features are cast to the model dtype on device anyway, so when the
    model runs bf16 this only moves the (value-preserving) cast before the
    wire.  Labels/weights are untouched.

    Transient-error policy: when ``batches`` is a loader (anything with a
    ``next_batch`` method, so the producing call can be re-issued), a
    ``TRANSIENT_ERRORS`` failure during batch assembly is retried up to
    ``retries`` times with exponential backoff before the poison-pill
    exception propagates — a single flaky NFS read must not kill a
    multi-hour run.  A retried batch redraws from the (infinite,
    wrap-around) stream, which only reorders coverage within the epoch.
    Plain iterators keep the old fail-fast contract: a generator is dead
    after it raises, so retrying it would silently end the stream instead
    of surfacing the error.

    Worker lifetime: abandoning the iterator (break / GeneratorExit) wakes
    the worker via the ``closed`` event and JOINS it, so no thread — and no
    prefetched HBM buffer it holds — outlives the consumer.

    ``telemetry`` (a ``telemetry.Telemetry``, optional): retry attempts
    count into the ``loader_retries`` counter (plus per-worker
    ``loader_retries_worker<i>`` under ``workers > 1``), the
    ``loader_queue_depth``/``loader_queue_capacity`` gauges expose the
    prefetch queue's occupancy between steps (they ride into
    heartbeat.json via the registry payload), and when span tracing is
    armed each worker records ``prefetch_assemble`` (h5 reads + numpy
    packing) and ``prefetch_device_put`` spans on its own trace row — the
    overlap of batch t+1's IO under step t's compute becomes visible in
    the Chrome trace.  None = one is-None check per batch.

    ``workers`` (default 1): ``N > 1`` runs N assembler threads through a
    bounded ORDERED reassembly queue (:class:`_OrderedPrefetcher`) — the
    emitted stream is bit-identical to the single-thread stream, the
    contract the multi-worker data plane is pinned to.  Requires a
    loader-shaped source (``next_plan``/``assemble``); a plain iterator
    cannot be drawn ahead safely, so it falls back to the single-thread
    path with a log line.  Parallelism pays when the source reads
    concurrently (preloaded/in-memory features, thread-safe stores);
    plain h5py serializes reads under its own global lock, leaving only
    the packing/cast/transfer work to overlap.
    """
    if workers > 1:
        if hasattr(batches, "next_plan"):
            pf = _OrderedPrefetcher(
                batches, workers=workers, size=size, device_put=device_put,
                feat_dtype=feat_dtype, retries=retries,
                retry_backoff_s=retry_backoff_s, telemetry=telemetry,
            ).start()
            yield from pf.batches()
            return
        log.warning("prefetch workers=%d needs a loader-shaped source "
                    "(next_plan/assemble); plain iterator falls back to "
                    "the single-thread prefetch path", workers)
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = object()
    closed = threading.Event()  # consumer gone: worker must drop its buffers
    tracer = telemetry.tracer if telemetry is not None else None
    if telemetry is not None:
        # Declared at 0 at prefetch start (cstlint:declared-counters):
        # 0 in the snapshot means the retry path was armed and unused.
        telemetry.declare("loader_retries")
        telemetry.registry.set_gauge("loader_queue_depth", 0)
        telemetry.registry.set_gauge("loader_queue_capacity", max(size, 1))

    next_batch = getattr(batches, "next_batch", None)
    if next_batch is None:
        it = iter(batches)
        retries = 0  # see docstring: a raised-through generator is dead

        def produce() -> Optional[Batch]:
            try:
                return next(it)
            except StopIteration:
                return None
    else:
        def produce() -> Optional[Batch]:
            return next_batch()

    def produce_with_retry() -> Optional[Batch]:
        delay = retry_backoff_s
        for attempt in range(retries + 1):
            try:
                return produce()
            except TRANSIENT_ERRORS as e:
                if attempt >= retries or closed.is_set():
                    raise
                if telemetry is not None:
                    telemetry.inc("loader_retries")
                log.warning(
                    "transient batch-read error (%s); retry %d/%d in %.2fs",
                    e, attempt + 1, retries, delay)
                time.sleep(delay)
                delay *= 2
        return None  # unreachable; keeps type checkers honest

    def _put(item) -> bool:
        while not closed.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def work():
        try:
            while not closed.is_set():
                if tracer is None:
                    b = produce_with_retry()
                else:
                    with tracer.span("prefetch_assemble"):
                        b = produce_with_retry()
                if b is None:  # finite source exhausted
                    break
                if feat_dtype is not None:
                    b = _cast_feats(b, feat_dtype)
                if device_put is not None:
                    put_span = (NULL_SPAN if tracer is None
                                else tracer.span("prefetch_device_put"))
                    with put_span:
                        b = _device_put_batch(b, device_put)
                if not _put(b):
                    return
        except Exception as e:  # propagate into the consumer thread
            _put(e)
        _put(stop)

    # Named so trace viewers (SpanTracer tid rows) and locksan receipts
    # can attribute this worker's spans (cstlint:thread-discipline).
    t = threading.Thread(target=work, name="loader-prefetch", daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if telemetry is not None:
                telemetry.registry.set_gauge("loader_queue_depth", q.qsize())
            if item is stop:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        # Consumers of the infinite stream exit via break/GeneratorExit:
        # wake the worker, drain whatever it already queued, and reap the
        # thread so neither it nor its prefetched buffers leak.  The reap
        # is deadline-bounded — a worker wedged inside a dead-transport
        # read must not transfer its hang to the consumer (it is a daemon
        # thread; the deadline only abandons the join, not the wake-up).
        closed.set()
        deadline = time.monotonic() + 5.0
        while True:
            try:
                q.get_nowait()
                continue  # drained one item; worker may be mid-_put
            except queue.Empty:
                pass
            if not t.is_alive() or time.monotonic() > deadline:
                break
            t.join(timeout=0.2)
