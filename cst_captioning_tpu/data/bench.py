"""Loader-only feed-rate probe — ``bench.py --stage data``.

Measures what the INPUT path can sustain, with the compute path removed:
batches/s and captions/s out of ``prefetch_to_device`` (the exact
prefetcher the trainer drives), the prefetch queue's occupancy, and the
``data_wait_ms`` share a consumer would see at a simulated step rate —
the receipt that the data plane can keep a chip fed at the recorded
30k caps/s XE rate (``XE_CHIP_CAPS_PER_SEC``) once the chip window
reopens.

Honesty (PARITY.md "Data-plane feed rate"): the probe's source is an
IN-MEMORY synthetic dataset with an explicit simulated per-read latency
(``read_ms``, modeling h5/NFS-shaped IO, which releases the GIL exactly
like a real blocking read).  Real h5py sources serialize reads under
h5py's global lock, so multi-worker gains there come from the packing/
cast/transfer overlap only — the probe's speedup is scoped to its own
shapes and source, never claimed for arbitrary stores.

No jax import at module level: the probe is pure host work and must be
importable before bench.py's backend probe decides where to run.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .loader import CaptionLoader, prefetch_to_device
from .sharding import ShardSpec

#: Peak recorded on-chip XE throughput (captions/s/chip, BENCH_r04 —
#: PARITY.md).  The probe's ``vs_xe_rate`` and default consumer pacing
#: derive from it: a feed rate >= 1.0x means the loader can keep one
#: chip fed at the fastest rate the compute path has ever demanded.
XE_CHIP_CAPS_PER_SEC = 30447.0


class SyntheticFeedDataset:
    """In-memory CaptionDataset twin for the feed probe: same duck-typed
    surface the loader consumes (``features``/``captions_for``/
    ``num_captions``/``video_ids``/``seq_length``/``num_videos``), backed
    by numpy arrays plus an explicit simulated per-read latency.

    ``read_ms`` sleeps once per ``features()`` call — the blocking-IO
    shape of an h5/NFS read (sleep releases the GIL, as those reads do),
    so worker-count scaling measured against it is the IO-overlap story,
    stated rather than smuggled."""

    def __init__(self, num_videos: int, seq_len: int = 30,
                 captions_per_video: int = 20, vocab: int = 8000,
                 feat_shapes: Sequence[Tuple[int, int]] = ((28, 2048),
                                                          (1, 4096)),
                 read_ms: float = 0.0, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.video_ids: List[str] = [f"v{i}" for i in range(num_videos)]
        self._feats = [
            rng.standard_normal((num_videos, t, d)).astype(np.float32)
            for t, d in feat_shapes
        ]
        self._labels = rng.integers(
            1, vocab, (num_videos * captions_per_video, seq_len)
        ).astype(np.int32)
        self._cpv = captions_per_video
        self._seq_len = seq_len
        self._read_ms = float(read_ms)

    @property
    def num_videos(self) -> int:
        return len(self.video_ids)

    @property
    def seq_length(self) -> int:
        return self._seq_len

    def features(self, video_ix: np.ndarray) -> List[np.ndarray]:
        if self._read_ms > 0:
            time.sleep(self._read_ms / 1000.0)
        ix = np.asarray(video_ix)
        # .copy() keeps the per-batch allocation+memcpy a real h5 read
        # pays (a zero-copy fancy-index view would flatter the number).
        return [f[ix].copy() for f in self._feats]

    def captions_for(self, video_ix: int) -> np.ndarray:
        s = int(video_ix) * self._cpv
        return self._labels[s:s + self._cpv]

    def num_captions(self, video_ix: int) -> int:
        return self._cpv


def feed_probe(batch_size: int = 32, seq_per_img: int = 20,
               seq_len: int = 30, vocab: int = 8000,
               num_videos: int = 64, workers: int = 1,
               data_shards: int = 0, data_shard_id: int = 0,
               read_ms: float = 10.0, consumer_ms: Optional[float] = None,
               batches: int = 48, prefetch_size: int = 4,
               warmup: int = 4, seed: int = 0,
               feat_shapes: Sequence[Tuple[int, int]] = ((28, 2048),
                                                        (1, 4096)),
               dataset=None) -> Dict:
    """One feed-rate measurement at one worker/shard configuration.

    Two phases over the SAME prefetcher configuration:

    1. **Unconstrained drain** — the consumer takes ``batches`` batches
       as fast as they arrive: the feed rate (batches/s, captions/s).
    2. **Paced consumer** — the consumer sleeps ``consumer_ms`` per batch
       (default: the per-batch step time of a chip running XE at
       ``XE_CHIP_CAPS_PER_SEC``) and measures how long each ``next()``
       blocked: the ``data_wait_ms`` share at the simulated step rate,
       plus the mean queue depth seen at each arrival.

    Returns the probe record (one dict, JSON-ready)."""
    from ..telemetry import Telemetry

    if dataset is None:
        dataset = SyntheticFeedDataset(
            num_videos, seq_len=seq_len, captions_per_video=seq_per_img,
            vocab=vocab, feat_shapes=feat_shapes, read_ms=read_ms,
            seed=seed)
    spec = (ShardSpec(int(data_shards), int(data_shard_id))
            if data_shards else None)
    loader = CaptionLoader(dataset, batch_size=batch_size,
                           seq_per_img=seq_per_img, shuffle=True,
                           seed=seed, shard_spec=spec)
    caps_per_batch = batch_size * seq_per_img
    if consumer_ms is None:
        consumer_ms = caps_per_batch / XE_CHIP_CAPS_PER_SEC * 1000.0
    telemetry = Telemetry()
    it = iter(prefetch_to_device(loader, size=prefetch_size,
                                 workers=workers, telemetry=telemetry))
    reg = telemetry.registry
    try:
        for _ in range(max(int(warmup), 1)):  # warm threads + allocator
            next(it)
        t0 = time.perf_counter()
        for _ in range(int(batches)):
            next(it)
        drain_s = time.perf_counter() - t0
        # Phase 2: paced consumer.
        waits = []
        depths = []
        paced_t0 = time.perf_counter()
        for _ in range(int(batches)):
            w0 = time.perf_counter()
            next(it)
            waits.append((time.perf_counter() - w0) * 1000.0)
            depths.append(reg.snapshot()["gauges"].get(
                "loader_queue_depth", 0))
            time.sleep(consumer_ms / 1000.0)
        paced_s = time.perf_counter() - paced_t0
    finally:
        it.close()
        telemetry.close()
    batches_per_sec = batches / drain_s
    caps_per_sec = batches_per_sec * caps_per_batch
    wait_ms_total = float(np.sum(waits))
    return {
        "batches_per_sec": round(batches_per_sec, 2),
        "captions_per_sec": round(caps_per_sec, 1),
        "vs_xe_rate": round(caps_per_sec / XE_CHIP_CAPS_PER_SEC, 3),
        "consumer_ms": round(float(consumer_ms), 3),
        "data_wait_ms_mean": round(float(np.mean(waits)), 3),
        "data_wait_ms_p99": round(float(np.percentile(waits, 99)), 3),
        "data_wait_share": round(wait_ms_total / (paced_s * 1000.0), 4),
        "queue_depth_mean": round(float(np.mean(depths)), 2),
        "queue_capacity": prefetch_size,
        "loader_workers": int(workers),
        "data_shards": int(data_shards),
        "data_shard_id": int(data_shard_id),
        "read_ms": float(read_ms),
        "batches": int(batches),
        "num_videos": int(num_videos),
        "retries": reg.counter("loader_retries"),
    }
