"""Synthetic tiny-dataset generator — the test/bench fixture factory.

The reference had no fixtures at all (SURVEY.md §4); this generator stands in
for its MSVD/MSR-VTT downloads: it emits the exact on-disk artifact set the
real pipeline uses, with captions drawn from a tiny grammar whose content
correlates with the feature vectors — so models can genuinely overfit it
(XE loss -> ~0) and reward-driven training has signal.

All label/info/cocofmt/reward artifacts are produced by the real
``prepro.build_split`` (fixtures can never diverge from the production
schema); only the feature h5s are synthesized here.
"""

from __future__ import annotations

import json
import zlib
from dataclasses import dataclass
from typing import Dict, List, Tuple

import h5py
import numpy as np

from ..metrics import tokenize
from .prepro import build_split
from .vocab import Vocab, load_vocab

_SUBJECTS = ["a man", "a woman", "a dog", "a cat", "a child"]
_VERBS = ["is cooking", "is running", "is singing", "is playing", "is dancing"]
_OBJECTS = ["in the kitchen", "in the park", "on stage", "with a ball", "outside"]


@dataclass
class SyntheticSpec:
    num_videos: int = 8
    captions_per_video: int = 5
    max_len: int = 16
    feat_dims: Tuple[int, ...] = (32, 16)     # e.g. tiny "resnet" + "c3d"
    feat_times: Tuple[int, ...] = (4, 1)      # temporal frames per modality
    seed: int = 0


def _make_captions(rng: np.random.Generator, spec: SyntheticSpec) -> List[List[str]]:
    """Per video: one (subject, verb, object) concept + paraphrase captions."""
    all_caps = []
    for _ in range(spec.num_videos):
        s = _SUBJECTS[rng.integers(len(_SUBJECTS))]
        v = _VERBS[rng.integers(len(_VERBS))]
        o = _OBJECTS[rng.integers(len(_OBJECTS))]
        caps = []
        for j in range(spec.captions_per_video):
            drop_o = j % 3 == 2
            caps.append(f"{s} {v}" if drop_o else f"{s} {v} {o}")
        all_caps.append(caps)
    return all_caps


def generate(root: str, split: str = "train", spec: SyntheticSpec = SyntheticSpec(),
             vocab: Vocab | None = None) -> Dict[str, str]:
    """Write one split's artifact set under ``root``; returns the path map.

    Pass the train split's vocab when generating val/test so ids agree.
    """
    # crc32, not hash(): str hashing is salted per process and would make
    # regenerated splits differ between interpreter runs.
    rng = np.random.default_rng(spec.seed + zlib.crc32(split.encode()))
    captions = _make_captions(rng, spec)
    video_ids = [f"{split}_video{i}" for i in range(spec.num_videos)]

    paths = build_split(
        [{"id": v, "captions": caps} for v, caps in zip(video_ids, captions)],
        root, split, max_len=spec.max_len, vocab=vocab,
    )
    vocab = load_vocab(paths["vocab_json"])

    # Features: deterministic per-video signal derived from the first
    # caption's token ids, so features genuinely predict captions.
    feat_paths = []
    for m, (dim, t_len) in enumerate(zip(spec.feat_dims, spec.feat_times)):
        feats = np.zeros((spec.num_videos, t_len, dim), dtype=np.float32)
        for i, caps in enumerate(captions):
            concept = rng.standard_normal(dim) * 0.1
            ids = vocab.encode(tokenize(caps[0]), spec.max_len)
            for tok in ids[ids > 0]:
                concept[int(tok) % dim] += 1.0
            feats[i] = concept[None, :] + 0.01 * rng.standard_normal((t_len, dim))
        p = f"{root}/{split}_feat{m}.h5"
        with h5py.File(p, "w") as f:
            f.create_dataset("feats", data=feats if t_len > 1 else feats[:, 0, :])
        feat_paths.append(p)
    paths["feat_h5"] = json.dumps(feat_paths)
    return paths


def split_paths(paths: Dict[str, str]):
    """Convert a generate() path map into a dataset.SplitPaths."""
    from .dataset import SplitPaths

    return SplitPaths(
        feat_h5=json.loads(paths["feat_h5"]),
        label_h5=paths["label_h5"],
        info_json=paths["info_json"],
        cocofmt_json=paths["cocofmt_json"],
    )
