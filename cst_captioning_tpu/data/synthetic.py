"""Synthetic tiny-dataset generator — the test/bench fixture factory.

The reference had no fixtures at all (SURVEY.md §4); this generator stands in
for its MSVD/MSR-VTT downloads: it emits the exact on-disk artifact set the
real pipeline uses, with captions drawn from a tiny grammar whose content
correlates with the feature vectors — so models can genuinely overfit it
(XE loss -> ~0) and reward-driven training has signal.

All label/info/cocofmt/reward artifacts are produced by the real
``prepro.build_split`` (fixtures can never diverge from the production
schema); only the feature h5s are synthesized here.
"""

from __future__ import annotations

import json
import logging
import zlib
from dataclasses import dataclass
from typing import Dict, List, Tuple

import h5py
import numpy as np

from ..metrics import tokenize
from .prepro import build_split
from .vocab import Vocab, load_vocab

log = logging.getLogger(__name__)

_SUBJECTS = ["a man", "a woman", "a dog", "a cat", "a child"]
_VERBS = ["is cooking", "is running", "is singing", "is playing", "is dancing"]
_OBJECTS = ["in the kitchen", "in the park", "on stage", "with a ball", "outside"]


@dataclass
class SyntheticSpec:
    num_videos: int = 8
    captions_per_video: int = 5
    max_len: int = 16
    feat_dims: Tuple[int, ...] = (32, 16)     # e.g. tiny "resnet" + "c3d"
    feat_times: Tuple[int, ...] = (4, 1)      # temporal frames per modality
    seed: int = 0
    # > 0 switches the caption grammar to a parameterized large-vocabulary
    # pool of about this many distinct words (MSR-VTT-scale runs use 8000),
    # with per-video (adj, subject, verb, prep, object) concepts and
    # paraphrase variation — so vocab-size-realistic statistics while
    # captions stay consensus-structured (CIDEr has signal).  0 keeps the
    # original 15-word grammar (tests/fixtures).
    rich_vocab: int = 0


def _rich_pools(n_words: int):
    """Deterministic word pools summing to roughly ``n_words``."""
    n_nouns = max(n_words * 45 // 100, 4)
    n_verbs = max(n_words * 30 // 100, 2)
    n_adjs = max(n_words - n_nouns - n_verbs - 8, 2)
    nouns = [f"noun{i}" for i in range(n_nouns)]
    verbs = [f"verb{i}ing" for i in range(n_verbs)]
    adjs = [f"adj{i}" for i in range(n_adjs)]
    preps = ["in", "on", "with", "near", "under", "behind"]
    return nouns, verbs, adjs, preps


def _make_captions(rng: np.random.Generator, spec: SyntheticSpec,
                   vocab: Vocab | None = None) -> List[List[str]]:
    """Per video: one concept + paraphrase captions.

    Tiny grammar (default): (subject, verb, object) from 15 fixed words.
    Rich grammar (``rich_vocab > 0``): (adj, subj, verb, prep, obj) drawn
    from ~rich_vocab pooled words; paraphrases share the concept's content
    n-grams (high intra-video consensus, like the 20 MSR-VTT captions) but
    vary articles/adjunct inclusion so consensus training has headroom.

    ``vocab`` (val/test generation): restrict rich-grammar draws to words
    the TRAIN split realized — otherwise most val concepts would be words
    the model has never seen (mapped to <unk> at encode time), and val
    metrics would measure vocabulary luck instead of learning.  Real
    datasets' splits share a vocabulary; the synthetic one must too.
    """
    if spec.rich_vocab:
        if spec.captions_per_video < 5:
            # the 60/20/20 form mix needs >= 5 captions; fewer would emit
            # only canonical forms (no adjectives realized, no consensus
            # gap) and silently defeat both properties the grammar exists
            # to provide
            raise ValueError(
                "rich_vocab grammar needs captions_per_video >= 5, got "
                f"{spec.captions_per_video}")
        nouns, verbs, adjs, preps = _rich_pools(spec.rich_vocab)
        if vocab is not None:
            # Restrict each pool INDEPENDENTLY to train-realized words
            # (per-pool fallback to the full pool only if nothing of that
            # class was realized): an all-or-nothing filter would
            # reintroduce the val-unseen-word bug whenever one class is
            # missing.
            known = set(vocab.word_to_ix)
            def _keep(pool, min_n=1):
                kept = [w for w in pool if w in known]
                return kept if len(kept) >= min_n else pool
            nouns = _keep(nouns, min_n=2)
            verbs = _keep(verbs)
            adjs = _keep(adjs)
            preps = _keep(preps)
        # MSR-VTT-like consensus structure: a DOMINANT caption form most
        # annotators use, plus minority paraphrases carrying per-caption
        # noise words.  This is what gives consensus training headroom
        # over maximum likelihood: XE spreads probability over every
        # observed form (noise included), while the CIDEr-consensus
        # optimum is the majority form — CST can beat XE only if the two
        # targets differ (arXiv:1712.09532's premise).  A grammar whose 20
        # captions are near-identical leaves no such gap (round-4 probes:
        # CST could only hold the warm start on the v1 grammar).
        all_caps = []
        for _ in range(spec.num_videos):
            s, o = (nouns[rng.integers(len(nouns))],
                    nouns[rng.integers(len(nouns))])
            v = verbs[rng.integers(len(verbs))]
            p = preps[rng.integers(len(preps))]
            canonical = f"a {s} is {v} {p} the {o}"
            caps = []
            for j in range(spec.captions_per_video):
                if j % 5 < 3:          # 60%: the consensus form
                    caps.append(canonical)
                elif j % 5 == 3:       # 20%: shortened variant
                    caps.append(f"the {s} is {v}")
                else:                  # 20%: noisy variant, per-caption
                    a = adjs[rng.integers(len(adjs))]       # random extras
                    a2 = adjs[rng.integers(len(adjs))]
                    caps.append(f"the {a} {s} is {v} {p} a {a2} {o}")
            all_caps.append(caps)
        return all_caps
    all_caps = []
    for _ in range(spec.num_videos):
        s = _SUBJECTS[rng.integers(len(_SUBJECTS))]
        v = _VERBS[rng.integers(len(_VERBS))]
        o = _OBJECTS[rng.integers(len(_OBJECTS))]
        caps = []
        for j in range(spec.captions_per_video):
            drop_o = j % 3 == 2
            caps.append(f"{s} {v}" if drop_o else f"{s} {v} {o}")
        all_caps.append(caps)
    return all_caps


def _warn_if_degenerate_exposure(captions) -> None:
    """Warn when the generated corpus is statistically unlearnable.

    Field lesson (round 4): at 640 videos x 8k-word pools the median
    content word appeared in exactly ONE video, so most words were
    video-private, val generalization was impossible, and XE collapsed
    to function-word templates while train loss fell normally.  Real
    MSR-VTT avoids this with ~6.5k train videos (plus a count-threshold
    to UNK in prepro).  "MSR-VTT scale" must mean the VIDEO COUNT, not
    just vocab/feature shapes.
    """
    videos_per_word: Dict[str, set] = {}
    for i, caps in enumerate(captions):
        for c in caps:
            for w in c.split():
                videos_per_word.setdefault(w, set()).add(i)
    counts = sorted(len(v) for v in videos_per_word.values())
    if not counts:
        return
    median = counts[len(counts) // 2]
    if median <= 1:
        singletons = sum(1 for c in counts if c == 1) / len(counts)
        log.warning(
            "synthetic corpus is statistically DEGENERATE: the median "
            "content word appears in %d video(s) (%.0f%% in exactly one) "
            "— val generalization is impossible for most words and XE "
            "will collapse to function-word templates. Raise num_videos "
            "toward the real dataset's count (MSR-VTT: 6513 train) or "
            "shrink rich_vocab.", median, 100 * singletons)
    elif median < 4:
        # Round-5 field lesson: median 2 at 512 videos x 1500-word pools
        # still produced beam decodes collapsed to SIX function-word
        # templates across 128 val videos — consensus metrics then
        # measure template fit, not content grounding.  4 is the
        # healthy-exposure floor the evidence criteria name.
        log.warning(
            "synthetic corpus has THIN word exposure: the median content "
            "word appears in only %d videos (healthy floor: 4) — beam "
            "decoding tends to collapse toward function-word templates "
            "and consensus metrics overstate content learning. Raise "
            "num_videos or shrink rich_vocab.", median)


def _write_features(root: str, split: str, spec: SyntheticSpec,
                    captions: List[List[str]], vocab: Vocab,
                    rng: np.random.Generator) -> List[str]:
    """Features: deterministic per-video signal derived from the first
    caption's token ids, so features genuinely predict captions.

    Tiny grammar: one-hot-ish bucket bumps (tok % dim) — dim >= vocab in
    tests, so buckets are collision-free and trivially separable.
    Rich grammar: vocab >> dim makes buckets collide 4+ ways; use a
    fixed random SIGNATURE per token instead (near-orthogonal dense
    vectors) so the word -> feature map stays linearly recoverable at
    MSR-VTT vocab/dim ratios — the learnability the real CNN features
    have, which bucket collisions destroy."""
    feat_paths = []
    sig_rng = np.random.default_rng(spec.seed + 7919)
    n_words = len(vocab) + 1
    for m, (dim, t_len) in enumerate(zip(spec.feat_dims, spec.feat_times)):
        signatures = None
        if spec.rich_vocab:
            signatures = sig_rng.standard_normal(
                (n_words, dim)).astype(np.float32) / np.sqrt(dim)
        feats = np.zeros((spec.num_videos, t_len, dim), dtype=np.float32)
        for i, caps in enumerate(captions):
            concept = rng.standard_normal(dim) * 0.1
            ids = vocab.encode(tokenize(caps[0]), spec.max_len)
            for tok in ids[ids > 0]:
                if signatures is not None:
                    concept += signatures[int(tok) % n_words] * 3.0
                else:
                    concept[int(tok) % dim] += 1.0
            feats[i] = concept[None, :] + 0.01 * rng.standard_normal((t_len, dim))
        p = f"{root}/{split}_feat{m}.h5"
        with h5py.File(p, "w") as f:
            f.create_dataset("feats", data=feats if t_len > 1 else feats[:, 0, :])
        feat_paths.append(p)
    return feat_paths


def generate(root: str, split: str = "train", spec: SyntheticSpec = SyntheticSpec(),
             vocab: Vocab | None = None, features: bool = True) -> Dict[str, str]:
    """Write one split's artifact set under ``root``; returns the path map.

    Pass the train split's vocab when generating val/test so ids agree.
    ``features=False`` skips the (multi-GB at north-star scale) feature
    h5s — the label-plane-only mode ``scripts/dataset_fingerprint.py``
    uses, since the dataset's identity is the label h5 + vocab (features
    are a deterministic function of them via the same seed chain).
    """
    # crc32, not hash(): str hashing is salted per process and would make
    # regenerated splits differ between interpreter runs.
    rng = np.random.default_rng(spec.seed + zlib.crc32(split.encode()))
    captions = _make_captions(rng, spec, vocab=vocab)
    video_ids = [f"{split}_video{i}" for i in range(spec.num_videos)]

    paths = build_split(
        [{"id": v, "captions": caps} for v, caps in zip(video_ids, captions)],
        root, split, max_len=spec.max_len, vocab=vocab,
    )
    if split == "train" and spec.rich_vocab:
        _warn_if_degenerate_exposure(captions)
    vocab = load_vocab(paths["vocab_json"])

    if features:
        paths["feat_h5"] = json.dumps(
            _write_features(root, split, spec, captions, vocab, rng))
    return paths


def split_paths(paths: Dict[str, str]):
    """Convert a generate() path map into a dataset.SplitPaths."""
    from .dataset import SplitPaths

    return SplitPaths(
        feat_h5=json.loads(paths["feat_h5"]),
        label_h5=paths["label_h5"],
        info_json=paths["info_json"],
        cocofmt_json=paths["cocofmt_json"],
    )
