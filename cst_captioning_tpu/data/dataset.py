"""HDF5-backed caption dataset — the reference's on-disk contract, TPU-side.

File schema (mirrors the reference's artifacts so a user's existing
preprocessed MSR-VTT/MSVD data plugs in — SURVEY.md §2 "Data loader",
§3.5 get_batch):

- ``<split>_<modality>_feat.h5``: one file per modality, dataset ``"feats"``
  of shape (N, D) (pooled, e.g. category one-hots) or (N, T, D) (temporal,
  e.g. ResNet frame features, C3D clip features).  Row i belongs to the
  i-th video of the split's video list in the info json.
- ``<split>_label.h5``: datasets ``"labels"`` (M, L) int32 0-padded token
  ids, ``"label_start_ix"`` and ``"label_end_ix"`` (N,) int64 giving video
  i's caption rows as the half-open range [start, end)  (0-indexed, unlike
  the reference's 1-indexed lua heritage — conversion happens in prepro).
- ``info.json``: {"ix_to_word": {...}, "videos": [{"id": ..}, ..]} per split.
- ``<split>_cocofmt.json``: coco-format references for metric eval.

Feature rows are read lazily via h5py random access; the loader layer
decides batching/prefetch.  All arrays come back as numpy — JAX device_put
happens at the loader/trainer boundary, never here.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import h5py
import numpy as np

from .vocab import Vocab


@dataclass
class SplitPaths:
    """Paths describing one split's artifacts (any feat count >= 1)."""

    feat_h5: Sequence[str]
    label_h5: str
    info_json: str
    cocofmt_json: Optional[str] = None


class CaptionDataset:
    """Random-access view over one split's HDF5 feature + label files.

    ``preload=True`` reads every feature array into RAM once — h5py random
    access is the input pipeline's only per-batch disk cost, and MSR-VTT-
    scale features (a few GB) fit host memory comfortably, so preloading
    removes the last IO from the 5k captions/sec/chip path (SURVEY.md §7
    hard part (e)).
    """

    def __init__(self, paths: SplitPaths, preload: bool = False):
        self.paths = paths
        with open(paths.info_json) as f:
            info = json.load(f)
        self.vocab = Vocab.from_json(info["ix_to_word"])
        self.video_ids: List[str] = [str(v["id"]) for v in info["videos"]]

        opened: list = []  # close these if validation below fails
        try:
            self._feat_files = [h5py.File(p, "r") for p in paths.feat_h5]
            opened.extend(self._feat_files)
            self._feats = [f["feats"] for f in self._feat_files]
            if preload:
                self._feats = [np.asarray(f, dtype=np.float32)
                               for f in self._feats]
                for f in self._feat_files:
                    f.close()
                self._feat_files = []
            self._label_file = h5py.File(paths.label_h5, "r")
            opened.append(self._label_file)
            self.labels = self._label_file["labels"]          # (M, L)
            self.label_start = np.asarray(self._label_file["label_start_ix"])
            self.label_end = np.asarray(self._label_file["label_end_ix"])
            if preload:  # label matrix is tiny (M x L int32)
                self.labels = np.asarray(self.labels, dtype=np.int32)

            n = len(self.video_ids)
            for feats, path in zip(self._feats, paths.feat_h5):
                if feats.shape[0] != n:
                    raise ValueError(
                        f"{path}: {feats.shape[0]} feature rows != {n} videos in info json"
                    )
            if len(self.label_start) != n or len(self.label_end) != n:
                raise ValueError("label index arrays do not match video count")
            empty = np.flatnonzero(self.label_end <= self.label_start)
            if len(empty):
                raise ValueError(
                    f"videos with zero captions: "
                    f"{[self.video_ids[i] for i in empty[:5]]}"
                )
        except Exception:
            for f in opened:
                f.close()
            raise

    # -- shapes ------------------------------------------------------------

    @property
    def num_videos(self) -> int:
        return len(self.video_ids)

    @property
    def seq_length(self) -> int:
        return self.labels.shape[1]

    @property
    def feat_dims(self) -> List[int]:
        return [int(f.shape[-1]) for f in self._feats]

    @property
    def feat_times(self) -> List[int]:
        """Temporal length per modality; 1 for pooled (N, D) features."""
        return [int(f.shape[1]) if f.ndim == 3 else 1 for f in self._feats]

    # -- access ------------------------------------------------------------

    def features(self, video_ix: np.ndarray) -> List[np.ndarray]:
        """Per-modality feature batches for the given video indices.

        Pooled (N, D) modalities come back as (B, 1, D) so every modality is
        uniformly (B, T_m, D_m) — static T_m per modality keeps XLA happy.
        """
        video_ix = np.asarray(video_ix)
        # h5py fancy selection needs sorted unique indices; np.unique gives
        # exactly that plus the gather map back to the requested order.
        uniq, inv = np.unique(video_ix, return_inverse=True)
        out = []
        for feats in self._feats:
            block = feats[uniq][inv]
            if block.ndim == 2:
                block = block[:, None, :]
            out.append(block.astype(np.float32))
        return out

    def captions_for(self, video_ix: int) -> np.ndarray:
        """(num_caps, L) label rows of one video."""
        s, e = int(self.label_start[video_ix]), int(self.label_end[video_ix])
        return np.asarray(self.labels[s:e], dtype=np.int32)

    def num_captions(self, video_ix: int) -> int:
        return int(self.label_end[video_ix] - self.label_start[video_ix])

    def references(self) -> Dict[str, List[str]]:
        """Ground-truth caption strings per video id (reward/eval path)."""
        if self.paths.cocofmt_json:
            with open(self.paths.cocofmt_json) as f:
                coco = json.load(f)
            refs: Dict[str, List[str]] = {}
            for ann in coco["annotations"]:
                refs.setdefault(str(ann["image_id"]), []).append(ann["caption"])
            return refs
        # fall back to decoding label ids
        return {
            vid: [self.vocab.decode(row) for row in self.captions_for(i)]
            for i, vid in enumerate(self.video_ids)
        }

    def close(self) -> None:
        for f in self._feat_files:
            f.close()
        self._label_file.close()

    def __enter__(self) -> "CaptionDataset":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
