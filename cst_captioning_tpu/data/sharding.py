"""Deterministic per-host shard assignment + global epoch shuffle.

The sharded data plane's contract (RESILIENCE.md "Sharded resume"):

- **Partition, exactly.**  Every epoch, the N shards of a dataset are the
  N strided slices of ONE global permutation — their union is the epoch
  (no video duplicated, none dropped), pinned by the shard-union test in
  tests/test_data_plane.py.
- **Pure-function shuffle.**  The global permutation is a deterministic
  function of ``(seed, epoch)`` ONLY — it consumes no draws from the
  loader's caption-selection RNG stream, so the PR 4 RNG-replay
  discipline (``CaptionLoader.skip_batches`` fast-forwards a resumed run
  draw-for-draw) holds unchanged under any shard count: a preempted-and-
  resumed sharded run is bit-identical to its uninterrupted twin.
- **Shard identity from config, not topology.**  ``--data_shards`` /
  ``--data_shard_id`` (env fallbacks ``CST_DATA_SHARDS`` /
  ``CST_DATA_SHARD_ID``) name the shard explicitly, so a run restarted on
  different hardware keeps its shard — unlike the legacy
  ``process_index``-strided split, which is implicit in process topology.
  ``--data_shards 0`` (the default) keeps the legacy behavior.

Every function here is host-side numpy; nothing touches jax.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

#: Env fallbacks for the CLI flags (resolved as argparse defaults in
#: opts.py, so a malformed value gets a one-line usage error — the PR 4
#: env discipline; tests/conftest.py pins both '' for hermeticity).
ENV_SHARDS = "CST_DATA_SHARDS"
ENV_SHARD_ID = "CST_DATA_SHARD_ID"

#: Domain-separation salt for the global epoch-shuffle RNG: the shuffle
#: must never share a stream with any other consumer of ``--seed`` (the
#: loader's caption draws, model init, rollout keys), or adding a shard
#: axis would perturb unrelated RNG and break the resume-twin drills.
_SHUFFLE_SALT = 0x5AD0


@dataclass(frozen=True)
class ShardSpec:
    """One shard's identity: ``shard_id`` of ``num_shards``."""

    num_shards: int
    shard_id: int

    def __post_init__(self):
        if self.num_shards < 1:
            raise ValueError(
                f"num_shards must be >= 1, got {self.num_shards}")
        if not (0 <= self.shard_id < self.num_shards):
            raise ValueError(
                f"shard_id must satisfy 0 <= shard_id < num_shards "
                f"({self.shard_id} vs {self.num_shards})")

    @property
    def single(self) -> bool:
        return self.num_shards == 1


def resolve_shard_spec(data_shards: int,
                       data_shard_id: int) -> Optional[ShardSpec]:
    """CLI flags -> ShardSpec, or None for the legacy per-process split.

    ``--data_shards 0`` (default) means "no explicit sharding": the
    loader keeps its historical ``process_index``-strided shard.  Any
    value >= 1 selects the global-shuffle sharded plane.  Range errors
    were already rejected at argparse time (opts.py); this re-validates
    for programmatic callers.
    """
    if not data_shards:
        return None
    return ShardSpec(int(data_shards), int(data_shard_id))


def global_epoch_order(num_videos: int, seed: int,
                       epoch: int) -> np.ndarray:
    """THE global shuffle: one permutation of the whole epoch, identical
    on every shard.  A pure function of ``(seed, epoch)`` — a fresh
    Generator per call, so computing epoch 7's order never depends on
    having computed epochs 0..6 (resume can jump straight to it)."""
    rng = np.random.default_rng([_SHUFFLE_SALT, int(seed), int(epoch)])
    return rng.permutation(int(num_videos))


def shard_epoch_order(num_videos: int, seed: int, epoch: int,
                      spec: ShardSpec, shuffle: bool = True) -> np.ndarray:
    """This shard's slice of epoch ``epoch``: positions
    ``shard_id::num_shards`` of the global permutation (or of the
    identity order when ``shuffle`` is off).  The strided slice is what
    makes the union property trivial to see: the N slices of one
    permutation partition it by construction."""
    if shuffle:
        order = global_epoch_order(num_videos, seed, epoch)
    else:
        order = np.arange(int(num_videos))
    return order[spec.shard_id::spec.num_shards]


def shard_size(num_videos: int, spec: ShardSpec) -> int:
    """len(shard_epoch_order(...)) without materializing it."""
    n, k, s = int(num_videos), spec.shard_id, spec.num_shards
    return (n - k + s - 1) // s
