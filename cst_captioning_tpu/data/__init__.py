"""Data layer: HDF5 feature/label datasets, batch streaming, prepro, fixtures."""

from .dataset import CaptionDataset, SplitPaths
from .loader import Batch, CaptionLoader, prefetch_to_device
from .vocab import PAD_EOS, Vocab, build_vocab, load_vocab, save_vocab

__all__ = [
    "Batch",
    "CaptionDataset",
    "CaptionLoader",
    "PAD_EOS",
    "SplitPaths",
    "Vocab",
    "build_vocab",
    "load_vocab",
    "prefetch_to_device",
    "save_vocab",
]
