"""Data layer: HDF5 feature/label datasets, batch streaming, prepro, fixtures."""

from .dataset import CaptionDataset, SplitPaths
from .loader import Batch, BatchPlan, CaptionLoader, prefetch_to_device
from .sharding import ShardSpec, resolve_shard_spec
from .vocab import PAD_EOS, Vocab, build_vocab, load_vocab, save_vocab

__all__ = [
    "Batch",
    "BatchPlan",
    "CaptionDataset",
    "CaptionLoader",
    "PAD_EOS",
    "ShardSpec",
    "SplitPaths",
    "Vocab",
    "build_vocab",
    "load_vocab",
    "prefetch_to_device",
    "resolve_shard_spec",
    "save_vocab",
]
