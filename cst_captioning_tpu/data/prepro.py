"""Offline preprocessing — vocab/label/cocofmt/df/consensus artifact builders.

The reference ships these as ad-hoc scripts + downloadable pickles
(SURVEY.md §2 "Offline prepro": build vocab + label h5 from annotations,
convert refs to coco format, precompute the CIDEr df pickle and the
per-caption consensus scores pickle).  Here they are one importable module
with a CLI:

    python -m cst_captioning_tpu.data.prepro \
        --annotations anns.json --split train --out_dir data/ \
        [--count_threshold 3] [--max_len 30] [--vocab_json existing.json]

``annotations`` format: {"videos": [{"id": ..., "captions": [...]}, ...]} —
the minimal dataset-agnostic shape MSVD/MSR-VTT/ActivityNet exports all map
onto.  Feature h5s are produced by upstream CNN extraction and are consumed
as-is (the reference never ran CNNs either).
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List, Optional, Sequence

import h5py
import numpy as np

from ..resilience.integrity import atomic_json_write
from ..metrics import (
    build_corpus_df,
    compute_consensus_scores,
    normalize_weights,
    save_consensus,
    save_corpus_df,
    tokenize,
)
from .vocab import Vocab, build_vocab, load_vocab, save_vocab


def load_annotations(path: str) -> List[dict]:
    with open(path) as f:
        obj = json.load(f)
    return obj["videos"] if isinstance(obj, dict) else obj


def build_split(
    annotations: Sequence[dict],
    out_dir: str,
    split: str,
    max_len: int = 30,
    count_threshold: int = 1,
    vocab: Optional[Vocab] = None,
    build_reward_artifacts: bool = True,
) -> Dict[str, str]:
    """Build every offline artifact for one split; returns the path map."""
    os.makedirs(out_dir, exist_ok=True)
    video_ids = [str(v["id"]) for v in annotations]
    raw_caps = [[str(c) for c in v["captions"]] for v in annotations]
    empty = [vid for vid, caps in zip(video_ids, raw_caps) if not caps]
    if empty:
        raise ValueError(
            f"videos with zero captions (fix or drop them): {empty[:5]}"
        )
    tokenized = [[tokenize(c) for c in caps] for caps in raw_caps]

    if vocab is None:
        vocab = build_vocab(
            (t for caps in tokenized for t in caps), count_threshold=count_threshold
        )
    paths: Dict[str, str] = {}

    vocab_path = os.path.join(out_dir, f"{split}_vocab.json")
    save_vocab(vocab_path, vocab)
    paths["vocab_json"] = vocab_path

    info_path = os.path.join(out_dir, f"{split}_info.json")
    atomic_json_write(info_path,
                      {"ix_to_word": vocab.to_json(),
                       "videos": [{"id": v} for v in video_ids]})
    paths["info_json"] = info_path

    rows, starts, ends = [], [], []
    for caps in tokenized:
        starts.append(len(rows))
        rows.extend(vocab.encode(t, max_len) for t in caps)
        ends.append(len(rows))
    label_path = os.path.join(out_dir, f"{split}_label.h5")
    with h5py.File(label_path, "w") as f:
        f.create_dataset("labels", data=np.stack(rows).astype(np.int32))
        f.create_dataset("label_start_ix", data=np.asarray(starts, dtype=np.int64))
        f.create_dataset("label_end_ix", data=np.asarray(ends, dtype=np.int64))
    paths["label_h5"] = label_path

    coco_path = os.path.join(out_dir, f"{split}_cocofmt.json")
    atomic_json_write(coco_path, {
        "images": [{"id": v} for v in video_ids],
        "annotations": [
            {"image_id": vid, "id": f"{vid}#{j}", "caption": c}
            for vid, caps in zip(video_ids, raw_caps)
            for j, c in enumerate(caps)
        ],
    })
    paths["cocofmt_json"] = coco_path

    if build_reward_artifacts:
        tok_refs = {vid: [" ".join(t) for t in toks]
                    for vid, toks in zip(video_ids, tokenized)}
        df, ndocs = build_corpus_df(tok_refs)
        df_path = os.path.join(out_dir, f"{split}_ciderdf.pkl")
        save_corpus_df(df_path, df, ndocs)
        paths["cached_tokens"] = df_path

        # Raw leave-one-out consensus scores (the reference's
        # --train_bcmrscores_pkl artifact): WXE normalizes them into weights
        # at train time; the scb-gt RL baseline uses them raw.
        scores = compute_consensus_scores(tok_refs)
        cons_path = os.path.join(out_dir, f"{split}_consensus.pkl")
        save_consensus(cons_path, scores)
        paths["consensus_pkl"] = cons_path

        # Pre-normalized WXE weights (mean 1 per video) for loaders that
        # want them without a normalize step.
        wxe_path = os.path.join(out_dir, f"{split}_wxe_weights.pkl")
        save_consensus(wxe_path, normalize_weights(scores))
        paths["wxe_weights_pkl"] = wxe_path
    return paths


def main(argv: Optional[Sequence[str]] = None) -> Dict[str, str]:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--annotations", required=True)
    ap.add_argument("--split", default="train")
    ap.add_argument("--out_dir", required=True)
    ap.add_argument("--max_len", type=int, default=30)
    ap.add_argument("--count_threshold", type=int, default=1)
    ap.add_argument("--vocab_json", default=None,
                    help="reuse an existing vocab (val/test splits)")
    ap.add_argument("--no_reward_artifacts", action="store_true")
    args = ap.parse_args(argv)

    vocab = load_vocab(args.vocab_json) if args.vocab_json else None
    paths = build_split(
        load_annotations(args.annotations),
        args.out_dir,
        args.split,
        max_len=args.max_len,
        count_threshold=args.count_threshold,
        vocab=vocab,
        build_reward_artifacts=not args.no_reward_artifacts,
    )
    print(json.dumps(paths, indent=2))
    return paths


if __name__ == "__main__":
    main()
