"""Vocabulary and sequence<->string conversion.

Token-id convention (matches the reference's neuraltalk-style labels —
SURVEY.md §3.5: labels are 0-padded int matrices, decoding stops at 0):

- id 0 is PAD and EOS at once: sequences end at the first 0, padding is 0.
- real words occupy ids 1..V.
- the decoder's BOS *input* is also id 0 (0 never occurs as a real word, so
  feeding it at t=0 is unambiguous); the embedding table has V+1 rows.

This one-symbol-fits-all scheme keeps masks trivial (`mask = cummax(seq==0)`
logic) and is exactly what the reference's CrossEntropyCriterion/``decode_sequence``
assume, so checkpoint semantics and caption truncation behave identically.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, Iterable, List, Mapping, Sequence

import numpy as np

from ..resilience.integrity import atomic_json_write

PAD_EOS = 0  # id 0: padding, end-of-sequence, and the decoder's BOS input
UNK_TOKEN = "<unk>"


class Vocab:
    """Immutable word<->id mapping with id 0 reserved for PAD/EOS/BOS."""

    def __init__(self, ix_to_word: Mapping[int, str]):
        self.ix_to_word: Dict[int, str] = {int(k): v for k, v in ix_to_word.items()}
        if PAD_EOS in self.ix_to_word:
            raise ValueError("id 0 is reserved for PAD/EOS")
        self.word_to_ix: Dict[str, int] = {w: i for i, w in self.ix_to_word.items()}
        self.unk_ix = self.word_to_ix.get(UNK_TOKEN)

    def __len__(self) -> int:
        # number of real words; embedding tables need len(vocab)+1 rows
        return len(self.ix_to_word)

    @property
    def size_with_pad(self) -> int:
        return len(self.ix_to_word) + 1

    def encode(self, tokens: Sequence[str], max_len: int) -> np.ndarray:
        """Tokens -> fixed-length id row, 0-padded (EOS implicit at first 0)."""
        out = np.zeros(max_len, dtype=np.int32)
        j = 0
        for w in tokens:
            if j >= max_len:
                break
            ix = self.word_to_ix.get(w, self.unk_ix)
            if ix is None:  # no <unk> in vocab: drop unknown words (no 0-hole,
                continue    # which would read as premature EOS)
            out[j] = ix
            j += 1
        return out

    def decode(self, ids: Iterable[int]) -> str:
        """Id sequence -> caption string, stopping at the first 0 (EOS)."""
        words = []
        for i in ids:
            i = int(i)
            if i == PAD_EOS:
                break
            words.append(self.ix_to_word.get(i, UNK_TOKEN))
        return " ".join(words)

    def decode_batch(self, seqs: np.ndarray) -> List[str]:
        """(B, L) id matrix -> list of caption strings (the reward-path
        device->host conversion; SURVEY.md §3.2)."""
        return [self.decode(row) for row in np.asarray(seqs)]

    def to_json(self) -> Dict[str, str]:
        return {str(k): v for k, v in self.ix_to_word.items()}

    @classmethod
    def from_json(cls, obj: Mapping[str, str]) -> "Vocab":
        return cls({int(k): v for k, v in obj.items()})


def build_vocab(
    tokenized_captions: Iterable[Sequence[str]],
    count_threshold: int = 1,
    add_unk: bool = True,
) -> Vocab:
    """Frequency-thresholded vocabulary (the reference's prepro policy:
    words below the count threshold collapse to <unk>)."""
    counts = Counter()
    for toks in tokenized_captions:
        counts.update(toks)
    words = sorted(w for w, c in counts.items() if c >= count_threshold)
    if add_unk and UNK_TOKEN not in words:
        words.append(UNK_TOKEN)
    return Vocab({i + 1: w for i, w in enumerate(words)})


def save_vocab(path: str, vocab: Vocab) -> None:
    # Dataset artifacts are durable: a torn vocab json would poison every
    # later stage that loads it (atomic-write discipline, ANALYSIS.md).
    atomic_json_write(path, {"ix_to_word": vocab.to_json()})


def load_vocab(path: str) -> Vocab:
    with open(path) as f:
        return Vocab.from_json(json.load(f)["ix_to_word"])
