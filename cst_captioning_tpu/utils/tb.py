"""Torch-free TensorBoard scalar writer.

The framework runs with no PyTorch in the loop (README), so TB scalars are
written through the ``tensorboard`` package's own event-file writer rather
than ``torch.utils.tensorboard``.  Only scalars are needed (train metrics +
val scores); anything fancier belongs in the profiler trace.
"""

from __future__ import annotations

import time


class ScalarWriter:
    """Minimal add_scalar/flush/close over tensorboard's EventFileWriter.

    Raises ImportError at construction if the tensorboard package is not
    installed — callers decide whether that is fatal (the trainer warns and
    continues; metrics.jsonl is always written regardless).

    Lifecycle: usable as a context manager, and ``close()`` is idempotent
    with ``add_scalar``/``flush`` after close tolerated as no-ops — the
    trainer closes via ``finally`` AND registers an atexit hook so events
    are not lost when a run dies mid-epoch, and that double/late-close
    ordering must never raise or resurrect the writer.
    """

    def __init__(self, logdir: str):
        from tensorboard.compat.proto.event_pb2 import Event
        from tensorboard.compat.proto.summary_pb2 import Summary
        from tensorboard.summary.writer.event_file_writer import (
            EventFileWriter,
        )

        self._Event = Event
        self._Summary = Summary
        self._writer = EventFileWriter(logdir)
        self._closed = False

    def __enter__(self) -> "ScalarWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        if self._closed:
            return  # late write after shutdown: dropped, not raised
        event = self._Event(
            step=int(step),
            wall_time=time.time(),
            summary=self._Summary(
                value=[self._Summary.Value(tag=tag,
                                           simple_value=float(value))]
            ),
        )
        self._writer.add_event(event)

    def flush(self) -> None:
        if self._closed:
            return
        self._writer.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._writer.flush()
        self._writer.close()
