"""Shared utilities (scalar logging, misc helpers)."""

from .tb import ScalarWriter

__all__ = ["ScalarWriter"]
