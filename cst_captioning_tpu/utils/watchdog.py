"""Progress watchdog: turn a wedged device backend into a fast, clean exit.

Operating over a remote-TPU tunnel (this session's axon transport), the
failure mode is not an exception but a HANG: a step dispatch, transfer, or
remote compile blocks forever on a dead RPC and the training process sits
in a futex wait with hours of chip time already invested.  Checkpointed
recovery (``--save_every_steps`` + auto-resume) makes dying CHEAP — what is
expensive is not noticing.  The watchdog makes the process die loudly and
promptly instead: a daemon thread watches a monotonic heartbeat the main
loop touches at every progress point, and if no beat lands for
``timeout_s`` seconds it logs CRITICAL state and ``os._exit``\\ s with
:data:`WEDGE_EXIT_CODE` (124, the coreutils ``timeout`` convention).

``os._exit`` (not ``sys.exit``) is deliberate: the main thread is stuck
inside a blocking C++ runtime call that Python exceptions cannot unwind,
and a "graceful" shutdown would block on the very transport that died.
Everything the run cannot afford to lose is already on disk (orbax
checkpoints, metrics.jsonl is line-buffered).

Callers that orchestrate stages (scripts/scale_chain.py) treat
WEDGE_EXIT_CODE — or any failure while the device probe also fails — as
"environment sick, resume when it heals", and every other exit as a real
failure to surface.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ..resilience.exitcodes import EXIT_WEDGE
from ..resilience.integrity import atomic_json_write

#: Exit status for "no progress within the timeout" — matches coreutils
#: ``timeout(1)`` so shell-level and watchdog-level wedge kills look alike.
#: Canonical home: the resilience exit-code taxonomy (resilience/
#: exitcodes.py); re-exported here for the many existing importers.
WEDGE_EXIT_CODE = EXIT_WEDGE


class ProgressWatchdog:
    """Daemon-thread heartbeat monitor.

    ``beat()`` is cheap (one monotonic read + store, no locking — a torn
    read just delays detection by one poll interval) and safe from any
    thread.  A ``timeout_s`` of 0 disables the kill policy; unless
    heartbeat-only mode is armed (``heartbeat_path`` +
    ``heartbeat_interval_s``), every method is then a no-op, so call
    sites need no conditionals.

    Heartbeat file: with ``heartbeat_path`` set, the monitor thread also
    writes a small JSON status file at thread start and once per poll —
    liveness PLUS context (``payload()``, e.g. the telemetry registry's
    last-step phase timings and resilience counters) that an external
    harness can read without attaching to the process.  The payload
    callable must only touch HOST state, exactly like ``describe``: it
    runs while the main thread may be wedged inside a dead transport, and
    a device fetch here would hang the very thread reporting the hang.
    Writes are atomic (tmp + replace) and best-effort — observability
    must never kill the run it observes.
    """

    def __init__(self, timeout_s: float,
                 describe: Optional[Callable[[], str]] = None,
                 on_timeout: Optional[Callable[[float], None]] = None,
                 heartbeat_path: Optional[str] = None,
                 payload: Optional[Callable[[], Dict]] = None,
                 heartbeat_interval_s: float = 0.0):
        self.timeout_s = float(timeout_s)
        self._describe = describe or (lambda: "")
        self._on_timeout = on_timeout or self._die
        self._heartbeat_path = heartbeat_path
        self._payload = payload
        # Heartbeat-only mode (the serving health plane): a positive
        # interval + a heartbeat path keep the monitor thread writing
        # heartbeat.json even with the wedge timeout disabled (0), so a
        # deployment can have liveness reporting without committing to a
        # kill policy.  With a timeout too, the poll is the finer of the
        # two cadences.
        self._hb_interval = float(heartbeat_interval_s or 0.0)
        self._last = time.monotonic()
        self._stop = threading.Event()
        # The monitor thread never manages its own lifecycle: only the
        # controlling thread may start/join/replace it
        # (cstlint:thread-ownership).
        self._thread: Optional[threading.Thread] = None  # cstlint: owned_by=control

    def _armed(self) -> bool:
        return self.timeout_s > 0 or (
            self._heartbeat_path is not None and self._hb_interval > 0)

    def _poll_s(self) -> float:
        polls = []
        if self.timeout_s > 0:
            polls.append(max(1.0, min(30.0, self.timeout_s / 4.0)))
        if self._heartbeat_path is not None and self._hb_interval > 0:
            polls.append(max(0.05, self._hb_interval))
        return min(polls)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ProgressWatchdog":
        if self._armed() and self._thread is None:
            self._stop.clear()
            self.beat()
            self._thread = threading.Thread(
                target=self._run, name="progress-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
            # Final heartbeat: the file's last state reflects the run's
            # END (full counters, last step), not whichever poll happened
            # to land last — heartbeats mid-run are poll-cadenced.
            self._write_heartbeat(time.monotonic() - self._last)

    def __enter__(self) -> "ProgressWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- heartbeat ---------------------------------------------------------
    def beat(self) -> None:
        self._last = time.monotonic()

    # -- internals ---------------------------------------------------------
    def _write_heartbeat(self, gap: float) -> None:
        if self._heartbeat_path is None:
            return
        try:
            doc = {"time": time.time(), "pid": os.getpid(),
                   "beat_gap_s": round(gap, 3),
                   "timeout_s": self.timeout_s}
            if self._payload is not None:
                doc.update(self._payload() or {})
            target = os.path.abspath(self._heartbeat_path)
            os.makedirs(os.path.dirname(target), exist_ok=True)
            # Durable-JSON discipline (fsync'd tmp + atomic rename + dir
            # fsync): a kill landing mid-write must never leave a TORN
            # heartbeat for the harness to misread as garbage.  Polls are
            # seconds apart, so the fsyncs are noise-level cost.
            atomic_json_write(target, doc, default=str)
        except Exception:
            pass  # best-effort: a full disk must not look like a wedge

    def _run(self) -> None:
        poll = self._poll_s()
        self._write_heartbeat(time.monotonic() - self._last)
        while not self._stop.wait(poll):
            gap = time.monotonic() - self._last
            self._write_heartbeat(gap)
            if self.timeout_s > 0 and gap > self.timeout_s:
                self._on_timeout(gap)
                # The default handler never returns (os._exit).  An
                # injected handler that does return wants continued
                # monitoring: rearm the heartbeat so the next timeout
                # measures a fresh gap instead of refiring every poll.
                self.beat()

    def _die(self, gap: float) -> None:  # pragma: no cover - exits process
        msg = ("no progress for %.0fs (timeout %.0fs) — device backend "
               "presumed wedged; exiting %d for checkpointed resume. %s"
               % (gap, self.timeout_s, WEDGE_EXIT_CODE, self._describe()))
        # Deliberately NOT log.critical: the wedged main thread may hold
        # the logging module lock (blocked mid-write to a dead pipe), and
        # acquiring it here would deadlock the watchdog too.  Write the
        # last word via the raw fd with O_NONBLOCK so even a full dead
        # pipe cannot block this thread (no restore needed — the next
        # line ends the process), then exit unconditionally.
        try:
            import fcntl

            fl = fcntl.fcntl(2, fcntl.F_GETFL)
            fcntl.fcntl(2, fcntl.F_SETFL, fl | os.O_NONBLOCK)
        except Exception:
            pass
        try:
            os.write(2, ("WATCHDOG: " + msg + "\n").encode())
        except Exception:
            pass
        os._exit(WEDGE_EXIT_CODE)
