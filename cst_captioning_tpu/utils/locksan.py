"""Runtime lock sanitizer: the dynamic cross-check on declared LOCK_ORDER.

The static ``lock-order`` rule (analysis/concurrency.py) can only see
LEXICALLY nested ``with`` acquisitions; the orders that actually deadlock
are usually dynamic — lock A held while a callback takes lock B in a
different module (the serving server's write lock around a per-connection
respond lock, the ProgramCache lock around the registry's counter lock).
And a declared order is a claim that rots: nothing stops a refactor from
quietly inverting it.  This module closes both gaps at runtime:

- :func:`named_lock` is the project's lock factory.  Disabled (the
  default), it returns a plain ``threading.Lock`` — zero overhead, no
  behavior change.  With ``CST_LOCK_SANITIZER=1`` in the environment at
  creation time it returns a :class:`_SanitizedLock` that records, per
  thread, every "acquired B while holding A" edge.
- :func:`declare_order` registers the same per-module ``LOCK_ORDER``
  tables the static rule checks (each table declares ``names[i]`` may be
  held while acquiring ``names[j]`` for ``i < j``).
- On every sanitized acquisition the edge is asserted against the
  declared partial order BEFORE blocking: an edge that INVERTS a
  declared path or an edge nobody declared writes a violation receipt
  through ``resilience.integrity.atomic_json_write`` (so a deadlock
  that follows cannot tear the evidence) and raises
  :class:`LockOrderViolation`.  Those two checks are complete: an edge
  is only ever RECORDED when the declared order covers it, so any
  would-be cross-thread cycle necessarily contains an edge one of the
  two checks rejects first (the recorded edges ride in the receipt as
  diagnostics).

Wired into ``make serve-chaos`` and the tier-1 serving fast slice
(tests/test_serving_resilience.py sets the env var), so the declared
order is re-validated under the PR 9 fault drills on every run — the
receipt requirement is pinned by tests/test_locksan.py.

The implementation lives HERE (utils/) rather than in analysis/ so the
runtime modules that create locks (telemetry, serving, native) depend
only on this stdlib-only file — never on the lint engine;
``analysis.locksan`` re-exports everything for the documented
analysis-side surface and the static rule's prose.

The sanitizer itself must stay reentrancy-clean: its one internal lock
(``_state_lock``) is a plain ``threading.Lock`` acquired only with NO
sanitized lock's internal state mid-update, and the receipt write happens
outside any sanitized lock the caller does not already hold.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

#: Environment flag read at lock-CREATION time (so a test can arm the
#: sanitizer for the objects it builds without rebuilding module state).
ENV_FLAG = "CST_LOCK_SANITIZER"
#: Where the violation receipt lands; overridable for tests.
ENV_RECEIPT = "CST_LOCK_SANITIZER_RECEIPT"
DEFAULT_RECEIPT = "/tmp/cst_locksan_violation.json"

#: Receipt format version.
LOCKSAN_SCHEMA = 1


class LockOrderViolation(AssertionError):
    """A runtime acquisition contradicted the declared LOCK_ORDER (or an
    order already observed on another thread).  Raised AFTER the receipt
    is durably written, so the evidence survives the deadlock this is
    predicting."""


# -- global sanitizer state (guarded by _state_lock) ------------------------

_state_lock = threading.Lock()
_declared_edges: Set[Tuple[str, str]] = set()
_declared_tables: List[Tuple[str, ...]] = []
_observed_edges: Dict[Tuple[str, str], Dict] = {}
_violations: List[Dict] = []
_tls = threading.local()


def enabled() -> bool:
    """Is the sanitizer armed in this environment right now?"""
    return os.environ.get(ENV_FLAG, "") == "1"


def declare_order(*names: str) -> None:
    """Register one LOCK_ORDER table: ``names[i]`` may be held while
    acquiring ``names[j]`` for every ``i < j``.  Idempotent; modules call
    it at import time next to their ``LOCK_ORDER`` tuple, so the runtime
    registry and the statically checked table are the same declaration."""
    table = tuple(str(n) for n in names)
    if len(table) < 2:
        return
    with _state_lock:
        if table not in _declared_tables:
            _declared_tables.append(table)
        for i in range(len(table)):
            for j in range(i + 1, len(table)):
                _declared_edges.add((table[i], table[j]))


def path_exists(edges, src: str, dst: str) -> bool:
    """Transitive reachability over an edge set (BFS) — shared by the
    runtime order check here and the static ``lock-order`` rule
    (analysis/concurrency.py), so the two analyses agree on what
    "declared before" means."""
    if src == dst:
        return True
    seen = {src}
    frontier = [src]
    while frontier:
        here = frontier.pop()
        for a, b in edges:
            if a == here and b not in seen:
                if b == dst:
                    return True
                seen.add(b)
                frontier.append(b)
    return False


def _declared_path(src: str, dst: str) -> bool:
    """Reachability in the declared order; caller holds ``_state_lock``."""
    return path_exists(_declared_edges, src, dst)


def _held_stack() -> List[str]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def violations() -> List[Dict]:
    """Violation records accumulated this process (receipts are also on
    disk); the serving fast slice asserts this stays empty."""
    with _state_lock:
        return list(_violations)


def reset_observed() -> None:
    """Test hook: clear observed edges + recorded violations.  Declared
    tables persist — they are import-time facts, not run state."""
    with _state_lock:
        _observed_edges.clear()
        _violations.clear()


def _receipt_path() -> str:
    return os.environ.get(ENV_RECEIPT, DEFAULT_RECEIPT)


def _record_violation(kind: str, held: str, acquiring: str,
                      message: str) -> None:
    """Write the receipt durably, remember the violation, raise."""
    with _state_lock:
        doc = {
            "schema": LOCKSAN_SCHEMA,
            "kind": kind,
            "edge": [held, acquiring],
            "thread": threading.current_thread().name,
            "held_stack": list(_held_stack()),
            "message": message,
            "declared_tables": [list(t) for t in _declared_tables],
            "observed_edges": sorted(
                [list(e) for e in _observed_edges]),
        }
        _violations.append(doc)
    # Durable receipt OUTSIDE the state lock: atomic_json_write fsyncs,
    # and nothing below needs the registries again.
    try:
        from ..resilience.integrity import atomic_json_write

        atomic_json_write(_receipt_path(), doc, indent=2)
    except OSError:
        pass  # a full disk must not mask the violation — the raise below
    raise LockOrderViolation(f"lock-order violation ({kind}): {message}")


def _check_edge(held: str, acquiring: str) -> None:
    """Assert one dynamic acquisition edge against the declared order.
    Called BEFORE blocking on the target lock, so a would-be deadlock is
    reported instead of entered."""
    with _state_lock:
        if _declared_path(acquiring, held):
            kind, msg = "inverted-order", (
                f"acquiring '{acquiring}' while holding '{held}' "
                "inverts the declared LOCK_ORDER "
                f"(declared: {acquiring} before {held})")
        elif not _declared_path(held, acquiring):
            kind, msg = "undeclared-edge", (
                f"acquiring '{acquiring}' while holding '{held}' is not "
                "covered by any declared LOCK_ORDER table — declare the "
                "pair (analysis/concurrency.py grammar) or break the "
                "nesting")
        else:
            _observed_edges.setdefault(
                (held, acquiring),
                {"thread": threading.current_thread().name})
            return
    _record_violation(kind, held, acquiring, msg)


class _SanitizedLock:
    """``threading.Lock`` twin that runs every acquisition through the
    order check.  Context-manager and acquire/release compatible with the
    subset of the Lock API this tree uses."""

    __slots__ = ("name", "_lk")

    def __init__(self, name: str):
        self.name = str(name)
        self._lk = threading.Lock()

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        for held in list(_held_stack()):
            _check_edge(held, self.name)
        got = self._lk.acquire(blocking, timeout)
        if got:
            _held_stack().append(self.name)
        return got

    def release(self) -> None:
        stack = _held_stack()
        # Remove the most recent occurrence from THIS thread's stack:
        # same-thread releases may legally be non-LIFO.  The sanitizer
        # assumes same-thread release (every project use is a with
        # block); a cross-thread handoff release would leave the
        # acquirer's stack stale — don't wrap such a lock.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._lk.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name!r} at {id(self):#x}>"


def named_lock(name: str):
    """The project's lock factory (the static lock-order rule resolves
    ``with``-acquisitions to canonical lock names through assignments
    from this call).  Plain ``threading.Lock`` unless the sanitizer env
    flag is set when the lock is CREATED."""
    if enabled():
        return _SanitizedLock(name)
    return threading.Lock()
