"""Host-platform control: keep jax off a wedged remote-TPU tunnel.

This session's interpreter may boot with an ``.axon_site`` sitecustomize
(injected via PYTHONPATH) that imports jax and registers a remote-TPU
"axon" PJRT plugin whose tunnel client blocks indefinitely when the tunnel
is down.  Setting ``JAX_PLATFORMS`` in-process is then too late — jax read
the env at import — so CPU-only code paths (tests, the multichip dry run)
must both update jax's config directly and deregister the plugin factory
so ``jax.devices()`` can never initialize the tunnel client.

Single source of truth for that scrub; used by tests/conftest.py and
``__graft_entry__._dryrun_multichip_impl``.
"""

from __future__ import annotations

import logging
import os


def configure_cli_logging(loglevel: str) -> None:
    """Install the CLI's root logging config, displacing any pre-existing
    handler.

    ``logging.basicConfig`` is a no-op when a root handler already exists,
    and this session's ``.axon_site`` sitecustomize installs one (at
    WARNING) while registering the PJRT plugin — which silently swallowed
    every INFO progress line of an in-field training run.  ``--loglevel``
    is the CLI's contract with the operator, so it wins: ``force=True``
    removes pre-installed handlers first.
    """
    logging.basicConfig(
        level=getattr(logging, str(loglevel).upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        force=True,
    )


def axon_registered() -> bool:
    """True if the remote-TPU "axon" PJRT plugin factory is registered.

    Fails CLOSED: if jax's private registry moved and we cannot tell, fall
    back to whether the ``.axon_site`` sitecustomize is on PYTHONPATH —
    callers use this to decide whether touching the default backend could
    hang, so "unsure" must not disarm their guard.
    """
    try:
        import jax._src.xla_bridge as _xb

        return "axon" in _xb._backend_factories
    except Exception:  # pragma: no cover - jax internals moved
        return "axon" in os.environ.get("PYTHONPATH", "").lower()


def scrub_env(env: dict) -> dict:
    """Strip everything that could route jax through the axon tunnel."""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    return env


def with_host_device_count(flags: str, n: int) -> str:
    """XLA_FLAGS string with ``--xla_force_host_platform_device_count=n``,
    preserving every other flag already present."""
    kept = [
        f for f in flags.split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    kept.append(f"--xla_force_host_platform_device_count={n}")
    return " ".join(kept)


def run_in_group(cmd: list, *, env: dict | None = None,
                 cwd: str | None = None, timeout: float | None = None,
                 stdout=None, stderr=None,
                 timeout_info: dict | None = None) -> int:
    """Run ``cmd`` in its own process GROUP with inherited stdio.

    On timeout, SIGKILL the whole group — a wedged PJRT tunnel plugin can
    spawn helper processes that outlive a direct-child kill — and return
    124 (the coreutils ``timeout`` convention).  Otherwise return the rc.
    Any other unwind (KeyboardInterrupt, SystemExit from a signal handler)
    also group-kills the child: a new-session child never receives the
    terminal's SIGINT, and an interrupted caller must not leave it running
    detached against the device.

    ``stdout`` may be a FILE object (not a pipe) to capture the child's
    stdout; a file stays safe across the group kill because no reader can
    block on it, unlike a pipe held open by orphaned tunnel helpers.

    ``timeout_info``, if given, gets ``timeout_info["timed_out"]`` set —
    callers that treat the child's OWN exit 124 differently from a
    harness-timeout 124 (scripts/scale_chain.py) need the distinction.
    """
    import signal
    import subprocess

    proc = subprocess.Popen(cmd, env=env, cwd=cwd, start_new_session=True,
                            stdout=stdout, stderr=stderr)

    def kill_group():
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            proc.kill()
        proc.wait()

    if timeout_info is not None:
        timeout_info["timed_out"] = False
    try:
        try:
            return proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            if timeout_info is not None:
                timeout_info["timed_out"] = True
            return 124
    finally:
        if proc.poll() is None:
            kill_group()


def git_head_sha(repo_dir: str | None = None) -> str:
    """HEAD commit of ``repo_dir`` (default: this package's repo), or
    ``"unknown"`` — evidence artifacts (BENCH_TPU_CACHE entries,
    collect_evidence manifests) stamp results with the code that produced
    them, and both stampers must share ONE fallback semantics."""
    import subprocess

    if repo_dir is None:
        repo_dir = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo_dir,
            capture_output=True, text=True, timeout=10)
        sha = proc.stdout.strip()
        return sha if proc.returncode == 0 and sha else "unknown"
    except Exception:
        return "unknown"


def enable_compile_cache(cache_dir: str) -> bool:
    """Turn on JAX's persistent compilation cache at ``cache_dir``.

    First compiles of the train/eval/beam programs cost 20-40s each on TPU;
    with the cache, repeat CLI invocations (stage chains, resumed runs,
    eval after train) load them in milliseconds.  Returns True if enabled;
    failures (read-only fs, backend without serialization support) only
    warn — the cache is an optimization, never a correctness dependency.
    """
    if not cache_dir:
        return False
    try:
        # A parent harness (the test suite, CI) that exports
        # JAX_COMPILATION_CACHE_DIR / JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS
        # owns the cache policy for every child it spawns; the CLI default
        # must not clobber it — otherwise spawned train.py/eval.py children
        # repopulate the operator's cache dir and recompile every
        # sub-second program the parent's lower threshold would have cached.
        path = os.path.expanduser(
            os.environ.get("JAX_COMPILATION_CACHE_DIR") or cache_dir)
        min_secs = float(os.environ.get(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", 1.0))
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_secs)
        return True
    except Exception as e:  # pragma: no cover - env-specific failures
        import logging

        logging.getLogger(__name__).warning(
            "persistent compilation cache disabled (%s)", e)
        return False


def force_cpu_platform() -> None:
    """Pin jax to the host-CPU platform and drop the axon plugin factory.

    Safe to call whether or not jax is already imported; env vars are also
    set so subprocesses inherit the choice.
    """
    scrub_env(os.environ)

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:  # pragma: no cover - jax internals moved; config above still holds
        pass
