"""Sequence/context parallelism: attention over a time-sharded encoder.

ActivityNet-length feature streams (driver config 5; minutes of video at
frame rate) make the encoder memory ``(B, T, H)`` the largest live tensor
— at T in the tens of thousands it stops fitting comfortably next to the
training step's other buffers, and the reference (which mean-pools T away
— SURVEY.md §5 "long-context") has nothing to imitate.  The TPU-native
answer: leave the memory sharded over a mesh axis along T and give the
decoder's cross-attention a blockwise online-softmax combine over that
axis, so the full T never materializes on any device.

Design notes:

- These are the *explicit* collective forms (``shard_map`` + ``pmax`` /
  ``psum``), not GSPMD annotations: a softmax over a sharded axis is
  exactly the case where XLA's partitioner may insert an all-gather of
  the sharded operand, which defeats the point.  The online combine
  guarantees per-device peak memory of one local block.
- Cross-attention (short decoder query, long encoder memory) wants the
  combine schedule, not a ring: every device holds its own K/V block
  once, computes its partial softmax statistics, and one ``psum`` merges
  them.  A ring (``ppermute`` rotating K/V blocks) pays (shards-1)
  communication hops to compute the same thing and only wins when Q is
  sharded over the SAME axis as K/V (self-attention over the long
  sequence), which this model family does not have — the decoder's
  self-attention is over <=30 caption tokens.  ``ring_cross_attention``
  below implements the ring schedule anyway (hop-pipelined, same
  numerics) both as the scaling path for memory-bound blocks and as an
  independent check on the combine version.
- The math is the standard streaming-softmax merge: each shard computes
  local max m_i, rescaled exp-sum s_i and context numerator n_i; the
  global result is softmax-combined via m = pmax(m_i),
  s = psum(s_i * exp(m_i - m)), ctx = psum(n_i * exp(m_i - m)) / s.
  Scores are computed in f32 regardless of storage dtype (the same
  decision as ops/attention.py and the Pallas kernel).

Reference counterpart: none — the reference has no sequence parallelism
(SURVEY.md §2 parallelism table); this module is the rebuild's "SP/CP"
row.  Equivalence to single-device attention is pinned to 1e-5 by
tests/test_sequence_parallel.py on the 8-device CPU mesh, including
ragged T with padding masks.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS

# jax moved shard_map out of experimental (and renamed check_rep ->
# check_vma) around 0.5/0.6; support both so the SP path works on the
# installed 0.4.x as well as newer runtimes.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
    _SM_CHECK_KW = "check_vma"
else:  # pragma: no cover - exercised on jax<0.5 installs (like this one)
    from jax.experimental.shard_map import shard_map

    _SM_CHECK_KW = "check_rep"


def _axis_size(axis_name: str) -> int:
    """jax.lax.axis_size appeared after 0.4.x; psum(1) is the portable
    spelling of "how many shards on this axis" inside shard_map."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)

_NEG_INF = -1e30  # finite "masked" score: keeps pmax/exp NaN-free when a
                  # whole shard (or a whole row) is padding


def time_sharding(mesh: Mesh) -> NamedSharding:
    """(B, T, ...) arrays: batch over ``data``, time over ``model``."""
    return NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS))


def _combine(scores: jnp.ndarray, values: jnp.ndarray, axis_name: str,
             contract: str):
    """Streaming-softmax combine of per-shard attention blocks.

    scores: local f32 attention logits with Tl last (already masked);
    values: local value block; ``contract`` is the einsum folding the
    exp'd scores with values into the local context numerator (e.g.
    ``"bqt,btd->bqd"`` for dot attention, ``"bt,bth->bh"`` for additive).
    """
    m_local = jnp.max(scores, axis=-1)
    m = jax.lax.pmax(m_local, axis_name)
    e = jnp.exp(scores - m[..., None])
    s = jax.lax.psum(jnp.sum(e, axis=-1), axis_name)
    n = jnp.einsum(contract, e, values)
    ctx = jax.lax.psum(n, axis_name) / jnp.maximum(s, 1e-30)[..., None]
    return ctx, s, m


def sp_dot_attention(
    q: jnp.ndarray,            # (B, Lq, D) queries (full, replicated on axis)
    k: jnp.ndarray,            # (B, Tl, D) LOCAL key block
    v: jnp.ndarray,            # (B, Tl, Dv) LOCAL value block
    *,
    axis_name: str,
    mask: Optional[jnp.ndarray] = None,   # (B, Tl) True = attend
) -> jnp.ndarray:
    """Scaled dot-product cross-attention over a time-sharded memory.

    Call inside ``shard_map`` with K/V sharded on ``axis_name``; returns
    the (B, Lq, Dv) context, identical on every shard of the axis.
    Multi-head callers fold heads into the batch dim (see
    ``sp_multihead_cross_attention``).
    """
    scores = jnp.einsum(
        "bqd,btd->bqt", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / jnp.sqrt(jnp.float32(q.shape[-1]))
    if mask is not None:
        scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    ctx, _, _ = _combine(scores, v.astype(jnp.float32), axis_name,
                         "bqt,btd->bqd")
    return ctx.astype(v.dtype)


def sp_additive_attention(
    q_proj: jnp.ndarray,            # (B, A) projected decoder query
    memory: jnp.ndarray,            # (B, Tl, H) LOCAL memory block
    projected_memory: jnp.ndarray,  # (B, Tl, A) LOCAL W_m . memory block
    score_v: jnp.ndarray,           # (A,) score vector
    *,
    axis_name: str,
    mask: Optional[jnp.ndarray] = None,   # (B, Tl) True = attend
) -> jnp.ndarray:
    """Additive (Bahdanau) attention over a time-sharded memory — the
    SP form of ``ops.attention.AdditiveAttention``'s score -> softmax ->
    context chain (same f32 casts), for the attention-LSTM decoder.
    Returns the (B, H) context."""
    scores = jnp.einsum(
        "bta,a->bt",
        jnp.tanh(projected_memory.astype(jnp.float32)
                 + q_proj.astype(jnp.float32)[:, None, :]),
        score_v.astype(jnp.float32),
    )
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    ctx, _, _ = _combine(scores, memory.astype(jnp.float32), axis_name,
                         "bt,bth->bh")
    return ctx.astype(memory.dtype)


def ring_cross_attention(
    q: jnp.ndarray,            # (B, Lq, D)
    k: jnp.ndarray,            # (B, Tl, D) LOCAL block
    v: jnp.ndarray,            # (B, Tl, Dv) LOCAL block
    *,
    axis_name: str,
    mask: Optional[jnp.ndarray] = None,   # (B, Tl)
) -> jnp.ndarray:
    """Ring-scheduled equivalent of ``sp_dot_attention``: K/V blocks hop
    around the axis via ``ppermute`` while each device folds one block per
    hop into its running (max, sum, numerator) — communication overlaps
    compute hop by hop and no collective touches the full T.  Numerics
    match the combine version exactly (same f32 streaming-softmax merge);
    preferred when even the psum of the (B, Lq, Dv) numerator is a
    concern, or as the building block for future Q-sharded self-attention
    over long streams."""
    n_shards = _axis_size(axis_name)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    qf = q.astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))

    def block_stats(kb, vb, mb):
        s = jnp.einsum("bqd,btd->bqt", qf, kb.astype(jnp.float32)) * scale
        if mb is not None:
            s = jnp.where(mb[:, None, :], s, _NEG_INF)
        m = jnp.max(s, axis=-1)                               # (B, Lq)
        e = jnp.exp(s - m[..., None])
        return m, jnp.sum(e, axis=-1), jnp.einsum(
            "bqt,btd->bqd", e, vb.astype(jnp.float32))

    def merge(acc, blk):
        m0, s0, n0 = acc
        m1, s1, n1 = blk
        m = jnp.maximum(m0, m1)
        a0, a1 = jnp.exp(m0 - m), jnp.exp(m1 - m)
        return m, s0 * a0 + s1 * a1, n0 * a0[..., None] + n1 * a1[..., None]

    mask_f = (jnp.ones(k.shape[:2], jnp.float32) if mask is None
              else mask.astype(jnp.float32))
    acc = block_stats(k, v, mask_f > 0.5)
    kb, vb, mb = k, v, mask_f
    for _ in range(n_shards - 1):
        kb, vb, mb = (jax.lax.ppermute(x, axis_name, perm)
                      for x in (kb, vb, mb))
        acc = merge(acc, block_stats(kb, vb, mb > 0.5))
    m, s, n = acc
    ctx = n / jnp.maximum(s, 1e-30)[..., None]
    return ctx.astype(v.dtype)


def sp_multihead_cross_attention(
    q: jnp.ndarray,            # (B, Lq, nH, Dh)
    k: jnp.ndarray,            # (B, Tl, nH, Dh) LOCAL block
    v: jnp.ndarray,            # (B, Tl, nH, Dh) LOCAL block
    *,
    axis_name: str,
    mask: Optional[jnp.ndarray] = None,   # (B, Tl)
    ring: bool = False,
) -> jnp.ndarray:
    """Multi-head wrapper: folds heads into batch, runs the SP attention,
    unfolds.  Same layout as ``nn.MultiHeadDotProductAttention``'s
    post-projection q/k/v."""
    b, lq, nh, dh = q.shape
    tl = k.shape[1]
    fold = lambda x, L: x.transpose(0, 2, 1, 3).reshape(b * nh, L, dh)
    qf, kf, vf = fold(q, lq), fold(k, tl), fold(v, tl)
    mf = None if mask is None else jnp.repeat(mask, nh, axis=0)
    fn = ring_cross_attention if ring else sp_dot_attention
    ctx = fn(qf, kf, vf, axis_name=axis_name, mask=mf)
    return ctx.reshape(b, nh, lq, dh).transpose(0, 2, 1, 3)


def sp_cross_attention_jit(mesh: Mesh, ring: bool = False):
    """Convenience global-array form: shard_map-wrap ``sp_dot_attention``
    over ``mesh`` — q sharded on batch only, k/v on (batch, time); the
    returned callable consumes/produces global arrays, so callers can use
    it without writing shard_map themselves."""
    fn = partial(ring_cross_attention if ring else sp_dot_attention,
                 axis_name=MODEL_AXIS)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS, MODEL_AXIS),
                  P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS)),
        out_specs=P(DATA_AXIS),
        # The ring's hop-accumulated context is replicated over the model
        # axis by construction (every device folds every block), but that
        # is invisible to the static varying-axes check — the combine
        # version's psum proves it, the ring's ppermute loop cannot.
        **{_SM_CHECK_KW: not ring},
    )
    def mapped(q, k, v, mask):
        return fn(q, k, v, mask=mask)

    jitted = jax.jit(mapped)

    def call(q, k, v, mask=None):
        if mask is None:
            mask = jnp.ones(k.shape[:2], dtype=bool)
        return jitted(q, k, v, mask)

    return call
