"""Context-parallel (time-sharded) training step compilation.

``context_parallel_jit`` is the GSPMD companion to the explicit
``parallel.sequence`` ops: the train step is jitted with long feature
modalities sharded ``(batch -> data, time -> model)`` and the encoder
memory constrained to stay time-sharded, so XLA's partitioner keeps the
``(B, T, H)`` memory distributed over the ``model`` axis and inserts the
cross-attention / pooling / gradient collectives itself.  Gradient
bookkeeping (which parameter grads are partial sums over the time axis
vs already-replicated) is exactly what GSPMD's global-view semantics
solve automatically — the reason this path is annotation-driven while
``parallel/sequence.py`` keeps the explicit shard_map form for
guaranteed-peak-memory attention.

Usage (ActivityNet-length streams, driver config 5):

    mesh = make_mesh(model_parallel=k)            # (data, model=k)
    step = context_parallel_jit(
        make_xe_step(model, S), mesh,
        feats_time_sharded=(True, False))          # I3D stream, clip feat

with the model built with ``time_shard_memory(mesh)`` as its
``encode_constraint`` so the fused memory keeps the time sharding through
the decoder blocks.

Reference counterpart: none — the reference mean-pools time away before
its decoder and has no sequence parallelism (SURVEY.md §5 long-context);
this module is the rebuild's CP answer for the config-5 scale.
Equivalence to the unsharded step is pinned by
tests/test_sequence_parallel.py::test_context_parallel_xe_step_*.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS, batch_sharding, replicated_sharding


def time_shard_memory(mesh: Mesh) -> Callable:
    """``encode_constraint`` for CaptionModel: keep the encoder memory
    ``(B, T, H)`` sharded (batch over data, time over model) through the
    decoder's cross-attention instead of letting the partitioner gather
    it onto every device."""
    sh = NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS, None))

    def constrain(memory):
        return jax.lax.with_sharding_constraint(memory, sh)

    return constrain


def context_parallel_jit(
    step_fn: Callable,
    mesh: Mesh,
    feats_time_sharded: Sequence[bool],
    batch_argnums=(1,),
    feats_argnum: int = 1,
    donate_argnums=(0,),
) -> Callable:
    """jit ``step_fn`` with DP + CP shardings.

    Like ``data_parallel_jit`` (state replicated, batch args sharded on
    ``data``, outputs replicated) except the ``feats_argnum`` argument is
    a per-modality list whose entries with ``feats_time_sharded[m]`` True
    are additionally sharded over ``model`` on their time axis.  Short
    modalities (e.g. a single clip-level vector) stay time-replicated.

    Divisibility: each sharded modality's T — and, when the model uses
    ``time_shard_memory``, the *concatenated* memory T (sum of all
    modality T's) — must divide the model-axis size; pad the feature
    stream to a multiple otherwise (long-stream loaders already pad to
    fixed T).  Violations fail at compile time with the offending shape.
    """
    b = batch_sharding(mesh)
    r = replicated_sharding(mesh)
    t = NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS, None))
    feats_sh = [t if s else b for s in feats_time_sharded]

    def in_sh(n):
        out = []
        for i in range(n):
            if i == feats_argnum:
                out.append(feats_sh)
            elif i in batch_argnums:
                out.append(b)
            else:
                out.append(r)
        return tuple(out)

    compiled = {}

    def wrapped(*args):
        fn = compiled.get(len(args))
        if fn is None:
            fn = jax.jit(
                step_fn,
                in_shardings=in_sh(len(args)),
                out_shardings=r,
                donate_argnums=donate_argnums,
            )
            compiled[len(args)] = fn
        return fn(*args)

    return wrapped
