"""Data-parallel compilation of train/eval steps.

``data_parallel_jit`` turns a pure step function into its SPMD form: state
replicated, batch sharded over the ``data`` mesh axis, outputs replicated.
XLA's partitioner lowers the replicated-param gradient sum to an ICI
all-reduce — the explicit TPU-native equivalent of the reference's hidden
NCCL all-reduce inside ``DataParallel`` (SURVEY.md §2 parallelism table).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh

from .mesh import batch_sharding, replicated_sharding


def data_parallel_jit(
    step_fn: Callable,
    mesh: Mesh,
    batch_argnums=(1,),
    donate_argnums=(0,),
    out_batch_tree=None,
    donate_batch: bool = False,
) -> Callable:
    """jit ``step_fn`` with DP shardings.

    Args:
      step_fn: pure function; arg 0 is the (replicated) train state pytree,
        args in ``batch_argnums`` are batch pytrees (leading axis = batch),
        everything else (rng, scalars) is replicated.
      batch_argnums: positional args whose array leaves shard on ``data``.
      donate_argnums: donated args (the state, for in-place HBM update).
      out_batch_tree: optional pytree-prefix of booleans over the output,
        True where an output keeps the batch axis (e.g. sampled tokens);
        by default ALL outputs are constrained replicated — letting XLA
        choose (out_shardings=None) can leave updated params sharded,
        which would silently break checkpointing and later steps.
      donate_batch: also donate every ``batch_argnums`` argument.  XLA
        (Kernel-path audit, ISSUE 6: ``--decode_kernel pallas`` changes
        nothing here — the fused decode cell consumes the same replicated
        params and while-loop-carried decode buffers as the reference
        cell, allocates its working set as kernel-managed VMEM blocks,
        and adds no donatable argument; the state-donation contract below
        is kernel-independent, test-pinned via parallel/dryrun.py.)
        donation is input->output ALIASING, so this only frees HBM when
        the program emits a batch-shaped output the input can alias onto
        (``out_batch_tree`` steps: token transforms, in-place table
        writes); a donation with no matching output is skipped with a
        warning and the buffer survives.  The shipped train steps emit
        only replicated state/metrics, so they donate the state alone
        (their largest live buffers) and leave this False.  Never set it
        for callers that replay the same arrays (bench loops) or feed a
        later program from the same buffer (the rollout's feats, which
        the grad step still needs).
    """
    b = batch_sharding(mesh)
    r = replicated_sharding(mesh)
    donated = tuple(donate_argnums) + (
        tuple(batch_argnums) if donate_batch else ())
    # A single sharding per argument/output broadcasts over its pytree.
    in_sh = lambda n: tuple(
        b if i in batch_argnums else r for i in range(n)
    )
    if out_batch_tree is None:
        out_sh = r
    else:
        out_sh = jax.tree_util.tree_map(
            lambda keep: b if keep else r, out_batch_tree
        )

    compiled = {}

    def jit_for(nargs: int):
        """The underlying ``jax.jit`` object for an ``nargs``-argument
        call — exposed so the donation audit
        (``analysis/donation.py``) can ``.lower()`` the REAL program and
        verify every donated leaf aliases an output, instead of
        re-deriving the sharding/donation spec by hand."""
        fn = compiled.get(nargs)
        if fn is None:
            fn = jax.jit(
                step_fn,
                in_shardings=in_sh(nargs),
                out_shardings=out_sh,
                donate_argnums=tuple(i for i in donated if i < nargs),
            )
            compiled[nargs] = fn
        return fn

    def wrapped(*args):
        return jit_for(len(args))(*args)

    wrapped.jit_for = jit_for
    return wrapped


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up (DCN): wraps ``jax.distributed.initialize``.

    On single-host runs (the common case, and the only one testable here)
    this is a no-op.  On a pod, each host calls this before any jax op;
    collectives then span hosts transparently through the same mesh.
    """
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
