"""Parallelism layer: device mesh, sharding rules, data-parallel steps.

The reference's entire distribution story was single-node
``torch.nn.DataParallel`` with NCCL hidden inside torch (SURVEY.md §2
"Parallelism strategy inventory").  Here distribution is first-class and
TPU-native: a ``jax.sharding.Mesh`` over all devices, ``NamedSharding``
annotations on batch inputs, replicated parameters, and XLA-inserted
``all-reduce`` over ICI/DCN for gradients — the pjit/GSPMD idiom rather
than a translation of NCCL calls.
"""

from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    host_local_slice,
    make_mesh,
    replicated_sharding,
    shard_batch_arrays,
)
from .cp import context_parallel_jit, time_shard_memory
from .dp import data_parallel_jit, distributed_init
from .sequence import (
    ring_cross_attention,
    sp_additive_attention,
    sp_cross_attention_jit,
    sp_dot_attention,
    sp_multihead_cross_attention,
    time_sharding,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "batch_sharding",
    "context_parallel_jit",
    "data_parallel_jit",
    "distributed_init",
    "host_local_slice",
    "make_mesh",
    "replicated_sharding",
    "ring_cross_attention",
    "shard_batch_arrays",
    "sp_additive_attention",
    "sp_cross_attention_jit",
    "sp_dot_attention",
    "sp_multihead_cross_attention",
    "time_shard_memory",
    "time_sharding",
]
