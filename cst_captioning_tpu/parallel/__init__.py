"""Parallelism layer: device mesh, sharding rules, data-parallel steps.

The reference's entire distribution story was single-node
``torch.nn.DataParallel`` with NCCL hidden inside torch (SURVEY.md §2
"Parallelism strategy inventory").  Here distribution is first-class and
TPU-native: a ``jax.sharding.Mesh`` over all devices, ``NamedSharding``
annotations on batch inputs, replicated parameters, and XLA-inserted
``all-reduce`` over ICI/DCN for gradients — the pjit/GSPMD idiom rather
than a translation of NCCL calls.
"""

from .mesh import (
    DATA_AXIS,
    batch_sharding,
    host_local_slice,
    make_mesh,
    replicated_sharding,
    shard_batch_arrays,
)
from .dp import data_parallel_jit, distributed_init

__all__ = [
    "DATA_AXIS",
    "batch_sharding",
    "data_parallel_jit",
    "distributed_init",
    "host_local_slice",
    "make_mesh",
    "replicated_sharding",
    "shard_batch_arrays",
]
