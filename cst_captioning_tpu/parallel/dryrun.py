"""Shared data-parallel smoke pipeline: XE -> rollout -> RL on a DP mesh.

One implementation of the "real model across a mesh" exercise, consumed by
both ``__graft_entry__.dryrun_multichip`` (the driver's multichip artifact)
and ``tests/test_real_model_mesh.py`` (the CI equivalence test), so the
wiring the driver grades and the wiring CI covers cannot drift apart
(VERDICT.md round 1, weak #2).

Shapes are tiny on purpose — this validates sharding/collective wiring and
global-view determinism, not speed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

VOCAB = 50
HIDDEN = 16
SEQ_PER_IMG = 2
MAX_LEN = 8
FEAT_SHAPES = [(4, 12), (1, 6)]


def run_dp_pipeline(n_devices: int, batch_size: int | None = None,
                    xe_steps: int = 1,
                    decode_kernel: str = "reference",
                    _attempt: int = 0) -> dict:
    """Run XE steps, a rollout with host round-trip, and an RL grad step,
    all sharded over an ``n_devices``-wide data-parallel mesh.

    ``batch_size`` defaults to ``2 * n_devices``; pass an explicit value
    divisible by every device count under comparison when checking 1-vs-N
    equivalence.  Returns host copies of everything a caller might assert
    on: xe_losses, sampled/greedy tokens, rl_loss, final params.

    ``decode_kernel="pallas"`` routes every rollout through the fused
    Pallas decode cell (ops/pallas_decode_cell.py) — the donation-audit
    surface for the kernel path: the pallas step introduces NO new
    donatable arguments (its operands are the same while-loop carries and
    replicated params as the reference cell; per-block VMEM buffers are
    kernel-managed), so the state-donation / donate_batch contract of
    ``data_parallel_jit`` is identical under either kernel — pinned by
    tests/test_pallas_decode_cell.py on this helper.
    """
    from cst_captioning_tpu.models import CaptionModel
    from cst_captioning_tpu.parallel import (
        data_parallel_jit,
        make_mesh,
        replicated_sharding,
        shard_batch_arrays,
    )
    from cst_captioning_tpu.training.state import create_train_state, make_optimizer
    from cst_captioning_tpu.training.steps import (
        make_rl_grad_step,
        make_rollout,
        make_xe_step,
    )

    B = batch_size if batch_size is not None else n_devices * 2
    S, L, V = SEQ_PER_IMG, MAX_LEN, VOCAB

    devices = jax.devices()[:n_devices]
    assert len(devices) == n_devices, (
        f"need {n_devices} devices, have {len(devices)}"
    )
    mesh = make_mesh(devices)

    model = CaptionModel(
        vocab_size=V, embed_size=HIDDEN, hidden_size=HIDDEN,
        attn_size=HIDDEN, num_layers=1, use_attention=True, dropout_rate=0.5,
        decode_kernel=decode_kernel,
    )
    tx, _ = make_optimizer(learning_rate=1e-3, grad_clip=5.0)
    state = create_train_state(
        model, jax.random.PRNGKey(0), FEAT_SHAPES, L, S, tx, batch_size=B
    )
    state = jax.device_put(state, replicated_sharding(mesh))

    rng = np.random.default_rng(0)
    feats = shard_batch_arrays(mesh, [
        jnp.asarray(rng.standard_normal((B,) + s), jnp.float32)
        for s in FEAT_SHAPES
    ])
    labels = shard_batch_arrays(
        mesh, jnp.asarray(rng.integers(1, V, (B * S, L)), jnp.int32)
    )
    weights = shard_batch_arrays(mesh, jnp.ones((B * S,), jnp.float32))
    advantage_host = jnp.asarray(rng.standard_normal(B * S), jnp.float32)
    key = jax.random.PRNGKey(1)

    # -- XE steps ----------------------------------------------------------
    xe = data_parallel_jit(make_xe_step(model, S), mesh,
                           batch_argnums=(1, 2, 3), donate_argnums=(0,))
    # Losses stay ON DEVICE until the single batched fetch in the return
    # below: per-step float() scalar fetches are the pattern this
    # session's native CPU stack nondeterministically garbles to 0.0
    # (RESILIENCE.md — the same reason the trainer's control plane runs
    # on host-side step integers), and this helper's results are
    # asserted bit-for-bit by tests/test_real_model_mesh.py.
    xe_losses = []
    for i in range(xe_steps):
        state, metrics = xe(state, feats, labels, weights,
                            jax.random.fold_in(key, i))
        xe_losses.append(metrics["loss"])

    # -- CST step: device rollout -> host advantage -> device grad ---------
    rollout = data_parallel_jit(
        make_rollout(model, L, S), mesh,
        batch_argnums=(1,), donate_argnums=(), out_batch_tree=(True, True),
    )
    sampled, greedy = rollout(state.params, feats, key)
    # Mimic the trainer's reward path: tokens leave the device for string
    # scoring, then return as a fresh sharded array.
    sampled_host = np.asarray(jax.device_get(sampled))
    greedy_host = np.asarray(jax.device_get(greedy))
    sampled = shard_batch_arrays(mesh, jnp.asarray(sampled_host))
    advantage = shard_batch_arrays(mesh, advantage_host)

    rl = data_parallel_jit(make_rl_grad_step(model, S), mesh,
                           batch_argnums=(1, 2, 3), donate_argnums=(0,))
    state, rl_metrics = rl(state, feats, sampled, advantage, key)

    # -- fused on-device reward step (--device_rewards) across the mesh ----
    from cst_captioning_tpu.training.device_rewards import build_device_tables
    from cst_captioning_tpu.training.steps import make_fused_cst_step

    refs = {
        f"v{i}": [f"w{1 + (i + j) % (VOCAB - 1)} w{1 + (i * j) % (VOCAB - 1)}"
                  for j in range(3)]
        for i in range(B)
    }
    # word_to_ix must mirror the token ids the model emits — without it the
    # encoder would assign ids in encounter order and hyp<->ref matching
    # would be scrambled.
    corpus, tables, _ = build_device_tables(
        refs, {f"w{k}": k for k in range(1, VOCAB)}
    )
    fused = data_parallel_jit(
        make_fused_cst_step(model, L, S, corpus, tables), mesh,
        batch_argnums=(1, 2), donate_argnums=(0,),
    )
    video_ix = shard_batch_arrays(mesh, jnp.arange(B, dtype=jnp.int32))
    state, fused_metrics = fused(state, feats, video_ix, key)

    # -- sequence/context parallelism: time-sharded cross-attention --------
    # A second mesh over the SAME devices with a model axis carries the
    # long-stream path (driver config 5 shapes, scaled down): encoder
    # memory (B, T, H) lives time-sharded and the decoder's cross-
    # attention combines blockwise — no device ever holds full T.
    sp_ctx_sum = None
    if n_devices % 2 == 0 and n_devices >= 2:
        from cst_captioning_tpu.parallel.sequence import (
            sp_cross_attention_jit,
            time_sharding,
        )

        sp_mesh = make_mesh(devices, model_parallel=2)
        t_long = 64
        bq = sp_mesh.shape["data"] * 2
        kv = jnp.asarray(
            rng.standard_normal((bq, t_long, HIDDEN)), jnp.float32)
        kv = jax.device_put(kv, time_sharding(sp_mesh))
        qq = jnp.asarray(rng.standard_normal((bq, 4, HIDDEN)), jnp.float32)
        ctx = sp_cross_attention_jit(sp_mesh)(qq, kv, kv)
        sp_ctx_sum = jnp.sum(ctx)

    # One batched device_get of every scalar, not N float() fetches —
    # see the xe_losses comment above.
    scalars = jax.device_get({
        "xe_losses": jnp.stack(xe_losses),
        "rl_loss": rl_metrics["loss"],
        "fused_loss": fused_metrics["loss"],
        "fused_reward": fused_metrics["reward"],
        "sp_ctx_sum": (jnp.zeros(()) if sp_ctx_sum is None else sp_ctx_sum),
    })
    # This session's native stack occasionally garbles one pipeline
    # invocation's device scalars to 0.0 (the RESILIENCE.md caveat;
    # observed ~1-in-3 per invocation some days, and NOT sticky — an
    # adjacent invocation in the same process is fine).  A random-init
    # model's XE loss is never exactly 0.0, so an all-zero loss curve is
    # a reliable garble signature (resilience/garble.py — the shared
    # detector the serving engine's self-healing scheduler uses too).
    # Fresh re-fetches of re-stacked arrays still read 0.0 (the zeros are
    # device-side), so the recovery is a bounded DETERMINISTIC re-run of
    # the whole pipeline: every input is seeded, so a clean retry returns
    # exactly what a clean first attempt would have — a real,
    # reproducible zero-loss regression would fail all retries and still
    # surface.
    from cst_captioning_tpu.resilience.garble import all_zero

    if all_zero(scalars["xe_losses"]):
        if _attempt < 2:
            print(f"run_dp_pipeline: device scalars garbled to all-0.0 "
                  f"(native-stack caveat, RESILIENCE.md); deterministic "
                  f"re-run {_attempt + 1}/2", flush=True)
            return run_dp_pipeline(n_devices, batch_size, xe_steps,
                                   decode_kernel, _attempt=_attempt + 1)
        print("run_dp_pipeline: all-0.0 scalars persisted across retries "
              "— reporting as computed", flush=True)
    return {
        "mesh_shape": dict(mesh.shape),
        "xe_losses": [float(v) for v in scalars["xe_losses"]],
        "sampled": sampled_host,
        "greedy": greedy_host,
        "rl_loss": float(scalars["rl_loss"]),
        "fused_loss": float(scalars["fused_loss"]),
        "fused_reward": float(scalars["fused_reward"]),
        "sp_ctx_sum": (None if sp_ctx_sum is None
                       else float(scalars["sp_ctx_sum"])),
        # How many garble retries this result cost (0 on a clean first
        # attempt, bounded at 2) — surfaced so callers/tests can assert
        # the retry ladder was respected instead of inferring it from
        # stdout (ISSUE 20 satellite).
        "garble_retries": _attempt,
        "params": jax.device_get(state.params),
    }
