"""Device mesh + sharding rules — the TPU replacement for DataParallel.

Design (SURVEY.md §5 "Distributed communication backend"):

- one logical ``data`` axis spanning every device (all chips of a slice,
  all slices of a pod); the model is small (~10–50M params) so parameters
  are replicated and only the batch is sharded.  A ``model`` axis is
  plumbed (``make_mesh(model_parallel=k)``) but unused by default — the
  mesh shape is the single point of change if TP is ever wanted;
- batch arrays are sharded on their leading axis with ``NamedSharding``;
  everything else (params, opt state, rng) is replicated;
- gradients need no hand-written psum: with sharded inputs + replicated
  params, XLA's SPMD partitioner inserts the ICI all-reduce during
  ``jit`` compilation (the pjit/GSPMD idiom, not a NCCL translation).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: int = 1,
) -> Mesh:
    """Mesh over ``devices`` (default: all) with axes (data, model).

    ``model_parallel=1`` (default) gives pure data parallelism; the model
    axis exists so shardings referencing it stay valid if it is widened.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over the data axis; rest replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_arrays(mesh: Mesh, tree):
    """device_put every array leaf with its leading axis sharded on ``data``.

    Feature lists, label matrices and weight vectors all share the batch
    leading dim, so one rule covers the whole batch pytree.
    """
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)


def host_local_slice(global_batch: int, process_index: Optional[int] = None,
                     process_count: Optional[int] = None) -> slice:
    """This host's contiguous rows of a globally-assembled batch.

    Multi-host JAX requires each process to provide its addressable shard;
    loaders build per-host batches of ``global_batch / process_count`` rows
    (see data.loader's process-strided video sharding) and this maps a
    host to its row range when a global batch is materialized instead.
    """
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if global_batch % pc != 0:
        raise ValueError(f"global batch {global_batch} not divisible by {pc} hosts")
    per = global_batch // pc
    return slice(pi * per, (pi + 1) * per)
