"""Checkpoint integrity: per-step manifests, verify-on-restore, walk-back.

Orbax's own commit is atomic against concurrent READERS (tmp dir + rename),
but "the newest step directory exists" still does not prove the payload is
whole: a power cut or SIGKILL can journal the rename without all data
blocks, and a torn file only surfaces as an opaque deserialization error at
the worst possible time — restore, inside an unattended resume loop.

This module closes that gap with a content manifest written AFTER the orbax
commit: ``<step_dir>/manifest.json`` lists every payload file with its size
and SHA-256.  Restore-time verification then has three honest outcomes:

- ``"verified"``   — manifest present, every file matches;
- ``"corrupt"``    — manifest present, a file is missing/resized/altered,
  OR the manifest itself is absent while the write marker says one was
  started (the save was torn between commit and manifest);
- ``"unverified"`` — no manifest and no marker: a checkpoint from before
  this layer existed.  Accepted (legacy compatibility) with a log line.

``CheckpointManager`` walks back to the newest non-corrupt step when the
latest one fails verification, so the scale-chain's "auto-resume from
newest" can never restore a torn state.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Dict, Optional, Tuple

log = logging.getLogger("cst_captioning_tpu.resilience.integrity")

MANIFEST_NAME = "manifest.json"
#: Written (fsync'd) BEFORE hashing starts, removed only by the manifest's
#: atomic replace: its presence without a manifest proves a torn save.
_MARKER_NAME = ".manifest.writing"


def manifest_path(step_dir: str) -> str:
    return os.path.join(step_dir, MANIFEST_NAME)


def _iter_payload_files(step_dir: str):
    """Every regular file under ``step_dir`` except the manifest artifacts,
    as (relpath, abspath), in sorted order for stable manifests."""
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            rel = os.path.relpath(os.path.join(root, name), step_dir)
            if rel in (MANIFEST_NAME, _MARKER_NAME):
                continue
            out.append((rel, os.path.join(root, name)))
    return sorted(out)


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def write_manifest(step_dir: str) -> Dict[str, Dict]:
    """Checksum every payload file of a committed step and atomically write
    the manifest.  Crash-ordering: the marker is fsync'd first, so a save
    killed mid-hash leaves marker-without-manifest (= corrupt, walk back),
    never a silently manifest-less "legacy" step."""
    marker = os.path.join(step_dir, _MARKER_NAME)
    with open(marker, "w") as f:
        f.flush()
        os.fsync(f.fileno())
    try:
        files = {}
        for rel, path in _iter_payload_files(step_dir):
            files[rel] = {"bytes": os.path.getsize(path),
                          "sha256": _sha256(path)}
        manifest = {"version": 1, "files": files}
        atomic_json_write(manifest_path(step_dir), manifest,
                          indent=1, sort_keys=True)
    except BaseException:
        # A CLEAN failure (caught and handled by the caller) must remove
        # the marker too (atomic_json_write already cleaned its tmp file):
        # the checkpoint itself is whole, and marker-without-manifest
        # would otherwise read as "torn" and get a perfectly good step
        # quarantined on the next start.  Only a hard crash mid-hash —
        # where no cleanup can run — leaves the marker, which is exactly
        # the case it exists for.
        try:
            os.unlink(marker)
        except OSError:
            pass
        raise
    try:
        os.unlink(marker)
    except OSError:
        pass
    fsync_dir(step_dir)
    return manifest


def verify_step_dir(step_dir: str, level: str = "full") -> Tuple[str, str]:
    """-> (status, detail) with status in {verified, corrupt, unverified}.

    ``level="full"`` re-hashes every payload file against the manifest;
    ``level="stat"`` stops at existence + byte sizes — sufficient for the
    torn-write failure mode (truncation / missing files) at stat cost,
    used by the startup quarantine scan so healthy multi-GB checkpoints
    are not fully re-read on every manager construction.  Restore-time
    verification always runs full."""
    mpath = manifest_path(step_dir)
    if not os.path.exists(mpath):
        if os.path.exists(os.path.join(step_dir, _MARKER_NAME)):
            return "corrupt", "manifest write was torn (marker present)"
        return "unverified", "no manifest (pre-integrity-layer checkpoint)"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, ValueError, KeyError) as e:
        return "corrupt", f"unreadable manifest: {e}"
    on_disk = dict(_iter_payload_files(step_dir))
    for rel, want in files.items():
        path = on_disk.get(rel)
        if path is None:
            return "corrupt", f"missing file {rel!r}"
        size = os.path.getsize(path)
        if size != want["bytes"]:
            return ("corrupt",
                    f"{rel!r} is {size} bytes, manifest says {want['bytes']}")
        if level == "full" and _sha256(path) != want["sha256"]:
            return "corrupt", f"{rel!r} content checksum mismatch"
    extra = set(on_disk) - set(files)
    if extra:
        # Extra files are tolerated (orbax may add metadata across
        # versions) but surfaced — they are not covered by the checksum.
        log.debug("step %s has %d file(s) outside its manifest: %s",
                  step_dir, len(extra), sorted(extra)[:3])
    return "verified", f"{len(files)} file(s) match"


def atomic_json_write(path: str, doc, **dump_kwargs) -> None:
    """The repo's one durable-JSON discipline: write to ``path + ".tmp"``,
    fsync the data, atomically rename over ``path``, then fsync the
    directory so a crash can't lose the rename either.  A reader therefore
    sees the old complete document or the new complete document, never a
    torn one — the contract infos.json, telemetry.json, heartbeat.json,
    and the step manifests all rely on.  ``dump_kwargs`` pass through to
    :func:`json.dump`.  On failure the tmp file is removed and the
    published document is untouched."""
    path = os.path.abspath(path)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, **dump_kwargs)
            # fsync before rename: a host crash can journal the rename
            # without the data, leaving an EMPTY file — worse than the
            # stale one the rename replaced.
            f.flush()
            os.fsync(f.fileno())
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def durable_rename(src: str, dst: str) -> None:
    """The repo's one durable-rename discipline: ``os.replace`` then
    fsync the destination directory, so a crash can't journal the
    rename away.  Every rename that PUBLISHES a durable artifact (part
    rotation, step promotion, quarantine moves) must go through here —
    a bare ``os.replace`` persists the data blocks but can lose the
    directory entry, which reads back as the file never existing."""
    os.replace(src, dst)
    fsync_dir(os.path.dirname(os.path.abspath(dst)))


def fsync_dir(path: str) -> None:
    """Persist directory-entry changes (renames, creates).  Best-effort:
    some filesystems refuse O_RDONLY-fsync on directories; the data-file
    fsyncs already happened, so a refusal only loses rename durability."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
