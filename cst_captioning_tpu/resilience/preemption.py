"""Signal-driven checkpoint-and-exit: preemption as a first-class fault.

A scheduler preemption, spot reclaim, or operator ``kill`` delivers
SIGTERM mid-step; without a handler the process dies wherever it stands,
losing up to a full save interval of work and surfacing to the stage
harness as an unclassifiable 143.  The preemption layer turns that into
"resumed with at most one step of lost work":

- :class:`PreemptionHandler` (installed by ``train.py`` before the slow
  Trainer init) catches SIGTERM/SIGINT and only sets a flag — the handler
  body must stay async-signal-safe-ish: no locks (a signal interrupting
  the main thread inside the metrics registry's lock would deadlock on
  ``inc``), no logging (same story for the logging module lock), no
  allocation-heavy work;
- the trainer loop checks the flag at every step boundary, forces a
  verified checkpoint save through the normal manifest/integrity path,
  stamps the preemption counters into telemetry, and raises
  :class:`PreemptedExit`;
- ``train.py`` maps :class:`PreemptedExit` to
  :data:`~.exitcodes.EXIT_PREEMPTED` (75, ``EX_TEMPFAIL``), which
  ``scripts/scale_chain.py`` classifies as "checkpoint advanced, restart
  immediately" rather than burning a no-progress attempt.

SIGINT keeps its interactive contract: the FIRST Ctrl-C requests the same
graceful checkpoint-and-exit, and the handler then restores the previous
SIGINT disposition so a second Ctrl-C is a hard ``KeyboardInterrupt`` for
an operator who really means stop-now.  Repeated SIGTERMs are absorbed
(counted) — a scheduler re-sending TERM during the grace window must not
kill the save it is waiting for; the hard stop is its SIGKILL.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Dict, Optional


class PreemptedExit(RuntimeError):
    """Raised by the trainer at the step boundary that honors a preemption
    signal; ``train.py`` maps it to ``exitcodes.EXIT_PREEMPTED``."""

    def __init__(self, step: int, signal_name: str, saved: bool):
        super().__init__(
            f"preempted by {signal_name} at step {step} "
            f"({'checkpoint saved' if saved else 'checkpoint already current'})")
        self.step = int(step)
        self.signal_name = signal_name
        self.saved = bool(saved)


class PreemptionHandler:
    """SIGTERM/SIGINT -> checkpoint-requested flag (main-thread install).

    ``requested`` is the only thing hot paths read (one attribute load per
    step boundary).  Signal counts accumulate handler-side and are drained
    into the metrics registry by the trainer at safe points
    (``drain_signal_count``) — never from the handler itself, which may be
    interrupting a thread that holds the registry lock.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        # A plain bool, NOT a threading.Event: Event.set() takes the
        # event's non-reentrant lock, and CPython delivers a nested signal
        # at the next bytecode boundary — a second SIGTERM landing while
        # the first handler sits inside set() would re-enter and deadlock
        # the main thread on a lock it already holds, hanging the process
        # until the scheduler's SIGKILL.  GIL-atomic attribute writes need
        # no lock at all.  Mechanized: cstlint:signal-safe-handler walks
        # every function reachable from a signal.signal registration and
        # rejects Event/Lock ops, logging, and print.
        self._requested = False
        self.signal_name: Optional[str] = None
        self.signal_monotonic: Optional[float] = None
        self.signal_count = 0
        self._drained = 0
        self._prev: Dict[int, object] = {}

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "PreemptionHandler":
        """Install the handlers; safe no-op (logged to stderr) off the main
        thread, where CPython forbids ``signal.signal``."""
        if threading.current_thread() is not threading.main_thread():
            os.write(2, b"preemption handler not installed: "
                        b"not on the main thread\n")
            return self
        for sig in self.SIGNALS:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self) -> None:
        """Restore the previous dispositions (idempotent)."""
        prev, self._prev = self._prev, {}
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError, TypeError):
                pass

    # -- state -------------------------------------------------------------

    @property
    def requested(self) -> bool:
        return self._requested

    def drain_signal_count(self) -> int:
        """Signals received since the last drain (for registry counters)."""
        n = self.signal_count - self._drained
        self._drained += n
        return n

    # -- the handler (async-signal context: flag + bookkeeping only) -------

    def _handle(self, signum, frame) -> None:
        self.signal_count += 1
        if not self._requested:
            self.signal_name = signal.Signals(signum).name
            self.signal_monotonic = time.monotonic()
            self._requested = True
        if signum == signal.SIGINT:
            # Second Ctrl-C must be a hard stop: hand SIGINT back to the
            # previous disposition (normally KeyboardInterrupt).
            try:
                signal.signal(
                    signal.SIGINT,
                    self._prev.get(signal.SIGINT, signal.default_int_handler))
            except (ValueError, OSError, TypeError):
                pass
        # Raw fd write, not logging: the interrupted thread may hold the
        # logging lock (watchdog._die has the same rationale).
        try:
            os.write(2, (f"PREEMPT: {self.signal_name or signum} received; "
                         "will checkpoint and exit at the next step "
                         "boundary\n").encode())
        except OSError:
            pass
