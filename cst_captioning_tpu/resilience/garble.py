"""Device-scalar garble signatures + serving health status.

This environment's native stack has a documented, probabilistic defect
(RESILIENCE.md "Environment caveat"): a compiled program's device scalars
are occasionally garbled to exactly ``0.0`` — device-side, not fetch-side,
and not sticky (an adjacent invocation of the same program is clean).
PR 8 detected it ad hoc in ``parallel/dryrun.py`` by the impossible
all-0.0 XE-loss curve; this module is that detector made shared, so the
serving engine's self-healing scheduler and the parallel dry-run pipeline
can never disagree on what "garbled" means.

Two signatures:

- :func:`all_zero` — the generic form: a non-empty batch of values that
  are ALL exactly ``0.0``.  Useful wherever the clean computation provably
  cannot produce an all-zero result (a random-init model's XE loss, a
  log-softmax score row).
- :func:`garbled_decode_slots` — the serving form: a decode chunk's
  fetched ``(tokens, finished)`` pair is IMPOSSIBLE for a live slot when
  the finished flag reads False but every token in the chunk is 0.  Both
  chunk bodies (greedy and beam, ``serving/engine.py``) set ``finished``
  the same step they emit token 0, so a row that emitted only zeros must
  read finished — unless the fetch (or the device buffers behind it) was
  zeroed wholesale, which is exactly the garble's shape.

Detection is cheap host-side numpy on buffers the scheduler already
fetched; nothing here touches a jitted program.  Recovery policy lives
with the caller (``dryrun`` re-runs its seeded pipeline; the serving
engine re-runs the chunk and escalates to an engine rebuild —
RESILIENCE.md "Serving faults").
"""

from __future__ import annotations

from typing import Iterable, List

import numpy as np


class GarbledChunk(RuntimeError):
    """A decode chunk's fetched outputs carry the garble signature.

    Raised by the serving engine's dispatch when recovery is armed;
    ``slots`` names the offending slot indices for the log line.
    """

    def __init__(self, slots: List[int]):
        super().__init__(
            f"decode chunk garbled (impossible all-zero signature) at "
            f"slot(s) {slots}")
        self.slots = list(slots)


def all_zero(values) -> bool:
    """True when ``values`` is non-empty and every element is exactly 0.0.

    The generic garble signature: use only where a clean computation
    provably cannot be all-zero (e.g. random-init XE losses — the
    ``parallel/dryrun.py`` detector this generalizes).
    """
    arr = np.asarray(values)
    return arr.size > 0 and bool(np.all(arr == 0))


def garbled_decode_slots(toks: np.ndarray, fin: np.ndarray,
                         live_slots: Iterable[int]) -> List[int]:
    """Slots whose fetched chunk outputs are impossible for a live row.

    ``toks`` is the chunk's emitted tokens — ``(slots, chunk)`` greedy or
    ``(slots, chunk, k)`` beam; ``fin`` the per-slot reduced finished mask
    (``ops.sampling.finished_mask``); ``live_slots`` the slots holding a
    resident at chunk entry (empty slots legitimately emit zeros forever
    and are never checked).  A live slot with ``fin == False`` and an
    all-zero token chunk violates the chunk-body invariant *emit 0 ⇒
    finished that same step* — the garble signature, per slot.
    """
    bad = []
    for slot in live_slots:
        if not bool(fin[slot]) and all_zero(toks[slot]):
            bad.append(int(slot))
    return bad


def health_status(*, draining: bool, recovering: bool) -> str:
    """The serving health plane's one-word status.

    ``draining`` (a preemption signal was honored; admissions closed)
    dominates; ``recovering`` (a recovery event — retry, rebuild, fault,
    slow chunk — inside the engine's degraded window) reads ``degraded``;
    otherwise ``ok``.  Shared by the engine's ``health()`` and the
    front-end ``{"op": "health"}`` response so the two can't drift.
    """
    if draining:
        return "draining"
    return "degraded" if recovering else "ok"
