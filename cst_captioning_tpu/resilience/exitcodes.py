"""Exit-code taxonomy for the training CLIs and the stage harness.

One table consolidating the process exit codes that used to be scattered
across the repo (train.py's advantage abort, the watchdog's wedge code,
scale_chain's SIGTERM unwind) plus the preemption layer's resumable exit,
with a :func:`classify` helper the harness uses to decide what an exit
MEANS instead of pattern-matching magic numbers at every call site:

========  ==================  ==========  ==================================
code      name                class       meaning
========  ==================  ==========  ==================================
``0``     ok                  ok          stage ran to completion
``1``     failure             fatal       unhandled exception (traceback)
``2``     usage               fatal       CLI/config error (argparse)
``4``     advantage_abort     fatal       negative-advantage window abort
                                          (opt-in; the stage is collapsing,
                                          reconfigure — retrying repeats it)
``75``    preempted           resumable   SIGTERM/SIGINT honored at a step
                                          boundary after a verified
                                          checkpoint save (sysexits.h
                                          ``EX_TEMPFAIL``: transient, retry)
``124``   wedge               wedge       no loop progress within
                                          ``--wedge_timeout`` (coreutils
                                          ``timeout(1)`` convention); resume
                                          once the device heals
``130``   sigint_unwind       fatal       hard operator interrupt (second
                                          Ctrl-C, or no handler installed) —
                                          a human chose to stop the run
``137``   sigkill             resumable   SIGKILL'd externally (scheduler
                                          grace expiry, OOM killer); the
                                          newest checkpoint resumes it
``143``   sigterm_unwind      resumable   SIGTERM death WITHOUT the graceful
                                          handler (eval stages, the harness
                                          itself); checkpoint may lag by up
                                          to one save interval
========  ==================  ==========  ==================================

Any other death-by-signal code (``128 < rc <= 192``, or the negative
``subprocess`` form) classifies as ``resumable`` — external kills prove
nothing about the stage; any other code classifies as ``fatal``.

The RESILIENCE.md exit-code table is sourced from :data:`CODES`
(test-pinned), so docs and code cannot drift.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

# -- the codes (importable constants; keep CODES below in sync) -------------

EXIT_OK = 0
EXIT_FAILURE = 1
EXIT_USAGE = 2               # argparse usage errors
EXIT_ADVANTAGE_ABORT = 4     # --abort_on_negative_advantage_window
EXIT_PREEMPTED = 75          # sysexits.h EX_TEMPFAIL: checkpointed + exited
EXIT_WEDGE = 124             # utils/watchdog.py (coreutils timeout(1))
EXIT_SIGINT = 130            # 128 + SIGINT
EXIT_SIGKILL = 137           # 128 + SIGKILL
EXIT_SIGTERM = 143           # 128 + SIGTERM

# -- classification classes -------------------------------------------------

OK = "ok"                 #: ran to completion
RESUMABLE = "resumable"   #: restart the stage; it resumes from checkpoint
WEDGE = "wedge"           #: resumable once the device/transport heals
FATAL = "fatal"           #: retrying can only hide it; surface instead


class ExitCode(NamedTuple):
    name: str
    category: str
    meaning: str


CODES: Dict[int, ExitCode] = {
    EXIT_OK: ExitCode("ok", OK, "stage ran to completion"),
    EXIT_FAILURE: ExitCode("failure", FATAL,
                           "unhandled exception (traceback)"),
    EXIT_USAGE: ExitCode("usage", FATAL, "CLI/config error (argparse)"),
    EXIT_ADVANTAGE_ABORT: ExitCode(
        "advantage_abort", FATAL,
        "negative-advantage window abort (stage collapsing; reconfigure)"),
    EXIT_PREEMPTED: ExitCode(
        "preempted", RESUMABLE,
        "signal honored at a step boundary after a verified checkpoint"),
    EXIT_WEDGE: ExitCode(
        "wedge", WEDGE,
        "no loop progress within --wedge_timeout (device presumed wedged)"),
    EXIT_SIGINT: ExitCode(
        "sigint_unwind", FATAL,
        "hard operator interrupt (second Ctrl-C / no handler)"),
    EXIT_SIGKILL: ExitCode(
        "sigkill", RESUMABLE,
        "killed externally (scheduler grace expiry, OOM killer)"),
    EXIT_SIGTERM: ExitCode(
        "sigterm_unwind", RESUMABLE,
        "SIGTERM death without the graceful handler"),
}


def normalize(rc: int) -> int:
    """Map ``subprocess``'s negative died-to-signal form (``-15``) onto the
    shell's ``128 + signum`` convention (``143``) so both spellings of the
    same death classify identically."""
    rc = int(rc)
    return 128 - rc if rc < 0 else rc


def classify(rc: int) -> str:
    """-> ``"ok"`` | ``"resumable"`` | ``"wedge"`` | ``"fatal"``."""
    rc = normalize(rc)
    code = CODES.get(rc)
    if code is not None:
        return code.category
    if 128 < rc <= 192:  # died to an uncatalogued signal: external kill
        return RESUMABLE
    return FATAL


def describe(rc: int) -> str:
    """Human one-liner for logs/abort messages: name + meaning when the
    code is catalogued, the classification otherwise."""
    n = normalize(rc)
    code = CODES.get(n)
    if code is not None:
        return f"{code.name}: {code.meaning}"
    if n != rc:
        return f"died to signal {-int(rc)} ({classify(rc)})"
    return f"uncatalogued exit {rc} ({classify(rc)})"
