"""Deterministic fault-injection plans for chaos-testing the trainer.

A :class:`FaultPlan` is parsed from the ``--fault_plan`` CLI flag (or the
``CST_FAULT_PLAN`` environment variable) and threaded EXPLICITLY into the
components that host an injection point — no module-global arming, so two
Trainers in one test process can never leak faults into each other.  Every
injection site follows the same shape::

    if plan is not None and plan.fire("kind", index):
        <raise / corrupt / block>

so a run without ``--fault_plan`` pays exactly one ``is not None`` check
per site, all on the host, never inside a jitted program.

Grammar (comma-separated specs)::

    kind@step=N        fire once when the trainer dispatches step N (0-based)
    kind@batch=N       fire once when the loader assembles batch N (0-based)
    kind@req=N         fire once for the serving engine's Nth submitted
                       request (0-based submission ordinal)
    kind@replica=K     fleet serving only: fire once inside replica K's
                       engine, at that engine's first opportunity for the
                       kind (serving kinds only; the router materializes
                       it via :meth:`FaultPlan.for_replica`).  The
                       process-level kinds (``proc_*``) ONLY use this
                       axis: they name an OS-process replica and are
                       fired by the fleet supervisor, never inside an
                       engine (:meth:`FaultPlan.fire_replica`)
    kind@step=N*K      fire on steps N, N+1, ..., N+K-1 (K consecutive)

Registered kinds and the index they key on:

===============  =======  ===================================================
kind             keys on  effect at the injection site
===============  =======  ===================================================
``ckpt_torn``    step     truncate a payload file of the just-committed
                          checkpoint AFTER its manifest was written — a torn
                          write the integrity layer must catch on restore
``nan_grad``     step     corrupt the step's host-side inputs to NaN so the
                          device computes a non-finite loss/gradient
``loader_err``   batch    raise a transient OSError from the loader's feature
                          read (the prefetch retry path must absorb it)
``wedge``        step     block the train loop forever (the watchdog must
                          turn this into a fast exit 124)
``preempt``      step     deliver a REAL ``SIGTERM`` to the running process
                          when step N is dispatched (the preemption layer
                          must checkpoint at the next step boundary and exit
                          with the resumable taxonomy code)
``serve_wedge``  req      raise a transient error from the serving engine's
                          chunk dispatch while request N is resident (the
                          self-healing scheduler must re-run the chunk —
                          RESILIENCE.md "Serving faults")
``serve_garble`` req      zero request N's fetched chunk outputs — the
                          native-stack device-scalar garble's signature
                          (``resilience/garble.py``); the engine must detect
                          the impossible output and re-run deterministically
``admit_err``    req      raise a transient error from request N's admission
                          (the engine must re-queue and retry, never drop
                          the request silently or kill the scheduler loop)
``proc_kill``    replica  SIGKILL replica K's serve.py process mid-work —
                          the supervisor must requeue its in-flight
                          requests and restart it (exit 137, resumable)
``proc_wedge``   replica  SIGSTOP replica K's process — it goes silent with
                          work owed; the wedge timeout must turn this into
                          a kill classified as exit 124 (wedge)
``proc_preempt`` replica  SIGTERM replica K's process — its own drain
                          contract completes residents, rejects its queue
                          (the supervisor requeues those), and exits 75
===============  =======  ===================================================

Firing is deterministic and single-shot per (kind, index): a plan replayed
after a rollback does not re-fire indices it already consumed, so chaos
tests converge instead of re-injecting forever.  The consumed set is
process-local by default; ``bind_state(path)`` persists it as JSONL next
to the checkpoints, so a drill that kills its own process (``wedge``) is
also single-shot across the resume attempts a recovery harness spawns —
without it, ``scale_chain --fault_plan wedge@step=N`` would wedge every
attempt forever.
"""

from __future__ import annotations

import json
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

log = logging.getLogger("cst_captioning_tpu.resilience.faults")

#: kind -> the index axis its specs must use.
KINDS: Dict[str, str] = {
    "ckpt_torn": "step",
    "nan_grad": "step",
    "loader_err": "batch",
    "wedge": "step",
    "preempt": "step",
    # Serving failure domain (RESILIENCE.md "Serving faults"): keyed on
    # the request's submission ordinal, threaded into serving/engine.py.
    "serve_wedge": "req",
    "serve_garble": "req",
    "admit_err": "req",
    "serve_cache": "req",
    # Process failure domain (RESILIENCE.md "Process faults"): keyed on
    # the OS-process replica the fleet supervisor owns.  Never threaded
    # into an engine — the supervisor delivers these as real signals
    # (serving/supervisor.py).
    "proc_kill": "replica",
    "proc_wedge": "replica",
    "proc_preempt": "replica",
}

#: Serving kinds that may ALTERNATIVELY target a fleet replica
#: (``kind@replica=K``).  The router splits such a plan per replica
#: (:meth:`FaultPlan.for_replica`); inside replica K's engine the spec
#: fires at the first index probed for that kind — single-shot, like
#: every other spec (RESILIENCE.md "Serving faults").
REPLICA_KINDS = frozenset(k for k, axis in KINDS.items() if axis == "req")

#: Process-level kinds: ``@replica=K`` is their ONLY axis.  Fired by the
#: process-fleet supervisor via :meth:`FaultPlan.fire_replica` (a signal
#: to the child OS process); never forwarded into an engine's plan and
#: never forwarded onto a child's command line.
PROC_KINDS = frozenset(k for k, axis in KINDS.items() if axis == "replica")

#: Sentinel ``FaultSpec.at``: the spec covers ANY index (used by the
#: per-replica plans ``for_replica`` derives from ``@replica=K`` specs).
ANY_INDEX = -1

_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<axis>step|batch|req|replica)=(?P<at>\d+)"
    r"(\*(?P<times>\d+))?$"
)


class InjectedFault(OSError):
    """Raised by injection sites that simulate a transient I/O failure."""


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault: ``kind`` fires at indices ``at .. at+times-1``.

    ``replica`` is the fleet-targeting axis (``kind@replica=K``): the spec
    is inert in the plan that parsed it and only acts once
    :meth:`FaultPlan.for_replica` converts it into an any-index spec for
    replica K's engine.  ``at == ANY_INDEX`` covers every index (single
    shot — the consumed key is ``(kind, ANY_INDEX)``)."""

    kind: str
    at: int
    times: int = 1
    replica: Optional[int] = None

    def covers(self, index: int) -> bool:
        if self.at == ANY_INDEX:
            return True
        return self.at <= index < self.at + self.times

    def __str__(self) -> str:
        if self.replica is not None:
            return f"{self.kind}@replica={self.replica}"
        axis = KINDS[self.kind]
        tail = f"*{self.times}" if self.times != 1 else ""
        at = "any" if self.at == ANY_INDEX else self.at
        return f"{self.kind}@{axis}={at}{tail}"


@dataclass
class FaultPlan:
    """Parsed, consumable fault plan.  ``fire`` is the single runtime API."""

    specs: List[FaultSpec]
    _consumed: Set[Tuple[str, int]] = field(default_factory=set)
    _state_path: Optional[str] = None
    _metrics: Optional[object] = field(default=None, repr=False)
    _derived: Dict[int, Optional["FaultPlan"]] = \
        field(default_factory=dict, repr=False)

    def bind_metrics(self, registry) -> "FaultPlan":
        """Count firings into a ``telemetry.MetricsRegistry``
        (``fault_firings`` total + ``fault_<kind>`` per kind) so a chaos
        drill's injections are auditable in the exit telemetry.json."""
        self._metrics = registry
        # Declared at 0 per armed kind: a drill's snapshot shows which
        # faults were LOADED, not only which fired.
        registry.declare("fault_firings",
                         *(f"fault_{s.kind}" for s in self.specs))
        return self

    def bind_state(self, path: str) -> "FaultPlan":
        """Persist consumed firings to ``path`` (JSONL, append-only) and
        load any prior process's firings from it — the cross-process half
        of single-shot semantics (a wedge drill's resume attempt must not
        re-wedge).  Best-effort IO: chaos bookkeeping must never kill the
        run it is testing."""
        self._state_path = path
        try:
            with open(path) as f:
                for line in f:
                    kind, ix = json.loads(line)
                    self._consumed.add((kind, int(ix)))
        except (OSError, ValueError):
            pass
        return self

    @classmethod
    def parse(cls, text: Optional[str]) -> Optional["FaultPlan"]:
        """``None``/empty -> ``None`` (disarmed); bad grammar -> ValueError
        naming the offending spec — a chaos drill with a typo'd plan must
        fail at startup, not silently run fault-free."""
        if not text or not text.strip():
            return None
        specs = []
        for raw in text.split(","):
            raw = raw.strip()
            if not raw:
                continue
            m = _SPEC_RE.match(raw)
            if m is None:
                raise ValueError(
                    f"bad fault spec {raw!r}; expected kind@step=N, "
                    f"kind@batch=N, kind@req=N, or kind@step=N*K with "
                    f"kind in {sorted(KINDS)}")
            kind, axis = m.group("kind"), m.group("axis")
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; registered: {sorted(KINDS)}")
            if axis == "replica":
                if kind not in REPLICA_KINDS and kind not in PROC_KINDS:
                    raise ValueError(
                        f"fault {kind!r} cannot target a fleet replica; "
                        f"@replica=K is valid for "
                        f"{sorted(REPLICA_KINDS | PROC_KINDS)}")
                if m.group("times"):
                    raise ValueError(
                        f"bad fault spec {raw!r}: @replica=K takes no "
                        "*K repeat (one firing per targeted replica)")
                specs.append(FaultSpec(kind, ANY_INDEX,
                                       replica=int(m.group("at"))))
                continue
            if KINDS[kind] != axis:
                raise ValueError(
                    f"fault {kind!r} keys on {KINDS[kind]!r}, not {axis!r}")
            specs.append(FaultSpec(kind, int(m.group("at")),
                                   int(m.group("times") or 1)))
        return cls(specs=specs) if specs else None

    def for_replica(self, replica: int) -> Optional["FaultPlan"]:
        """The per-replica plan the fleet router hands replica
        ``replica``'s engine: every ``kind@replica=K`` spec targeting this
        replica becomes an any-index single-shot spec (it fires at the
        engine's FIRST probe of that kind — deterministic, because the
        router and engine are single-threaded per scheduler loop).
        Specs on other axes are NOT forwarded: in fleet mode the ``@req``
        ordinal is per-engine and therefore ambiguous, so replica drills
        use ``@replica=K`` (RESILIENCE.md).  Returns None when nothing
        targets this replica (the engine pays zero per-site checks).
        Metrics binding is inherited; consumed state is per-derived-plan
        (each targeted replica fires its own specs once).  MEMOIZED per
        replica: a restarted replica's fresh engine receives the SAME
        derived plan, so its consumed set survives the restart — the
        single-shot-across-resumes discipline ``fire`` has for
        rollbacks, without which a replica-targeted fault would re-fire
        on every restart and burn the whole restart budget.  ``proc_*``
        kinds are NOT materialized: they act on the replica's OS process
        from outside (``fire_replica``), not inside its engine."""
        k = int(replica)
        if k in self._derived:
            return self._derived[k]
        specs = [FaultSpec(s.kind, ANY_INDEX) for s in self.specs
                 if s.replica == k and s.kind not in PROC_KINDS]
        derived: Optional[FaultPlan] = None
        if specs:
            derived = FaultPlan(specs=specs)
            derived._metrics = self._metrics
        self._derived[k] = derived
        return derived

    def _consume(self, kind: str, key: Tuple[str, int]) -> None:
        """Shared single-shot bookkeeping for ``fire``/``fire_replica``:
        mark consumed, persist, count."""
        self._consumed.add(key)
        if self._state_path is not None:
            # Record BEFORE the fault acts: a wedge kills the
            # process, and the resume attempt must see it spent.
            try:
                with open(self._state_path, "a") as f:
                    # The CONSUMED key (ANY_INDEX for any-index
                    # specs), so a reload blocks the same spec.
                    f.write(json.dumps([kind, key[1]]) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                pass
        if self._metrics is not None:
            self._metrics.inc("fault_firings")
            self._metrics.inc(f"fault_{kind}")

    def fire(self, kind: str, index: int) -> bool:
        """True exactly once per (kind, index) covered by a spec.  The
        consumed set makes replays after rollback/resume fault-free.
        Replica-targeted specs never fire from the plan that parsed them
        (only from a ``for_replica`` derivative, where they cover any
        index and consume the ``ANY_INDEX`` key)."""
        for spec in self.specs:
            if spec.kind == kind and spec.replica is None \
                    and spec.covers(index):
                key = (kind, ANY_INDEX if spec.at == ANY_INDEX
                       else int(index))
                if key in self._consumed:
                    return False
                self._consume(kind, key)
                log.warning("FAULT INJECTED: %s fired at %s=%d (spec %s)",
                            kind, KINDS[kind], index, spec)
                return True
        return False

    def fire_replica(self, kind: str, replica: int) -> bool:
        """True exactly once per (``proc_*`` kind, replica): the
        process-fleet SUPERVISOR's firing API.  Process-level faults act
        on replica ``replica``'s OS process from outside (a real signal
        — serving/supervisor.py probes each armed kind once the replica
        is mid-work), so they never flow through an engine's ``fire``.
        Single-shot with the same persisted-consumed-set semantics:
        a restarted replica does not re-eat its own kill."""
        if KINDS.get(kind) != "replica":
            raise ValueError(
                f"fire_replica is for process-level kinds "
                f"{sorted(PROC_KINDS)}, not {kind!r}")
        k = int(replica)
        for spec in self.specs:
            if spec.kind == kind and spec.replica == k:
                key = (kind, k)
                if key in self._consumed:
                    return False
                self._consume(kind, key)
                log.warning("FAULT INJECTED: %s fired at replica=%d "
                            "(spec %s)", kind, k, spec)
                return True
        return False

    def cli_for_child(self, replica: int) -> Optional[str]:
        """The ``--fault_plan`` string a process-fleet supervisor passes
        to replica ``replica``'s serve.py child: every SERVING
        ``kind@replica=K`` spec targeting this replica becomes
        ``kind@req=0`` — the child's first submitted request, the
        process-boundary analogue of the any-index firing
        :meth:`for_replica` hands an in-process engine (a fresh child's
        first request IS its first opportunity for the kind).  ``proc_*``
        kinds are NOT forwarded — the supervisor itself delivers them as
        signals.  None when nothing serving-level targets this replica
        (the child runs fault-free)."""
        k = int(replica)
        specs = [f"{s.kind}@req=0" for s in self.specs
                 if s.replica == k and s.kind in REPLICA_KINDS]
        return ",".join(specs) or None

    def pending(self, kind: str) -> int:
        """Indices of ``kind`` armed but not yet consumed (test assertions)."""
        n = 0
        for spec in self.specs:
            if spec.kind != kind:
                continue
            if spec.kind in PROC_KINDS and spec.replica is not None:
                # Process-level specs consume a (kind, replica) key.
                n += int((kind, spec.replica) not in self._consumed)
                continue
            n += sum(1 for i in range(spec.at, spec.at + spec.times)
                     if (kind, i) not in self._consumed)
        return n

    def __str__(self) -> str:
        return ",".join(str(s) for s in self.specs)
