"""Host half of the divergence guard: lagged bad-step accounting + rollback.

The device half lives in ``training/steps.py``: guarded step factories fold
an ``isfinite(loss) & isfinite(grad_norm)`` check into the compiled program
and mask out the parameter/optimizer update when it fails, emitting a
``bad_step`` metric (0.0/1.0).  That keeps the skip decision entirely
on-device — no extra host sync in the step.

This class consumes those ``bad_step`` device scalars WITHOUT stalling the
dispatch loop: ``observe`` starts an async device->host copy and queues the
array; ``poll`` only blocks on entries at least ``lag`` steps old, whose
step has long since completed, so the fetch is a reap, not a wait.  After
``max_bad`` CONSECUTIVE bad steps it asks the trainer to roll back to the
last known-good checkpoint; ``max_rollbacks`` bounds how often that can
happen before the run is declared unrecoverable (a deterministic divergence
replaying forever would otherwise silently loop).
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Deque, Optional, Tuple

import numpy as np

log = logging.getLogger("cst_captioning_tpu.resilience.guard")


class DivergenceUnrecoverable(RuntimeError):
    """Raised when divergence persists past the rollback budget."""


class DivergenceGuard:
    """Counts consecutive non-finite steps; decides skip vs rollback.

    ``metrics`` (a ``telemetry.MetricsRegistry``, optional) receives the
    audit counters — ``divergence_guard_trips`` per non-finite step and
    ``divergence_guard_rollbacks`` per rollback — so a chaos drill's
    outcome is machine-readable in the exit telemetry.json instead of
    only greppable from stderr.  None costs one is-None check per event
    (and events are rare by construction)."""

    def __init__(self, max_bad: int = 3, max_rollbacks: int = 2,
                 lag: int = 1, metrics=None):
        self.max_bad = max(1, int(max_bad))
        self.max_rollbacks = max(0, int(max_rollbacks))
        self.lag = max(0, int(lag))
        self._metrics = metrics
        if metrics is not None:
            # Declared at 0 at arm time (cstlint:declared-counters): an
            # exit snapshot with 0 trips proves the guard RAN clean.
            metrics.declare("divergence_guard_trips",
                            "divergence_guard_rollbacks")
        self._queue: Deque[Tuple[int, object]] = deque()
        self.consecutive = 0
        self.total_skipped = 0
        self.rollbacks = 0
        self.last_bad_step: Optional[int] = None

    # -- ingestion ---------------------------------------------------------

    def observe(self, step_ix: int, bad_step) -> None:
        """Queue one step's ``bad_step`` device scalar (may be None when the
        step ran unguarded, e.g. a legacy factory)."""
        if bad_step is None:
            return
        if hasattr(bad_step, "copy_to_host_async"):
            bad_step.copy_to_host_async()  # overlap the fetch with step t+1
        self._queue.append((int(step_ix), bad_step))

    def _reap_one(self) -> None:
        step_ix, arr = self._queue.popleft()
        bad = float(np.asarray(arr)) > 0.0
        if bad:
            self.consecutive += 1
            self.total_skipped += 1
            self.last_bad_step = step_ix
            if self._metrics is not None:
                self._metrics.inc("divergence_guard_trips")
            log.warning(
                "divergence guard: non-finite loss/grad at step %d — update "
                "skipped on device (%d consecutive, %d total)",
                step_ix + 1, self.consecutive, self.total_skipped)
        else:
            self.consecutive = 0

    # -- decisions ---------------------------------------------------------

    def poll(self) -> bool:
        """Reap every entry older than ``lag`` steps; True when the
        consecutive-bad threshold is crossed (trainer should roll back)."""
        while len(self._queue) > self.lag:
            self._reap_one()
        return self.consecutive >= self.max_bad

    def flush(self) -> bool:
        """Reap everything (epoch boundary / end of run)."""
        while self._queue:
            self._reap_one()
        return self.consecutive >= self.max_bad

    def note_rollback(self) -> None:
        """Record one rollback; raise once the budget is exhausted."""
        self.rollbacks += 1
        if self._metrics is not None:
            self._metrics.inc("divergence_guard_rollbacks")
        if self.rollbacks > self.max_rollbacks:
            raise DivergenceUnrecoverable(
                f"training diverged again after {self.max_rollbacks} "
                "rollback(s) to known-good checkpoints — a deterministic "
                "divergence (bad data, runaway lr) that replaying cannot "
                "fix; fix the config instead of rolling back forever")
        self.reset()

    def reset(self) -> None:
        """Clear the consecutive counter and any queued observations (the
        steps they belong to were discarded by a rollback)."""
        self.consecutive = 0
        self._queue.clear()
