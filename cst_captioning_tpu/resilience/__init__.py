"""Resilience subsystem: fault injection, divergence guard, checkpoint integrity.

Three layers, each owning one class of failure (taxonomy + ownership table
in RESILIENCE.md):

- :mod:`faults` — a deterministic, CLI/env-armed fault-injection plan
  (``--fault_plan 'ckpt_torn@step=40,nan_grad@step=55,...'``) whose hooks
  live at the host-side seams of the trainer (checkpoint commit, loader
  read, step dispatch, train loop) and cost nothing when disarmed;
- :mod:`guard` — the host half of the divergence guard: consumes the
  ``bad_step`` flag the guarded train steps compute on device, counts
  consecutive bad steps with a lagged (non-blocking) fetch, and decides
  when to roll back to the last known-good checkpoint;
- :mod:`integrity` — per-step checkpoint manifests (content checksums
  written after the orbax commit), verify-on-restore, and the newest-
  verified-step walk-back that keeps auto-resume off torn checkpoints;
- :mod:`preemption` — SIGTERM/SIGINT -> checkpoint-requested flag; the
  trainer honors it at the next step boundary with a verified save and a
  dedicated resumable exit code;
- :mod:`exitcodes` — the exit-code taxonomy (ok/resumable/wedge/fatal)
  shared by the CLIs and the stage harness;
- :mod:`garble` — the native-stack device-scalar garble signatures (the
  all-0.0 detector shared by ``parallel/dryrun.py`` and the serving
  engine's self-healing scheduler) + the serving health-status words.
"""

from .exitcodes import (
    EXIT_ADVANTAGE_ABORT,
    EXIT_PREEMPTED,
    EXIT_WEDGE,
    classify,
    describe,
)
from .faults import FaultPlan, FaultSpec, InjectedFault
from .garble import GarbledChunk, all_zero, garbled_decode_slots, health_status
from .guard import DivergenceGuard, DivergenceUnrecoverable
from .integrity import (
    MANIFEST_NAME,
    manifest_path,
    verify_step_dir,
    write_manifest,
)
from .preemption import PreemptedExit, PreemptionHandler

__all__ = [
    "EXIT_ADVANTAGE_ABORT",
    "EXIT_PREEMPTED",
    "EXIT_WEDGE",
    "classify",
    "describe",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "GarbledChunk",
    "all_zero",
    "garbled_decode_slots",
    "health_status",
    "DivergenceGuard",
    "DivergenceUnrecoverable",
    "MANIFEST_NAME",
    "manifest_path",
    "verify_step_dir",
    "write_manifest",
    "PreemptedExit",
    "PreemptionHandler",
]
