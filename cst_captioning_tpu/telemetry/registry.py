"""Metrics registry: named counters/gauges/histograms with sink fan-out.

Replaces the trainer's ad-hoc metrics-dict writes with ONE instrument
surface that fans each step record out to every attached sink —
``metrics.jsonl`` (schema-versioned, fsync-able at checkpoint boundaries),
the TensorBoard ``ScalarWriter``, and a machine-readable ``telemetry.json``
snapshot written on exit.  Counters are the resilience audit trail: a
chaos drill's divergence trips, quarantines, fault firings, and loader
retries all land here instead of vanishing into stderr.

Threading: counters/gauges may be touched from worker threads (loader
prefetch retries) and read from the watchdog thread (heartbeat payload);
every mutation holds one small lock.  ``log_step`` is main-thread (the
trainer's logging cadence), but locks anyway — correctness over the ~µs.
The instrument tables are annotated ``guarded_by=self._lock`` and the
lock is created through ``utils.locksan.named_lock`` as
``telemetry.registry`` — a LEAF in every declared LOCK_ORDER table: no
registry method may acquire another project lock while holding it
(enforced by cstlint:guarded-by / cstlint:lock-order + the runtime
sanitizer).

Schema: every ``metrics.jsonl`` record and the ``telemetry.json`` snapshot
carry ``"schema": 2`` so downstream readers (scripts/chain_report.py,
scripts/collect_evidence.py) can evolve against a stable contract.
Schema 1 is the implicit pre-telemetry format (no schema field).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.locksan import named_lock

#: Version stamped into every metrics.jsonl record and telemetry snapshot.
METRICS_SCHEMA = 2


class MetricsRegistry:
    """Counters, gauges, histograms + step-record fan-out to sinks."""

    def __init__(self):
        self._lock = named_lock("telemetry.registry")
        self._counters: Dict[str, float] = {}      # cstlint: guarded_by=self._lock
        self._gauges: Dict[str, float] = {}        # cstlint: guarded_by=self._lock
        self._hists: Dict[str, Dict[str, float]] = {}  # cstlint: guarded_by=self._lock
        self._meta: Dict[str, Any] = {}            # cstlint: guarded_by=self._lock
        self._sinks: List[Any] = []
        self._last_train: Optional[Dict[str, Any]] = None  # cstlint: guarded_by=self._lock
        self._last_val: Optional[Dict[str, Any]] = None    # cstlint: guarded_by=self._lock

    def set_meta(self, name: str, value: Any) -> None:
        """Run-constant provenance (JSON-serializable) stamped into every
        snapshot under ``meta`` — e.g. the tuned-config resolution record
        (``meta.tuned_config``).  Unlike gauges these never change per
        step; unlike counters they carry structure."""
        with self._lock:
            self._meta[name] = value

    # -- instruments -------------------------------------------------------

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def declare(self, *names: str) -> None:
        """Pre-register counters at 0 (idempotent; never resets a live
        count).  Rare-event counters — the preemption layer's
        ``preempt_signals``/``preempt_saves`` — are declared at startup so
        every snapshot/heartbeat carries them explicitly: a reader can
        tell "armed, nothing happened" (0) from "feature absent"."""
        with self._lock:
            for name in names:
                self._counters.setdefault(name, 0)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Histogram-style observation: count/sum/min/max summary (enough
        for latency audits without an unbounded reservoir)."""
        v = float(value)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                self._hists[name] = {"count": 1, "sum": v, "min": v, "max": v}
            else:
                h["count"] += 1
                h["sum"] += v
                h["min"] = min(h["min"], v)
                h["max"] = max(h["max"], v)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    # -- step records ------------------------------------------------------

    def add_sink(self, sink) -> None:
        """A sink implements log_step(step, scope, metrics, wall_time),
        flush(fsync=False), close()."""
        self._sinks.append(sink)

    def log_step(self, step: int, scope: str,
                 metrics: Dict[str, Any]) -> None:
        """Fan one step's metrics out to every sink and remember the last
        record per scope (heartbeat + exit snapshot)."""
        now = time.time()
        with self._lock:
            rec = {"step": int(step), "scope": scope, **metrics}
            if scope == "val":
                self._last_val = rec
            else:
                self._last_train = rec
        for sink in self._sinks:
            sink.log_step(step, scope, metrics, now)

    def flush(self, fsync: bool = False) -> None:
        for sink in self._sinks:
            sink.flush(fsync=fsync)

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            hists = {
                name: {**h, "mean": h["sum"] / max(h["count"], 1)}
                for name, h in self._hists.items()
            }
            return {
                "schema": METRICS_SCHEMA,
                "time": time.time(),
                "meta": dict(self._meta),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": hists,
                "last_train": self._last_train,
                "last_val": self._last_val,
            }

    def heartbeat_payload(self) -> Dict[str, Any]:
        """Small host-state dict the watchdog stamps into the heartbeat
        file each poll: the last logged step (with its phase timings when
        step timing is on) plus the resilience counters.  Host memory
        only — reading it can never block on a dead device transport."""
        with self._lock:
            return {
                "last_train": self._last_train,
                "last_val_step": (self._last_val or {}).get("step"),
                "counters": dict(self._counters),
                # Gauges joined the payload for the data plane: the
                # prefetch queue's depth/occupancy between steps is
                # exactly the between-heartbeats state a stall
                # investigation needs (ISSUE 15 satellite) — retries
                # alone say a fault happened, not whether the queue was
                # starved or full when it did.
                "gauges": dict(self._gauges),
            }

    def write_snapshot(self, path: str) -> None:
        """Atomic telemetry.json write (the exit snapshot)."""
        from ..resilience.integrity import atomic_json_write

        atomic_json_write(path, self.snapshot(), indent=2, default=str)

    def close(self) -> None:
        for sink in self._sinks:
            try:
                sink.close()
            except Exception:  # one dying sink must not mute the others
                pass
        self._sinks = []


class JsonlSink:
    """Append-only metrics.jsonl writer (schema 2).

    Keeps the file handle open across records (the trainer used to
    open/close per write); ``flush(fsync=True)`` makes everything written
    so far durable — called at checkpoint boundaries so the metrics
    stream can never be newer on disk than the checkpoint it describes
    by more than one interval."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        self._f = open(path, "a")
        self._closed = False

    def log_step(self, step: int, scope: str, metrics: Dict[str, Any],
                 wall_time: float) -> None:
        if self._closed:
            return
        self._f.write(json.dumps(
            {"schema": METRICS_SCHEMA, "step": int(step), "scope": scope,
             "time": wall_time, **metrics}) + "\n")
        self._f.flush()  # line-buffered semantics, matching the old writer

    def flush(self, fsync: bool = False) -> None:
        if self._closed:
            return
        self._f.flush()
        if fsync:
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass  # metrics durability is best-effort, never fatal

    def close(self) -> None:
        if self._closed:
            return
        self.flush(fsync=True)
        self._f.close()
        self._closed = True


class ScalarWriterSink:
    """Adapter from the registry's log_step to utils.tb.ScalarWriter
    (which tolerates writes after close, so shutdown ordering between
    the registry and an atexit hook can never raise)."""

    def __init__(self, writer):
        self._writer = writer

    def log_step(self, step: int, scope: str, metrics: Dict[str, Any],
                 wall_time: float) -> None:
        for k, v in metrics.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self._writer.add_scalar(f"{scope}/{k}", v, step)

    def flush(self, fsync: bool = False) -> None:
        self._writer.flush()

    def close(self) -> None:
        self._writer.close()
