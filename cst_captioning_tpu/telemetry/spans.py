"""Host-side span tracer with Chrome-trace-event JSON export.

Answers "where does a step's wall-time go?" for the parts of training the
XLA profiler cannot see: the HOST side — data wait, dispatch, CIDEr-D
scoring, checkpoint commit (ISSUE 2 / OBSERVABILITY.md).  A span is a
named wall-clock interval opened with ``tracer.span("data_wait")`` (or the
``trace_span`` helper when the tracer may be absent); completed spans are
buffered thread-safely and exported as Chrome trace events — the
``{"traceEvents": [...]}`` JSON that Perfetto / chrome://tracing load
directly, with one row per host thread (main loop vs loader prefetch).

Design constraints, in priority order:

- **Disabled = free.**  Nothing here runs unless a tracer object exists;
  call sites hold ``None`` and pay one is-None check (the ``--fault_plan``
  pattern).  ``trace_span(None, ...)`` returns a shared no-op singleton —
  no allocation on the disabled path.
- **Never inside jit.**  Spans time host code only; device work appears
  as host *wait* time (the fetch that blocks on it), which is exactly the
  quantity overlap tuning needs.
- **Cheap when enabled.**  One ``perf_counter`` pair + one small dict per
  span, appended under a lock (~1 µs); the buffer rotates to a part file
  at ``max_buffered_events`` so a long run cannot grow host memory
  unboundedly.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.locksan import named_lock


class _NullSpan:
    """Shared no-op context manager — the disabled path of every hook."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


#: The one instance call sites use when their tracer is None.
NULL_SPAN = _NullSpan()


def trace_span(tracer: Optional["SpanTracer"], name: str, **args):
    """``with trace_span(tracer, "data_wait"): ...`` — no-op when
    ``tracer`` is None (one is-None check, zero allocation)."""
    if tracer is None:
        return NULL_SPAN
    return tracer.span(name, **args)


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer._record(self._name, self._t0, time.perf_counter(),
                             self._args)
        return False


class SpanTracer:
    """Thread-safe span buffer + Chrome-trace JSON writer.

    Spans may be opened from any thread (the loader prefetch worker
    records alongside the main loop — the trace shows them as separate
    ``tid`` rows, which is how overlap becomes visible).  Files land in
    ``trace_dir`` as ``trace_<pid>r<k>[_partN].json``; each is a
    complete, independently loadable Chrome trace (a rotated long run
    yields several).  ``r<k>`` is a process-global tracer sequence
    number, so two tracers sharing one pid AND one trace_dir — two train
    stages in one script, like scripts/trace_demo.py — append distinct
    files instead of the second clobbering the first's.
    """

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, trace_dir: str, process_index: int = 0,
                 max_buffered_events: int = 200_000):
        self._dir = os.path.abspath(trace_dir)
        os.makedirs(self._dir, exist_ok=True)
        self._pid = os.getpid()
        with SpanTracer._seq_lock:
            self._run = SpanTracer._seq
            SpanTracer._seq += 1
        self._process_index = int(process_index)
        # Buffer state is guarded (any thread may record a span); the
        # *_locked helper convention marks the callers-hold-it paths.
        self._lock = named_lock("telemetry.spans")
        self._events: List[Dict[str, Any]] = []  # cstlint: guarded_by=self._lock
        self._named_tids: set = set()            # cstlint: guarded_by=self._lock
        self._max = max(1000, int(max_buffered_events))
        self._part = 0                           # cstlint: guarded_by=self._lock
        self._closed = False                     # cstlint: guarded_by=self._lock
        # ts epoch: perf_counter is monotonic but has an arbitrary zero;
        # anchor it once so every event's ts is "µs since tracer start"
        # and the wall-clock anchor rides in the file's otherData.
        self._t_epoch = time.perf_counter()
        self._wall_epoch = time.time()
        self._events.append({
            "name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
            "args": {"name": f"cst_captioning_tpu host "
                             f"(process {self._process_index})"},
        })

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **args) -> _Span:
        """Context manager timing one host interval; nests naturally."""
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker event (e.g. a fault firing)."""
        now = time.perf_counter()
        ev = {"name": name, "ph": "i", "s": "t", "cat": "host",
              "ts": (now - self._t_epoch) * 1e6,
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._append(ev)

    def async_event(self, phase: str, name: str, aid, **args) -> None:
        """Async-track event (Chrome phases ``b``/``n``/``e``): events
        sharing ``id`` render as ONE track spanning threads — how the
        request-lifecycle tracer draws a request's journey across the
        router and replica span rows (telemetry/lifecycle.py).  Chrome
        pairs ``b``/``e`` by name+cat+id, so callers keep those stable
        per track and put the varying detail in ``args``."""
        if phase not in ("b", "n", "e"):
            raise ValueError(f"async phase must be 'b', 'n' or 'e', "
                             f"got {phase!r}")
        now = time.perf_counter()
        ev = {"name": name, "ph": phase, "cat": "request",
              "id": str(aid),
              "ts": (now - self._t_epoch) * 1e6,
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._append(ev)

    def _record(self, name: str, t0: float, t1: float,
                args: Optional[Dict[str, Any]]) -> None:
        ev = {"name": name, "ph": "X", "cat": "host",
              "ts": (t0 - self._t_epoch) * 1e6,
              "dur": (t1 - t0) * 1e6,
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, ev: Dict[str, Any]) -> None:
        tid = ev["tid"]
        rotate = None
        with self._lock:
            if self._closed:
                return  # a straggler worker thread after close: drop, not die
            if tid not in self._named_tids:
                self._named_tids.add(tid)
                self._events.append({
                    "name": "thread_name", "ph": "M", "pid": self._pid,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                })
            self._events.append(ev)
            if len(self._events) >= self._max:
                rotate = self._take_events_locked()
        if rotate is not None:
            self._write_part(*rotate)

    def _take_events_locked(self):
        """-> (events, part_path); claims the part number under the lock
        so concurrent rotations cannot collide on a file name."""
        events, self._events = self._events, []
        # thread-name metadata must reappear in every part file so each
        # one loads self-described.
        self._named_tids.clear()
        suffix = "" if self._part == 0 else f"_part{self._part}"
        self._part += 1
        return events, os.path.join(
            self._dir, f"trace_{self._pid}r{self._run}{suffix}.json")

    # -- export ------------------------------------------------------------

    def _write_part(self, events: List[Dict[str, Any]], path: str) -> None:
        if not events:
            return
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "pid": self._pid,
                "process_index": self._process_index,
                "wall_epoch_unix_s": self._wall_epoch,
            },
        }
        from ..resilience.integrity import atomic_json_write

        # Was a hand-rolled tmp+replace (whole files, not torn) — the
        # shared discipline adds the data/dir fsyncs for free.
        atomic_json_write(path, doc)

    def flush(self) -> None:
        """Write buffered events out now (a complete part file)."""
        with self._lock:
            events, path = self._take_events_locked()
        self._write_part(events, path)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            events, path = self._take_events_locked()
            self._closed = True
        self._write_part(events, path)
