"""Request-lifecycle tracing plane + flight recorder (OBSERVABILITY.md
"Request lifecycle & flight recorder").

The serving plane's aggregate telemetry (spans, counters, gauges) can
say "p99 is bad"; it cannot say "why was THIS request's p99 bad", and a
process dying with exit 124 leaves no record of what was in flight.
This module closes both gaps:

- **Per-request causal traces.**  Every request carries its id through
  typed lifecycle events — ``received``, ``queued``, ``routed`` (fleet
  placement), ``cache_hit``, ``admitted``, ``decode_chunk``, ``retry``
  / ``rebuild`` (the self-healing ladder, per affected resident),
  ``killed`` / ``requeued`` (a fleet replica dying with the request
  aboard), ``dropped`` (expired / deadline-shed / admit-failed, with
  ``where``), ``shed``, ``completed``, ``responded`` — each stamped
  with a monotonic timestamp from the SAME clock the engine schedules
  by, so the event stream reconciles exactly with the engine's own
  latency accounting.  Events forward to the Chrome-trace exporter as
  async events (``SpanTracer.async_event``), so Perfetto renders a
  request's whole journey as one track beside the router/replica span
  rows.

- **Latency attribution.**  :func:`attribute_request` replays one
  request's events through a small state machine and splits its total
  latency into ``queue_wait`` / ``admit`` / ``decode`` / ``recovery`` /
  ``requeue`` components that SUM to the measured latency by
  construction (the intervals partition [received, terminal]; the admit
  program's measured cost is carved out of the interval that contains
  it).  :meth:`LifecycleTracer.attribution_report` aggregates those
  into per-component p50/p99 — fleet-wide and per completing replica —
  and reconciles every request's component sum against the engine's
  measured latency within a tolerance; ``scripts/serve_report.py``
  exits 1 when the books don't balance.

- **Flight recorder.**  Events land in a bounded ring buffer (fixed-
  size host memory — a deque, never a file handle on the hot path).
  :meth:`dump` writes the forensic ``blackbox.json`` through
  ``atomic_json_write``: the last-N lifecycle events plus whatever
  state providers are attached (registry counters, per-replica health,
  ProgramCache builds/entries) and the terminal-accounting verdict.
  The serving front ends dump it on ``ServingUnrecoverable`` /
  ``FleetUnrecoverable`` (exit 124), on a hard-abort drain, and on
  demand via the ``{"op": "dump"}`` wire op.

Disabled path (the house rule): call sites hold ``None`` and pay one
is-None check per hook; nothing here ever touches a compiled program —
events are host dicts about host decisions.

Threading: emits come from the scheduler loop (the engine/server single-
owner thread); the ring buffer still takes a small named lock so an
exit-path dump racing a straggler emit reads a consistent buffer.  The
lock is declared in LOCK_ORDER ahead of the span-tracer leaf, though the
span forward deliberately happens OUTSIDE it.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.locksan import declare_order, named_lock

#: Event kinds the tracer accepts (a typo'd kind is a programming error,
#: not a new event type).
EVENT_KINDS = (
    "received",      # intake: the request entered the serving plane
    "queued",        # it joined an engine's admission queue
    "routed",        # fleet placement decision (attrs: replica)
    "cache_hit",     # exact-result cache completed it at submit time
    "admitted",      # one-encoder-pass admission (attrs: slot, admit_ms)
    "decode_chunk",  # one compiled chunk advanced it (attrs: k, slot)
    "retry",         # self-healing chunk re-run while it was resident
    "rebuild",       # engine rebuild re-admitted it (replay prefix kept)
    "killed",        # its replica was killed/restarted with it aboard
    "requeued",      # it re-entered admission after a kill/rotation
    "dropped",       # terminal: expired/deadline_shed/admit_failed
    "shed",          # terminal: backpressure shed (queue or fleet edge)
    "completed",     # terminal: caption harvested (attrs: latency_ms)
    "responded",     # the front end wrote the final wire response
    "slo_alert",     # fleet SLO burn-rate alert fired/cleared (attrs:
                     # objective, state, fast_burn, slow_burn) — id is
                     # the objective name, not a request; its chain has
                     # no `received` so accounting counts it truncated,
                     # never a terminal violation (telemetry/fleetobs.py)
    "autoscale_decision",  # autoscaler scale/brownout decision (attrs:
                     # action, replicas_before/after, rung) — id is the
                     # decision seq, not a request; same truncated-chain
                     # accounting as slo_alert (serving/autoscale.py)
    "replayed",      # intake-journal replay re-entered it after a
                     # supervisor relaunch (attrs: key, seq_out,
                     # sent_tokens) — intake happened in the DEAD
                     # process, so its chain has no `received` and
                     # accounting counts it truncated, never a terminal
                     # violation (serving/journal.py, ISSUE 20)
)

#: The kinds that END a request's story exactly once.  ``responded`` is
#: a supplementary front-end marker (it FOLLOWS a semantic terminal and
#: may legitimately be absent in engine-only callers like the bench
#: probe), so it is not part of the exactly-once accounting set.
TERMINAL_KINDS = ("completed", "dropped", "shed")

#: Attribution component names, in render order.  Every interval of a
#: request's life is assigned to exactly one, so they sum to the total.
COMPONENTS = ("queue_wait", "admit", "decode", "recovery", "requeue")

#: Flight-recorder file format version.
BLACKBOX_SCHEMA = 1

#: Default ring capacity: ~a few thousand requests' worth of events in
#: fixed host memory (one event is a small dict).
DEFAULT_EVENTS = 4096

#: Declared acquisition order (cstlint:lock-order + the runtime
#: sanitizer): the ring lock may in principle be held into the span
#: tracer's buffer leaf (both telemetry-plane locks); the registry stays
#: its own leaf — emit never counts while holding the ring.
LOCK_ORDER = ("telemetry.lifecycle", "telemetry.spans")
declare_order(*LOCK_ORDER)


def attribute_request(events: List[Dict[str, Any]]
                      ) -> Optional[Dict[str, float]]:
    """Split one request's lifecycle into latency components (seconds).

    ``events`` are the request's events in timestamp order.  Returns
    ``None`` when the stream has no ``received`` or no terminal event
    (an in-flight or malformed chain — the accounting check reports
    those separately).  The returned dict carries every name in
    :data:`COMPONENTS` plus ``total`` (terminal ts - received ts); the
    components partition the total by construction:

    - intervals before admission accrue to ``queue_wait`` (minus the
      measured ``admit_ms`` carved out as ``admit``);
    - intervals while resident accrue to ``decode``;
    - an interval ending at a ``retry``/``rebuild`` event — a failed
      dispatch the self-healing ladder absorbed — and the re-run that
      follows it accrue to ``recovery``;
    - everything between a ``killed`` (or rotation ``requeued``) event
      and the re-admission accrues to ``requeue`` — the fleet-restart
      cost the kill drill asserts is attributed, not hidden.
    """
    comp = {c: 0.0 for c in COMPONENTS}
    t_start = None
    terminal_ts = None
    prev_ts = None
    state = "queue_wait"
    for ev in events:
        kind = ev["kind"]
        ts = ev["ts"]
        if t_start is None:
            if kind != "received":
                # A chain that starts mid-story (ring rotation ate the
                # head): not attributable.
                return None
            t_start = ts
            prev_ts = ts
            continue
        if terminal_ts is not None:
            break  # ignore post-terminal markers (responded)
        span = max(ts - prev_ts, 0.0)
        # Interval classification: ending-event overrides for the
        # failure kinds, the running state otherwise.
        if kind in ("retry", "rebuild"):
            comp["recovery"] += span
            state = "recovery"
        elif kind == "killed":
            comp[state] += span
            state = "requeue"
        elif kind == "requeued":
            comp["requeue"] += span
            state = "requeue"
        elif kind == "admitted":
            # Event attrs are host floats by construction (emit() owns
            # the one coercion), so no per-event conversions here.
            admit_s = ev.get("admit_ms", 0.0) / 1e3
            admit_s = min(max(admit_s, 0.0), span)
            comp[state] += span - admit_s
            comp["admit"] += admit_s
            state = "decode"
        elif kind == "decode_chunk":
            comp[state] += span
            state = "decode"
        elif kind in TERMINAL_KINDS:
            comp[state] += span
            terminal_ts = ts
        else:  # queued / routed / cache_hit: waiting-side bookkeeping
            comp[state] += span
        prev_ts = ts
    if t_start is None or terminal_ts is None:
        return None
    comp["total"] = terminal_ts - t_start
    return comp


class LifecycleTracer:
    """Bounded per-request event ring + attribution + flight recorder.

    ``clock`` must be the SAME callable the engines schedule by (the
    default ``time.monotonic`` matches the engine default), so event
    timestamps reconcile with the engine's latency bookkeeping;
    deterministic tests inject one fake clock into both.  ``tracer``
    (optional, a :class:`telemetry.spans.SpanTracer`) mirrors every
    event into the Chrome trace as an async-track event.  ``registry``
    (optional) counts ``lifecycle_events`` / ``lifecycle_dumps``
    (declared at 0).
    """

    def __init__(self, max_events: int = DEFAULT_EVENTS,
                 *, clock: Callable[[], float] = time.monotonic,
                 tracer=None, registry=None):
        self.max_events = max(16, int(max_events))
        self.clock = clock
        self._tracer = tracer
        self._registry = registry
        self._lock = named_lock("telemetry.lifecycle")
        self._events: deque = deque(maxlen=self.max_events)  # cstlint: guarded_by=self._lock
        self._emitted = 0                                    # cstlint: guarded_by=self._lock
        self._dumps = 0
        #: State providers the blackbox pulls from at dump time (all
        #: optional; attach whatever this deployment has).
        self._providers: Dict[str, Callable[[], Any]] = {}
        if registry is not None:
            registry.declare("lifecycle_events", "lifecycle_dumps")

    # -- wiring -------------------------------------------------------------

    def attach(self, **providers: Callable[[], Any]) -> "LifecycleTracer":
        """Register blackbox state providers by name — e.g.
        ``attach(counters=registry.snapshot, health=router.health,
        program_cache=lambda: {...})``.  Later attaches override."""
        for name, fn in providers.items():
            if fn is None:
                self._providers.pop(name, None)
            else:
                self._providers[name] = fn
        return self

    def for_replica(self, replica: int,
                    intake: bool = False) -> "_ReplicaLifecycle":
        """A labeled view for one fleet replica's engine: every emit
        gains ``replica=k``.  With ``intake=False`` (the fleet default)
        the view drops ``received``/``shed`` — the ROUTER owns intake,
        and a per-candidate engine shed is a routing detail, not a
        terminal answer."""
        return _ReplicaLifecycle(self, int(replica), bool(intake))

    # -- recording ----------------------------------------------------------

    def emit(self, kind: str, request_id: Any,
             ts: Optional[float] = None, **attrs: Any) -> None:
        """Record one lifecycle event.  ``ts`` defaults to ``clock()``;
        the engine passes its own already-read clock values (arrival,
        done_at) so the stream and its bookkeeping share timestamps."""
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown lifecycle event kind {kind!r} "
                             f"(expected one of {EVENT_KINDS})")
        ev: Dict[str, Any] = {
            "ts": float(self.clock() if ts is None else ts),
            "id": request_id, "kind": kind,
        }
        if attrs:
            ev.update(attrs)
        with self._lock:
            self._events.append(ev)
            self._emitted += 1
        if self._registry is not None:
            self._registry.inc("lifecycle_events")
        if self._tracer is not None:
            # Async-track mirror: one Perfetto track per request id —
            # begun at intake, ended at the semantic terminal (Chrome
            # matches b/e on name+cat+id, so those share the constant
            # name "request"), every other event an instant step whose
            # name IS the kind.
            if kind == "received":
                self._tracer.async_event("b", "request", request_id,
                                         kind=kind, **attrs)
            elif kind in TERMINAL_KINDS:
                self._tracer.async_event("e", "request", request_id,
                                         kind=kind, **attrs)
            else:
                self._tracer.async_event("n", kind, request_id, **attrs)

    # -- views --------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        """Snapshot of the retained ring (oldest first)."""
        with self._lock:
            return [dict(ev) for ev in self._events]

    def emitted(self) -> int:
        with self._lock:
            return self._emitted

    def _chains(self) -> List[Tuple[Any, List[Dict[str, Any]]]]:
        """(request_id, events) segments in ts order.  A request id a
        client REUSES (allowed on the wire — each submission is a fresh
        stream) yields one segment per ``received``, so a finished
        request followed by its reused id is two clean stories, never a
        fake multi-terminal."""
        by_id: Dict[Any, List[Dict[str, Any]]] = {}
        for ev in self.events():
            by_id.setdefault(ev["id"], []).append(ev)
        segments: List[Tuple[Any, List[Dict[str, Any]]]] = []
        for rid, evs in by_id.items():
            evs.sort(key=lambda e: e["ts"])
            cur: List[Dict[str, Any]] = []
            for ev in evs:
                if ev["kind"] == "received" and cur:
                    segments.append((rid, cur))
                    cur = []
                cur.append(ev)
            if cur:
                segments.append((rid, cur))
        return segments

    def accounting(self) -> Dict[str, Any]:
        """The exactly-one-terminal audit over the retained ring: every
        request id that entered (``received``) must reach exactly one
        semantic terminal (``completed``/``dropped``/``shed``).  Chains
        whose head rotated out of the ring are excluded (noted in
        ``truncated``) — a bounded recorder can prove the window it
        kept, never the window it dropped."""
        submitted = unterminated = multi = 0
        bad_ids: List[str] = []
        truncated = 0
        for rid, evs in self._chains():
            kinds = [e["kind"] for e in evs]
            if kinds[0] != "received":
                truncated += 1
                continue
            submitted += 1
            n_term = sum(1 for k in kinds if k in TERMINAL_KINDS)
            if n_term == 0:
                unterminated += 1
                bad_ids.append(str(rid))
            elif n_term > 1:
                multi += 1
                bad_ids.append(str(rid))
        return {
            "submitted": submitted,
            "truncated": truncated,
            "unterminated": unterminated,
            "multi_terminal": multi,
            "terminal_ok": unterminated == 0 and multi == 0,
            "bad_ids": bad_ids[:16],
        }

    def attribution_report(self, measured_ms: Optional[Dict[Any, float]]
                           = None, tolerance_ms: float = 50.0,
                           tolerance_frac: float = 0.02) -> Dict[str, Any]:
        """Aggregate per-request attribution into per-component p50/p99
        (overall + per completing replica) and reconcile each request's
        component sum against its measured latency.

        ``measured_ms`` maps request id -> the caller's measured latency
        (e.g. the probe's ``Completion.latency_s * 1e3``); when None,
        the ``latency_ms`` attr the engine stamps on ``completed``
        events is used.  A request reconciles when
        ``|sum(components) - measured| <= tolerance_ms +
        tolerance_frac * measured``.
        """
        per_comp: Dict[str, List[float]] = {c: [] for c in COMPONENTS}
        per_replica: Dict[int, Dict[str, List[float]]] = {}
        residuals: List[float] = []
        bad: List[str] = []
        n = 0
        for rid, evs in self._chains():
            comp = attribute_request(evs)
            if comp is None:
                continue
            n += 1
            for c in COMPONENTS:
                per_comp[c].append(comp[c] * 1e3)
            rep = next((e.get("replica") for e in reversed(evs)
                        if e["kind"] in TERMINAL_KINDS
                        and e.get("replica") is not None), None)
            if rep is not None:
                # replica attrs are host ints by construction
                # (for_replica coerces once at view creation).
                rows = per_replica.setdefault(
                    rep, {c: [] for c in COMPONENTS})
                for c in COMPONENTS:
                    rows[c].append(comp[c] * 1e3)
            # The engine stamps its measured latency on `completed`
            # (a host float by construction); a caller-supplied
            # measurement — documented plain-float ms — fills
            # drop/shed terminals.
            measured = next(
                (e["latency_ms"] for e in evs
                 if e["kind"] == "completed" and "latency_ms" in e),
                None)
            if measured is None and measured_ms is not None:
                measured = measured_ms.get(rid)
            if measured is None:
                continue
            got = sum(comp[c] for c in COMPONENTS) * 1e3
            residual = abs(got - measured)
            residuals.append(residual)
            if residual > tolerance_ms + tolerance_frac * measured:
                bad.append(str(rid))

        def pcts(vals: List[float]) -> Dict[str, Optional[float]]:
            if not vals:
                return {"p50_ms": None, "p99_ms": None, "sum_ms": 0.0}
            s = sorted(vals)

            def pick(q: float) -> float:
                ix = min(len(s) - 1, int(round(q * (len(s) - 1))))
                return round(s[ix], 3)

            return {"p50_ms": pick(0.50), "p99_ms": pick(0.99),
                    "sum_ms": round(sum(s), 3)}

        return {
            "requests": n,
            "components": {c: pcts(v) for c, v in per_comp.items()},
            "per_replica": {
                str(k): {c: pcts(v) for c, v in rows.items()}
                for k, rows in sorted(per_replica.items())},
            "reconciled": len(residuals),
            "reconcile_ok": not bad,
            "reconcile_failures": bad[:16],
            "max_residual_ms": (round(max(residuals), 3)
                                if residuals else None),
            "tolerance_ms": float(tolerance_ms),
            "tolerance_frac": float(tolerance_frac),
        }

    # -- the flight recorder ------------------------------------------------

    def blackbox(self, reason: str = "on_demand") -> Dict[str, Any]:
        """The forensic snapshot: last-N events + attached state + the
        accounting/attribution verdicts.  Pure host memory — safe to
        build while the device transport is dead (that is the point)."""
        events = self.events()          # one consistent locked snapshot
        doc: Dict[str, Any] = {
            "schema": BLACKBOX_SCHEMA,
            "reason": str(reason),
            "wall_time": time.time(),
            "clock_now": float(self.clock()),
            "events_retained": len(events),
            "events_emitted": self.emitted(),
            "max_events": self.max_events,
            "accounting": self.accounting(),
            "attribution": self.attribution_report(),
            "events": [
                {**ev, "id": _json_id(ev["id"])} for ev in events
            ],
        }
        for name, fn in self._providers.items():
            try:
                doc[name] = fn()
            except Exception as e:  # a dead provider must not mute the rest
                doc[name] = {"provider_error": repr(e)}
        return doc

    def dump(self, path: str, reason: str = "on_demand") -> Dict[str, Any]:
        """Write ``blackbox.json`` durably (atomic_json_write) and
        return the doc.  Callers on the exit-124 path write FIRST, then
        exit — the evidence outlives the process."""
        from ..resilience.integrity import atomic_json_write

        doc = self.blackbox(reason)
        atomic_json_write(path, doc, indent=2, default=str)
        self._dumps += 1
        if self._registry is not None:
            self._registry.inc("lifecycle_dumps")
        return doc


class _ReplicaLifecycle:
    """A replica-labeled emit view over one shared tracer (see
    :meth:`LifecycleTracer.for_replica`).  Engines hold this exactly as
    they would the base tracer; attribution/accounting stay fleet-wide
    on the base object."""

    __slots__ = ("_base", "replica", "_intake")

    def __init__(self, base: LifecycleTracer, replica: int, intake: bool):
        self._base = base
        self.replica = replica
        self._intake = intake

    @property
    def clock(self):
        return self._base.clock

    def emit(self, kind: str, request_id: Any,
             ts: Optional[float] = None, **attrs: Any) -> None:
        if not self._intake and kind in ("received", "shed"):
            return  # the router owns intake terminals (module docstring)
        self._base.emit(kind, request_id, ts=ts,
                        replica=self.replica, **attrs)


def _json_id(rid: Any) -> Any:
    """Request ids are caller-opaque (ints, strings, tuples); make them
    JSON-stable for the blackbox without losing distinctness."""
    if isinstance(rid, (str, int, float, bool)) or rid is None:
        return rid
    return repr(rid)
