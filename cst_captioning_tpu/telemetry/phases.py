"""Step-phase accumulator: per-log-interval data_wait/compute/score/ckpt.

The trainer wraps each phase of its loop in ``phases.phase(name)``; at
every ``--log_every`` interval the accumulated totals drain into the
metrics stream as per-step ``<phase>_ms`` gauges.  Attribution is
EXCLUSIVE: a phase opened inside another (host-path CST scores inside the
step completion, so ``score`` nests under ``compute``) has its time
subtracted from the parent, so the gauges partition wall-time instead of
double-counting — the span trace keeps the full nested durations.

Main-thread only by design (the trainer's loop is single-threaded; the
prefetch worker reports through tracer spans + registry counters, not
phases), so the nesting stack needs no locking.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .spans import SpanTracer

#: The canonical step phases, in loop order.  drain() always emits all of
#: them so the metrics.jsonl contract is stable even for phases a given
#: configuration never enters (e.g. score under --device_rewards 1).
STEP_PHASES = ("data_wait", "compute", "score", "ckpt")


class _PhaseCtx:
    __slots__ = ("_phases", "_name", "_span", "_t0", "_child")

    def __init__(self, phases: "StepPhases", name: str):
        self._phases = phases
        self._name = name
        tracer = phases._tracer
        self._span = tracer.span(name) if tracer is not None else None

    def __enter__(self) -> "_PhaseCtx":
        if self._span is not None:
            self._span.__enter__()
        self._child = 0.0
        self._phases._stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        dur = time.perf_counter() - self._t0
        if self._span is not None:
            self._span.__exit__(*exc)
        ph = self._phases
        ph._stack.pop()
        ph._totals[self._name] = (
            ph._totals.get(self._name, 0.0) + dur - self._child)
        if ph._stack:
            ph._stack[-1]._child += dur
        return False


class StepPhases:
    """Accumulates exclusive per-phase seconds; drains to *_ms gauges."""

    def __init__(self, tracer: Optional[SpanTracer] = None):
        self._tracer = tracer
        self._totals: Dict[str, float] = {}
        self._stack: List[_PhaseCtx] = []

    def phase(self, name: str) -> _PhaseCtx:
        return _PhaseCtx(self, name)

    def drain_ms(self, steps: int) -> Dict[str, float]:
        """-> {"<phase>_ms": mean exclusive ms per step} over the interval
        since the last drain; resets the accumulator.  Every canonical
        phase is always present (0.0 when never entered)."""
        n = max(1, int(steps))
        out = {f"{name}_ms": round(
                   self._totals.get(name, 0.0) / n * 1e3, 3)
               for name in STEP_PHASES}
        for name in self._totals:
            if name not in STEP_PHASES:  # ad-hoc phases still surface
                out[f"{name}_ms"] = round(self._totals[name] / n * 1e3, 3)
        self._totals = {}
        return out
