"""Analytic model-FLOPs accounting + MFU derivation — ONE implementation.

Moved out of the root ``bench.py`` so the trainer's live ``mfu_pct`` gauge
and the benchmark's offline MFU report share the same arithmetic and can
never drift (bench.py re-exports these names for its callers).  Pure
Python/math — deliberately importable without jax, because bench's parent
process must not initialize a backend before its probe does.

Counts the MXU work the architecture performs (encoder projections,
memory projection, per-step attention, LSTM gates, vocab head) at
2 FLOPs/MAC, with backward ≈ 2x forward — the standard "model FLOPs"
convention, so the derived MFU excludes remat recompute and the device
CIDEr-D's integer hashing (both make real utilization slightly higher
than reported).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

#: bf16 peak matmul TFLOP/s per chip by device_kind substring (first match
#: wins; jax device_kind strings look like "TPU v5 lite").  Public numbers
#: from the TPU generations' spec sheets; used only to turn achieved
#: TFLOP/s into an MFU percentage.
PEAK_BF16_TFLOPS = (
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0),
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 46.0),
)

#: The MSR-VTT bench shapes (ResNet-152 + C3D) — bench.py's default.
DEFAULT_FEAT_SHAPES: Tuple[Tuple[int, int], ...] = ((28, 2048), (1, 4096))


def peak_tflops(device_kind: str) -> Optional[float]:
    kind = (device_kind or "").lower()
    for sub, peak in PEAK_BF16_TFLOPS:
        if sub in kind:
            return peak
    return None


def caption_step_flops(
    batch_size: int,
    seq_per_img: int,
    seq_len: int,
    vocab: int,
    hidden: int,
    feat_shapes: Sequence[Tuple[int, int]] = DEFAULT_FEAT_SHAPES,
) -> Dict[str, float]:
    """Analytic matmul FLOPs of one optimizer step -> {"xe": F, "cst": F}.

    Shapes mirror the attention-LSTM captioner with embed = attn = hidden
    (the shipped default; runs with distinct --input_encoding_size/
    --att_size read this as an estimate, which is all MFU needs).

    CST counts the shipped fused step: sampled + greedy rollouts (forward
    only, one shared encode) plus the REINFORCE gradient step (fwd+bwd)
    over the sampled captions.
    """
    B, S, L = batch_size, seq_per_img, seq_len
    N = B * S
    H = A = hidden
    V = vocab
    feat = list(feat_shapes)
    T = sum(t for t, _ in feat)
    enc = B * sum(t * d * H for t, d in feat)   # per-modality Dense
    enc += B * (len(feat) * H) * H              # fuse Dense
    enc += B * T * H * A                        # memory_proj (attention)
    enc += B * H * 2 * H                        # state_init
    # One decoder step for one caption: attention query proj + additive
    # scores + context, LSTM gates on concat(embed, context) -> (3H x 4H),
    # and the hoisted vocab head.
    per_step = H * A + T * A + T * H + 3 * H * 4 * H + H * V
    dec = N * L * per_step
    fwd = enc + dec
    xe = 3 * fwd * 2.0                          # fwd + 2x bwd, 2 FLOPs/MAC
    # The greedy-baseline rollout decodes ONE row per image (B rows, not
    # B*S — steps.py make_rollout_fused returns greedy (B, L)).
    greedy_dec = B * L * per_step
    cst = (enc + dec + greedy_dec) * 2.0 + xe
    return {"xe": xe, "cst": cst}


def mfu_fields(flops_per_step: float, captions_per_sec: Optional[float],
               ncaps: int, device_kind: Optional[str]) -> dict:
    """captions/s -> {model_tflops_per_step, achieved_tflops, mfu_pct}.

    mfu_pct is None off-TPU (no meaningful peak for the host CPU) and on
    unrecognized device kinds."""
    if not captions_per_sec:
        return {}
    achieved = flops_per_step * captions_per_sec / ncaps / 1e12
    peak = peak_tflops(device_kind or "")
    sig = lambda x: float(f"{x:.4g}")  # keep tiny-shape runs nonzero
    return {
        "model_tflops_per_step": sig(flops_per_step / 1e12),
        "achieved_tflops": sig(achieved),
        "mfu_pct": None if peak is None else sig(100.0 * achieved / peak),
    }
