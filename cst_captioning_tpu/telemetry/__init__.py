"""Unified telemetry: span tracing, metrics registry, step phases, MFU.

One bundle (:class:`Telemetry`) threads through the components that host
an instrumentation point — trainer loop, loader prefetch, reward scoring,
checkpoint manager, resilience machinery — exactly the way ``FaultPlan``
threads: explicitly, no module globals, and a disabled instrument costs
its call site one is-None check (``OBSERVABILITY.md`` has the taxonomy
and overhead notes).

Pieces:

- :mod:`.spans`    — host-side span tracer, Chrome-trace JSON export
  (``--trace_dir``; view in Perfetto / chrome://tracing).
- :mod:`.registry` — counters/gauges/histograms with sink fan-out to
  metrics.jsonl (schema 2), TensorBoard, and a ``telemetry.json`` exit
  snapshot.
- :mod:`.phases`   — per-log-interval step-phase gauges
  (``data_wait_ms``/``compute_ms``/``score_ms``/``ckpt_ms``).
- :mod:`.flops`    — analytic model FLOPs + MFU (shared with bench.py).
"""

from __future__ import annotations

import os
from typing import Optional

from .flops import caption_step_flops, mfu_fields, peak_tflops
from .phases import STEP_PHASES, StepPhases
from .registry import (
    METRICS_SCHEMA,
    JsonlSink,
    MetricsRegistry,
    ScalarWriterSink,
)
from .lifecycle import LifecycleTracer
from .spans import NULL_SPAN, SpanTracer, trace_span

__all__ = [
    "METRICS_SCHEMA", "NULL_SPAN", "STEP_PHASES",
    "JsonlSink", "LifecycleTracer", "MetricsRegistry",
    "ScalarWriterSink", "SpanTracer",
    "StepPhases", "Telemetry",
    "caption_step_flops", "mfu_fields", "peak_tflops", "trace_span",
]


class Telemetry:
    """Registry (always) + optional tracer + optional phase timer.

    ``registry`` always exists — counters are how rare resilience events
    (rollbacks, quarantines, retries) become auditable, and they cost
    nothing per step.  ``tracer``/``phases`` stay None unless the
    telemetry flags enable them; hot-loop call sites hold the attribute
    in a local and branch on is-None (the ``--fault_plan`` pattern), so
    an un-instrumented run allocates nothing per step.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[SpanTracer] = None,
                 phases: Optional[StepPhases] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.tracer = tracer
        self.phases = phases
        self.snapshot_path: Optional[str] = None
        self._closed = False

    @classmethod
    def from_opts(cls, opt) -> "Telemetry":
        """Build from the CLI namespace: ``--trace_dir`` arms the span
        tracer, ``--step_timing`` (auto-on under --trace_dir) arms the
        phase gauges.  Sinks are attached later by the owner, once it
        knows whether this process is the pod's metrics writer."""
        tracer = None
        trace_dir = getattr(opt, "trace_dir", None)
        if trace_dir:
            tracer = SpanTracer(trace_dir)
        phases = None
        step_timing = getattr(opt, "step_timing", None)
        if step_timing is None:
            step_timing = tracer is not None
        if int(step_timing) or tracer is not None:
            phases = StepPhases(tracer)
        return cls(tracer=tracer, phases=phases)

    # -- convenience hooks -------------------------------------------------

    def span(self, name: str, **args):
        """Tracer span, or the shared no-op when tracing is off."""
        tracer = self.tracer
        if tracer is None:
            return NULL_SPAN
        return tracer.span(name, **args)

    def phase(self, name: str):
        """Phase-timed (and traced) interval; no-op when both are off."""
        phases = self.phases
        if phases is not None:
            return phases.phase(name)
        tracer = self.tracer
        if tracer is not None:
            return tracer.span(name)
        return NULL_SPAN

    def inc(self, name: str, n: float = 1) -> None:
        self.registry.inc(name, n)

    def declare(self, *names: str) -> None:
        """Pre-register counters at 0 (registry.declare passthrough) —
        every component that increments through this facade declares its
        names at attach time (enforced by cstlint:declared-counters)."""
        self.registry.declare(*names)

    def flush(self, fsync: bool = False) -> None:
        self.registry.flush(fsync=fsync)
        if self.tracer is not None and fsync:
            self.tracer.flush()

    def close(self, snapshot_path: Optional[str] = None) -> None:
        """Idempotent: flush sinks, write the exit telemetry.json (when a
        path was configured), close the tracer.  Safe from atexit."""
        if self._closed:
            return
        self._closed = True
        path = snapshot_path or self.snapshot_path
        if path:
            try:
                os.makedirs(os.path.dirname(os.path.abspath(path)),
                            exist_ok=True)
                self.registry.write_snapshot(path)
            except OSError:
                pass  # the snapshot is evidence, never a crash source
        self.registry.close()
        if self.tracer is not None:
            self.tracer.close()
