"""Fleet-wide observability plane (ISSUE 17 / OBSERVABILITY.md "Fleet
plane").

PR 16 made the failure domain the OS process; this module lifts the
per-process evidence discipline (PR 2 spans, PR 14 lifecycle) to the
fleet, in three layers driven from the supervisor's single-owner tick
loop:

- **Clock sync for trace stitching** (:class:`ClockSync`).  The
  supervisor timestamps a ``{"op": "ping"}`` to each live child; the
  echo carries the child's wall clock.  ``offset = child_wall -
  (wall_send + rtt/2)`` is a midpoint estimate whose uncertainty is
  bounded by ``rtt/2``; the best (min-RTT) sample per child *process*
  (keyed by pid, so a restart's fresh process is re-measured from
  scratch) lands in ``clock_sync.json`` — the skew table
  ``scripts/fleet_trace.py`` uses to rebase every child trace onto the
  supervisor's timeline and merge one Perfetto file with per-child
  process rows.

- **Continuous aggregation** (:class:`FleetObs` scraper).  On the
  ``--fleet_scrape_ms`` cadence the supervisor's snapshot of every
  replica (live OR restarting OR dead — one row per replica per sample,
  so the series has zero gaps across a child restart) is appended to a
  bounded in-memory ring and to the append-only ``fleet_metrics.jsonl``
  (schema-stamped lines; fsync'd periodically; rotation goes through
  ``os.replace`` + an ``atomic_json_write`` part index, so a crash can
  tear at most the final line of the active part).  Each sample carries
  fleet-wide and per-child p50/p99 latency, queue depth, slot
  occupancy, cache hit rate and attribution-component p99s — the feed
  the ROADMAP autoscaler consumes.  Stats queries and clock pings are
  paced per child through :class:`serving.policy.QueryPacer`, the SAME
  policy object family the supervisor's health poll uses.

- **SLO burn-rate monitor** (:class:`SLOMonitor`).  Declared
  objectives (p99 latency, availability, error rate) are evaluated
  over sliding fast/slow windows; an objective fires when BOTH windows
  burn the error budget faster than the threshold (the classic
  multi-window guard against one-bad-second pages).  Alerts are typed
  ``slo_alert`` lifecycle events, flip the fleet health worst-of to
  ``degraded`` while firing, append to ``slo_alerts.jsonl``, ride the
  blackbox out on exit, and gate ``serve_report``/``fleet_report``
  with exit 1.

Pure host code — importable by a supervisor process that never touches
an accelerator.  All time arithmetic goes through injected ``clock``
(monotonic, the supervisor's scheduling clock) and ``wall`` callables,
so tests drive the whole plane with fake clocks and the skew math never
touches ``time.time()`` literals on a deadline path.

Threading: everything here runs on the supervisor's tick thread except
:meth:`FleetObs.series`, which a report/debug caller may invoke from
another thread — hence the ring's named lock.  The ring lock is a near-
leaf: nothing is emitted or counted while holding it (LOCK_ORDER below
permits the registry leaf, and nothing else).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils.locksan import declare_order, named_lock

#: fleet_metrics.jsonl line format version (every line is stamped).
FLEET_METRICS_SCHEMA = 1

#: clock_sync.json format version.
CLOCK_SYNC_SCHEMA = 1

#: Registry counters this plane owns (declared at 0 when a registry is
#: attached; the table is test-pinned in OBSERVABILITY.md "Fleet
#: plane").
FLEETOBS_COUNTERS = (
    "fleet_samples",           # scrape sample rows appended
    "fleet_child_rows",        # per-child rows across all samples
    "fleet_stats_queries",     # {"op": "stats"} scrape queries sent
    "fleet_pings",             # clock-sync pings sent
    "fleet_ping_echoes",       # echoes folded into offset estimates
    "fleet_metric_rotations",  # fleet_metrics.jsonl part rotations
    "slo_alerts_fired",        # objective transitions into firing
    "slo_alerts_cleared",      # objective transitions back to ok
)

#: Declared acquisition order (cstlint:lock-order + runtime sanitizer):
#: the scraper ring lock may in principle be held into the registry
#: leaf; in practice nothing counts under the ring lock — the order is
#: declared so an accidental nesting fails loudly in the right
#: direction instead of deadlocking quietly in the wrong one.
LOCK_ORDER = ("telemetry.fleetobs.ring", "telemetry.registry")
declare_order(*LOCK_ORDER)

#: SLO objective names, in render order.
SLO_OBJECTIVES = ("p99", "availability", "error_rate")


class ClockSync:
    """Midpoint clock-offset estimation over the ping echo.

    One estimate per child *process* (keyed by the pid the echo
    carries): a restarted replica is a new process with a new clock, so
    it is re-measured from scratch — the PR 16 restart ladder never
    inherits a dead process's skew.  ``wall`` is the supervisor's wall
    clock callable (injectable for tests).
    """

    #: Pending pings are bounded: a child that never echoes must not
    #: grow supervisor memory.
    MAX_PENDING = 256

    def __init__(self, wall: Callable[[], float] = time.time):
        self.wall = wall
        self._pending: Dict[tuple, tuple] = {}  # (index, seq) -> (t0, wall_send)
        self._best: Dict[int, Dict[str, Any]] = {}  # pid -> best sample
        self._seq = 0

    def ping_payload(self, index: int, t0: float) -> Dict[str, Any]:
        """Build the wire ping for replica ``index`` sent at monotonic
        ``t0`` (the supervisor's clock), recording the matching wall
        read for the midpoint estimate."""
        self._seq += 1
        while len(self._pending) >= self.MAX_PENDING:
            self._pending.pop(next(iter(self._pending)))
        self._pending[(int(index), self._seq)] = (float(t0), self.wall())
        return {"op": "ping", "seq": self._seq, "t0": float(t0)}

    def on_echo(self, index: int, obj: Dict[str, Any],
                t1: float) -> Optional[Dict[str, Any]]:
        """Fold one echo received at monotonic ``t1`` into the per-pid
        estimate; returns the sample (or None for an unmatched echo)."""
        key = (int(index), int(obj.get("seq", -1)))
        rec = self._pending.pop(key, None)
        if rec is None:
            return None
        t0, wall_send = rec
        rtt = max(float(t1) - t0, 0.0)
        mid_wall = wall_send + rtt / 2.0
        child_wall = float(obj.get("wall", mid_wall))
        pid = int(obj.get("pid", -1))
        sample = {
            "index": int(index),
            "pid": pid,
            "skew_s": child_wall - mid_wall,
            "uncertainty_s": rtt / 2.0,
            "rtt_s": rtt,
            "samples": 1,
        }
        best = self._best.get(pid)
        if best is None or rtt < best["rtt_s"]:
            sample["samples"] = 1 if best is None else best["samples"] + 1
            self._best[pid] = sample
        else:
            best["samples"] += 1
        return sample

    def drop_pending(self, index: int) -> None:
        """Forget in-flight pings to replica ``index`` — called when
        its process is replaced (the echo would cross generations)."""
        idx = int(index)
        for key in [k for k in self._pending if k[0] == idx]:
            self._pending.pop(key, None)

    def skew_for_pid(self, pid: int) -> Optional[Dict[str, Any]]:
        return self._best.get(int(pid))

    def doc(self) -> Dict[str, Any]:
        """The ``clock_sync.json`` document fleet_trace.py consumes."""
        return {
            "schema": CLOCK_SYNC_SCHEMA,
            "supervisor_pid": os.getpid(),
            "written_wall_s": self.wall(),
            "children": {str(pid): dict(rec)
                         for pid, rec in sorted(self._best.items())},
        }


class SLOMonitor:
    """Sliding-window burn-rate evaluation of declared objectives.

    Objectives (any may be 0 = disabled):

    - ``p99_ms``: target p99 latency.  Error budget: 1% of requests may
      exceed it.  Burn = (fraction over target) / 0.01.
    - ``availability``: target success fraction (e.g. 0.99).  Budget =
      1 - target; burn = (error fraction) / budget.
    - ``error_rate``: max tolerated error fraction.  Burn = (error
      fraction) / target.

    An objective **fires** when both the fast and the slow window burn
    at >= ``burn_threshold`` with at least ``min_requests`` in the fast
    window; it **clears** when the fast window drops back under the
    threshold.  Transitions emit ``slo_alert`` lifecycle events (id is
    ``slo:<objective>`` — an event chain with no ``received``, which
    the accounting audit counts as truncated, never as a terminal
    violation) and are retained in :attr:`alerts` for the alert log and
    the blackbox.
    """

    def __init__(self, *, p99_ms: float = 0.0, availability: float = 0.0,
                 error_rate: float = 0.0, fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0, burn_threshold: float = 2.0,
                 min_requests: int = 12,
                 clock: Callable[[], float] = time.monotonic,
                 lifecycle=None, registry=None, max_outcomes: int = 65536):
        self.p99_ms = max(float(p99_ms), 0.0)
        self.availability = float(availability)
        self.error_rate = float(error_rate)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.burn_threshold = float(burn_threshold)
        self.min_requests = int(min_requests)
        self.clock = clock
        self._lifecycle = lifecycle
        self._registry = registry
        # (ts, ok, latency_ms) outcomes; trimmed to the slow window on
        # observe/evaluate, hard-bounded so a burst cannot grow memory.
        self._outcomes: deque = deque(maxlen=int(max_outcomes))
        self._firing: Dict[str, bool] = {}
        self._last_status: Dict[str, Any] = {"enabled": self.enabled,
                                             "firing": []}
        self.alerts: List[Dict[str, Any]] = []
        self.alerts_fired = 0
        self.alerts_cleared = 0

    @property
    def enabled(self) -> bool:
        return bool(self.p99_ms > 0 or self.availability > 0
                    or self.error_rate > 0)

    @property
    def alerting(self) -> bool:
        """True while any objective is firing — the fleet-health
        degraded flip reads this (a plain bool: no lock nesting)."""
        return any(self._firing.values())

    def observe(self, ok: bool, latency_ms: Optional[float],
                now: Optional[float] = None) -> None:
        """Record one request outcome (terminal answer at the
        supervisor: completed => ok, shed/expired/errored => not ok)."""
        if not self.enabled:
            return
        t = float(self.clock() if now is None else now)
        self._outcomes.append(
            (t, bool(ok),
             None if latency_ms is None else float(latency_ms)))
        self._trim(t)

    def _trim(self, now: float) -> None:
        horizon = now - self.slow_window_s
        while self._outcomes and self._outcomes[0][0] < horizon:
            self._outcomes.popleft()

    def _window(self, window_s: float, now: float) -> Dict[str, float]:
        lo = now - window_s
        n = errs = over = 0
        for ts, ok, lat in self._outcomes:
            if ts < lo:
                continue
            n += 1
            if not ok:
                errs += 1
            if self.p99_ms > 0 and lat is not None and lat > self.p99_ms:
                over += 1
        return {"n": n,
                "err_frac": (errs / n) if n else 0.0,
                "over_frac": (over / n) if n else 0.0}

    def _burn(self, objective: str, win: Dict[str, float]) -> float:
        if objective == "p99":
            return win["over_frac"] / 0.01 if self.p99_ms > 0 else 0.0
        if objective == "availability":
            budget = 1.0 - self.availability
            return (win["err_frac"] / budget
                    if 0.0 < self.availability < 1.0 else 0.0)
        budget = self.error_rate
        return win["err_frac"] / budget if budget > 0 else 0.0

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Recompute burns, run the firing state machine, return (and
        retain) the status doc the scrape rows and reports embed."""
        t = float(self.clock() if now is None else now)
        if not self.enabled:
            self._last_status = {"enabled": False, "firing": []}
            return self._last_status
        self._trim(t)
        fast = self._window(self.fast_window_s, t)
        slow = self._window(self.slow_window_s, t)
        objectives: Dict[str, Any] = {}
        for name in SLO_OBJECTIVES:
            target = {"p99": self.p99_ms, "availability": self.availability,
                      "error_rate": self.error_rate}[name]
            if not target:
                continue
            fast_burn = self._burn(name, fast)
            slow_burn = self._burn(name, slow)
            was = self._firing.get(name, False)
            if (not was and fast["n"] >= self.min_requests
                    and fast_burn >= self.burn_threshold
                    and slow_burn >= self.burn_threshold):
                self._transition(name, "firing", fast_burn, slow_burn,
                                 target, t)
            elif was and fast_burn < self.burn_threshold:
                self._transition(name, "cleared", fast_burn, slow_burn,
                                 target, t)
            objectives[name] = {
                "target": target,
                "fast_burn": round(fast_burn, 4),
                "slow_burn": round(slow_burn, 4),
                "firing": self._firing.get(name, False),
            }
        self._last_status = {
            "enabled": True,
            "burn_threshold": self.burn_threshold,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "window_n": fast["n"],
            "objectives": objectives,
            "firing": sorted(k for k, v in self._firing.items() if v),
            "alerts_fired": self.alerts_fired,
            "alerts_cleared": self.alerts_cleared,
        }
        return self._last_status

    def _transition(self, name: str, state: str, fast_burn: float,
                    slow_burn: float, target: float, t: float) -> None:
        firing = state == "firing"
        self._firing[name] = firing
        if firing:
            self.alerts_fired += 1
        else:
            self.alerts_cleared += 1
        alert = {"kind": "slo_alert", "objective": name, "state": state,
                 "target": target, "fast_burn": round(fast_burn, 4),
                 "slow_burn": round(slow_burn, 4), "t": t}
        self.alerts.append(alert)
        if self._registry is not None:
            self._registry.inc("slo_alerts_fired" if firing
                               else "slo_alerts_cleared")
        if self._lifecycle is not None:
            self._lifecycle.emit(
                "slo_alert", f"slo:{name}", ts=t, objective=name,
                state=state, target=target,
                fast_burn=round(fast_burn, 4),
                slow_burn=round(slow_burn, 4))

    def status(self) -> Dict[str, Any]:
        """The last evaluated status (blackbox provider)."""
        return dict(self._last_status)


class FleetObs:
    """The supervisor-side plane: scraper + clock sync + SLO monitor.

    Held by the supervisor as an optional collaborator (``None`` when
    unarmed — the house disabled-path rule: one is-None check per
    hook).  The supervisor calls, all from its tick thread:

    - :meth:`tick` once per supervisor tick (pings + scrape + SLO
      evaluation);
    - :meth:`on_ping` when a ping echo arrives on the wire;
    - :meth:`on_stats` when a stats reply arrives (marks the pacer ok);
    - :meth:`observe_request` at every terminal answer;
    - :meth:`on_child_assigned` when a replica gets a fresh process;
    - :meth:`close` on shutdown (final fsync + clock_sync.json).

    ``sup`` in :meth:`tick` is duck-typed: anything with
    ``scrape_snapshot()``, ``query_child(index, payload) -> bool`` and
    ``clock`` works — tests drive the plane with a stub.
    """

    def __init__(self, out_dir: str, *, scrape_interval_s: float = 1.0,
                 slo: Optional[SLOMonitor] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 registry=None, lifecycle=None, ring_len: int = 512,
                 rotate_rows: int = 100_000, fsync_every: int = 64):
        from ..serving.policy import QueryPacer

        self.out_dir = os.path.abspath(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self.metrics_path = os.path.join(self.out_dir, "fleet_metrics.jsonl")
        self.alerts_path = os.path.join(self.out_dir, "slo_alerts.jsonl")
        self.sync_path = os.path.join(self.out_dir, "clock_sync.json")
        self.scrape_interval_s = max(float(scrape_interval_s), 1e-6)
        self.slo = slo
        self.clock = clock
        self.wall = wall
        self._registry = registry
        self._lifecycle = lifecycle
        # One pacing policy family for everything timed (ISSUE 17
        # satellite): stats scrapes and clock pings each get a pacer on
        # the scrape cadence; the supervisor's health poll holds its own
        # QueryPacer on the health cadence.
        self.stats_pacer = QueryPacer(self.scrape_interval_s)
        self.ping_pacer = QueryPacer(self.scrape_interval_s)
        self.clock_sync = ClockSync(wall)
        self._ring_lock = named_lock("telemetry.fleetobs.ring")
        self._ring: deque = deque(maxlen=max(int(ring_len), 8))  # cstlint: guarded_by=self._ring_lock
        # Scrape/file state below is tick-thread-only (the supervisor
        # loop is the single owner; reports read files, not handles).
        self._seq = 0                  # cstlint: owned_by=supervisor_tick
        self._rows_in_part = 0         # cstlint: owned_by=supervisor_tick
        self._part = 0                 # cstlint: owned_by=supervisor_tick
        self._fh = None                # cstlint: owned_by=supervisor_tick
        self._alerts_written = 0       # cstlint: owned_by=supervisor_tick
        self._sync_dirty = False       # cstlint: owned_by=supervisor_tick
        self._closed = False           # cstlint: owned_by=supervisor_tick
        self.rotate_rows = max(int(rotate_rows), 16)
        self.fsync_every = max(int(fsync_every), 1)
        if registry is not None:
            registry.declare(*FLEETOBS_COUNTERS)
        if lifecycle is not None and slo is not None:
            lifecycle.attach(fleet_slo=slo.status)

    # -- supervisor hooks ---------------------------------------------------

    def tick(self, sup, now: float) -> None:
        """One observability turn: ping due children, scrape on the
        cadence, evaluate SLOs, drain alerts."""
        if self._closed:
            return
        snap = sup.scrape_snapshot()
        for child in snap["children"]:
            idx = child["index"]
            if not child["live"]:
                continue
            if self.ping_pacer.due(idx, now):
                payload = self.clock_sync.ping_payload(idx, t0=sup.clock())
                self.ping_pacer.sent(idx, now)
                if sup.query_child(idx, payload):
                    if self._registry is not None:
                        self._registry.inc("fleet_pings")
                else:
                    self.ping_pacer.failed(idx)
        if self.stats_pacer.due("#scrape", now):
            self.stats_pacer.sent("#scrape", now)
            if self.slo is not None:
                self.slo.evaluate(now)
            self._sample(snap, now)
            for child in snap["children"]:
                idx = child["index"]
                if not child["live"]:
                    continue
                if self.stats_pacer.due(idx, now):
                    self.stats_pacer.sent(idx, now)
                    if sup.query_child(idx, {"op": "stats"}):
                        if self._registry is not None:
                            self._registry.inc("fleet_stats_queries")
                    else:
                        self.stats_pacer.failed(idx)
            self._drain_alerts()
            if self._sync_dirty:
                self._write_clock_sync()

    def on_ping(self, index: int, obj: Dict[str, Any], t1: float) -> None:
        sample = self.clock_sync.on_echo(index, obj, t1)
        if sample is not None:
            self.ping_pacer.ok(index)
            self._sync_dirty = True
            if self._registry is not None:
                self._registry.inc("fleet_ping_echoes")

    def on_stats(self, index: int) -> None:
        self.stats_pacer.ok(index)

    def observe_request(self, ok: bool, latency_ms: Optional[float],
                        now: Optional[float] = None) -> None:
        if self.slo is not None:
            self.slo.observe(ok, latency_ms, now)

    def on_child_assigned(self, index: int) -> None:
        """A replica got a fresh OS process: its clocks, pacing history
        and in-flight pings belong to the dead one — reset, so the new
        process is pinged and scraped immediately (zero-gap contract)."""
        self.ping_pacer.forget(index)
        self.stats_pacer.forget(index)
        self.clock_sync.drop_pending(index)

    @property
    def alerting(self) -> bool:
        return self.slo is not None and self.slo.alerting

    def slo_status(self) -> Dict[str, Any]:
        return self.slo.status() if self.slo is not None else {
            "enabled": False, "firing": []}

    # -- sampling -----------------------------------------------------------

    def _sample(self, snap: Dict[str, Any], now: float) -> None:
        self._seq += 1
        children = [self._child_row(c) for c in snap["children"]]
        row = {
            "schema": FLEET_METRICS_SCHEMA,
            "kind": "fleet_sample",
            "seq": self._seq,
            "t": float(now),
            "wall": self.wall(),
            "interval_ms": self.scrape_interval_s * 1e3,
            "fleet": snap.get("fleet", {}),
            "children": children,
            "slo": self.slo_status(),
        }
        with self._ring_lock:
            self._ring.append(row)
        self._append_row(row)
        if self._registry is not None:
            self._registry.inc("fleet_samples")
            self._registry.inc("fleet_child_rows", len(children))

    @staticmethod
    def _child_row(child: Dict[str, Any]) -> Dict[str, Any]:
        """Shape one replica's scrape row from the supervisor snapshot
        (tolerant of missing stats — a child that has not answered yet
        still gets a row; the zero-gap contract is per replica, not per
        answer)."""
        st = child.get("stats") or {}
        row = {
            "index": child["index"],
            "state": child.get("state"),
            "live": bool(child.get("live")),
            "restarts": child.get("restarts", 0),
            "inflight": child.get("inflight", 0),
            "retiring": bool(child.get("retiring")),
            "queue_depth": st.get("queue_depth"),
            "latency_p50_ms": st.get("latency_p50_ms"),
            "latency_p99_ms": st.get("latency_p99_ms"),
            "compiles": st.get("compiles"),
        }
        slots = st.get("slots")
        residents = st.get("residents")
        if isinstance(slots, (int, float)) and slots:
            row["slot_occupancy"] = round(float(residents or 0)
                                          / float(slots), 4)
        hits = st.get("cache_hits")
        misses = st.get("cache_misses")
        if isinstance(hits, (int, float)) and isinstance(misses,
                                                         (int, float)):
            total = float(hits) + float(misses)
            row["cache_hit_rate"] = (round(float(hits) / total, 4)
                                     if total else None)
        attrib = st.get("attribution")
        if isinstance(attrib, dict):
            comps = attrib.get("components")
            if isinstance(comps, dict):
                row["attribution_p99_ms"] = {
                    c: v.get("p99_ms") for c, v in comps.items()
                    if isinstance(v, dict)}
        return row

    # -- durable output -----------------------------------------------------

    def _append_row(self, row: Dict[str, Any]) -> None:
        # Append-only JSONL: a crash tears at most the final line of
        # the active part; whole-file atomicity is reserved for the
        # rotation index and clock_sync.json (atomic_json_write).
        if self._fh is None:
            self._fh = open(self.metrics_path, "a", encoding="utf-8")
        self._fh.write(json.dumps(row, default=str) + "\n")
        self._fh.flush()
        self._rows_in_part += 1
        if self._rows_in_part % self.fsync_every == 0:
            os.fsync(self._fh.fileno())
        if self._rows_in_part >= self.rotate_rows:
            self._rotate()

    def _rotate(self) -> None:
        from ..resilience.integrity import atomic_json_write, durable_rename

        if self._fh is not None:
            os.fsync(self._fh.fileno())
            self._fh.close()
            self._fh = None
        part_path = os.path.join(
            self.out_dir, f"fleet_metrics_part{self._part}.jsonl")
        # durable_rename, not bare os.replace: the advisor-flagged
        # straggler — a crash between the rename and the index write
        # could journal the part's directory entry away.
        durable_rename(self.metrics_path, part_path)
        self._part += 1
        self._rows_in_part = 0
        atomic_json_write(
            os.path.join(self.out_dir, "fleet_metrics_index.json"),
            {"schema": FLEET_METRICS_SCHEMA,
             "parts": [f"fleet_metrics_part{k}.jsonl"
                       for k in range(self._part)],
             "active": os.path.basename(self.metrics_path)},
            indent=2)
        if self._registry is not None:
            self._registry.inc("fleet_metric_rotations")

    def _drain_alerts(self) -> None:
        if self.slo is None:
            return
        fresh = self.slo.alerts[self._alerts_written:]
        if not fresh:
            return
        with open(self.alerts_path, "a", encoding="utf-8") as f:
            for alert in fresh:
                f.write(json.dumps({**alert, "wall": self.wall()}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._alerts_written = len(self.slo.alerts)

    def _write_clock_sync(self) -> None:
        from ..resilience.integrity import atomic_json_write

        atomic_json_write(self.sync_path, self.clock_sync.doc(), indent=2)
        self._sync_dirty = False

    # -- views / shutdown ---------------------------------------------------

    def series(self) -> List[Dict[str, Any]]:
        """Snapshot of the in-memory sample ring (oldest first) — the
        autoscaler-facing view; reports read the JSONL instead."""
        with self._ring_lock:
            return [dict(r) for r in self._ring]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._drain_alerts()
        if self._sync_dirty or self.clock_sync._best:
            self._write_clock_sync()
        if self._fh is not None:
            try:
                os.fsync(self._fh.fileno())
            except OSError:
                pass
            self._fh.close()
            self._fh = None
