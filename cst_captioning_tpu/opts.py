"""CLI config — the reference's ``opts.py`` surface, TPU-backed.

One argparse namespace carries every knob (SURVEY.md §2 "CLI config"); flag
names follow the reference where known (``--train_feat_h5`` multi-valued,
``--train_label_h5``, ``--*_cocofmt_file``, ``--rnn_size``,
``--input_encoding_size``, ``--beam_size``, ``--train_cached_tokens``,
``--train_bcmrscores_pkl``, ``--checkpoint_path``, ``--start_from``,
``--result_file``, ``--eval_metric``...), with TPU-specific additions
(mesh size, bfloat16) grouped separately.  The namespace is JSON-serialized
into checkpoint infos so eval re-reads model hyperparams from the
checkpoint, not the CLI (SURVEY.md §5 config system).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence


def _add_data_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("data")
    for split in ("train", "val", "test"):
        g.add_argument(f"--{split}_feat_h5", nargs="+", default=None,
                       help=f"{split} feature h5 files, one per modality")
        g.add_argument(f"--{split}_label_h5", default=None)
        g.add_argument(f"--{split}_info_json", default=None,
                       help="vocab + video-id list for the split")
        g.add_argument(f"--{split}_cocofmt_file", default=None,
                       help="coco-format references for metric eval")
    g.add_argument("--train_cached_tokens", default=None,
                   help="precomputed CIDEr-D corpus document-frequency pickle")
    g.add_argument("--train_bcmrscores_pkl", default=None,
                   help="precomputed per-caption consensus CIDEr scores pickle")
    g.add_argument("--batch_size", type=int, default=64)
    g.add_argument("--eval_batch_size", type=int, default=0,
                   help="0 = use --batch_size")
    g.add_argument("--seq_per_img", type=int, default=20,
                   help="captions per video per batch")
    g.add_argument("--compile_cache_dir",
                   default="~/.cache/cst_captioning_tpu/xla",
                   help="JAX persistent compilation cache directory: repeat "
                        "CLI invocations (stage chains, eval after train) "
                        "reuse compiled programs instead of paying 20-40s "
                        "per program on TPU.  '' disables")
    g.add_argument("--device_feats", type=int, default=0,
                   help="1 = pin EVERY training video's features in device "
                        "HBM once (replicated over the mesh) and gather "
                        "them by video index inside the train step: no "
                        "per-batch feature h5 reads or host->device "
                        "transfers.  Needs the feature set to fit in HBM "
                        "(MSR-VTT ~0.8 GB in bf16); 0 = stream per batch "
                        "via the prefetch thread")
    g.add_argument("--device_cider_chunk_mb", type=float, default=256.0,
                   help="HBM budget for the on-device CIDEr-D hyp-ref match "
                        "transient; when batch x refs x lengths would exceed "
                        "it, the reward contraction is chunked over the "
                        "reference axis (bit-identical scores, bounded peak)")
    g.add_argument("--device_feats_max_gb", type=float, default=8.0,
                   help="startup guard for --device_feats: fail loudly when "
                        "the replicated feature table would exceed this many "
                        "GB PER DEVICE (the table is full-size on every "
                        "device regardless of mesh shape), instead of an "
                        "opaque device OOM mid-epoch")
    g.add_argument("--device_feats_upload_mb", type=float, default=64.0,
                   help="row-chunk size for the --device_feats table upload: "
                        "each host->device transfer stays under this many MB "
                        "(one monolithic multi-hundred-MB device_put wedged "
                        "a remote-tunnel transport; chunking also bounds "
                        "host RAM to ~one chunk and logs upload progress)")
    g.add_argument("--preload_feats", type=int, default=0,
                   help="1 = read all feature h5s into host RAM at startup "
                        "(removes per-batch disk IO; needs dataset-sized RAM)")
    # Sharded multi-worker data plane (data/sharding.py, data/loader.py).
    # String env defaults + argparse `type` = the PR-4 env discipline: a
    # malformed CST_LOADER_WORKERS/CST_DATA_SHARDS gets the same one-line
    # usage error as a malformed flag; tests/conftest.py pins all three
    # '' for hermeticity, beside CST_TUNED_CONFIGS.
    g.add_argument("--loader_workers",
                   type=_positive_int(
                       "--loader_workers (or CST_LOADER_WORKERS)"),
                   default=os.environ.get("CST_LOADER_WORKERS") or 1,
                   help="prefetch assembler threads feeding a bounded "
                        "ORDERED reassembly queue: batch order stays "
                        "bit-identical to the single-thread stream while "
                        "feature reads/packing/transfers overlap.  1 "
                        "(default) = the historical single prefetch "
                        "thread.  Env fallback: CST_LOADER_WORKERS")
    g.add_argument("--data_shards",
                   type=_nonneg_int("--data_shards (or CST_DATA_SHARDS)",
                                    "legacy per-process strided split"),
                   default=os.environ.get("CST_DATA_SHARDS") or 0,
                   help="explicit dataset shard count: the training "
                        "stream becomes this shard's strided slice of a "
                        "deterministic GLOBAL epoch shuffle — N shards "
                        "partition every epoch exactly (no dup, no "
                        "drop), and preempt-resume stays bit-identical "
                        "under any shard count (RESILIENCE.md 'Sharded "
                        "resume').  0 (default) = the legacy "
                        "process_index-strided split.  Env fallback: "
                        "CST_DATA_SHARDS")
    g.add_argument("--data_shard_id",
                   type=_nonneg_int(
                       "--data_shard_id (or CST_DATA_SHARD_ID)",
                       "the first shard"),
                   default=os.environ.get("CST_DATA_SHARD_ID") or 0,
                   help="which shard this run consumes; must satisfy "
                        "0 <= id < --data_shards.  Env fallback: "
                        "CST_DATA_SHARD_ID")


def _add_model_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("model")
    g.add_argument("--model_type", default="lstm",
                   choices=("lstm", "transformer"),
                   help="decoder family (transformer = driver config 5)")
    g.add_argument("--fusion_type", default="temporal",
                   choices=("temporal", "manet"),
                   help="attention memory: temporal frames (default) or "
                        "per-modality tokens (the reference's modality-"
                        "attention 'manet' variant)")
    g.add_argument("--rnn_size", type=int, default=512,
                   help="LSTM hidden size / transformer model dim")
    g.add_argument("--input_encoding_size", type=int, default=512,
                   help="word embedding size")
    g.add_argument("--num_layers", type=int, default=1)
    g.add_argument("--att_size", type=int, default=512,
                   help="additive-attention projection size")
    g.add_argument("--use_attention", type=int, default=1,
                   help="1 = attention-LSTM; 0 = reference mean-pool model")
    g.add_argument("--drop_prob", type=float, default=0.5)
    g.add_argument("--num_heads", type=int, default=8, help="transformer")
    g.add_argument("--num_tx_layers", type=int, default=2, help="transformer")
    g.add_argument("--use_bfloat16", type=int, default=0,
                   help="compute in bfloat16 (MXU-native) with fp32 params")
    g.add_argument("--bf16_feats", type=int, default=None,
                   help="cast features to bfloat16 on the HOST before the "
                        "device transfer — halves host->device feature "
                        "bytes.  Default: follow --use_bfloat16 (the model "
                        "casts features to its compute dtype on device "
                        "anyway, so this just moves the cast before the "
                        "wire); 0 forces f32 transfer")
    g.add_argument("--pallas_attention", type=int, default=0,
                   help="1 = fused Pallas VMEM attention kernel in the LSTM "
                        "decoder (interpret-mode off TPU)")
    g.add_argument("--remat_cell", type=int, default=DEFAULT_REMAT_CELL,
                   help="1 (default) = rematerialize the decoder cell in "
                        "backward: recompute the per-step attention/LSTM "
                        "instead of storing per-step residuals — less HBM "
                        "traffic and memory, measured faster on TPU "
                        "(PARITY.md); 0 = store residuals")
    g.add_argument("--scan_unroll", type=_positive_int("--scan_unroll"),
                   default=DEFAULT_SCAN_UNROLL,
                   help="decoder-scan unroll factor (teacher forcing + "
                        "sampling rollout): k steps per lax.scan iteration, "
                        "identical numerics, amortized per-step overhead.  "
                        "Must be >= 1.  Default = measured best on TPU "
                        "(PARITY.md; scripts/unroll_probe.py), or the "
                        "platform's tuning record when one exists")


def _add_optim_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("optimization")
    g.add_argument("--max_epochs", type=int, default=50)
    g.add_argument("--learning_rate", type=float, default=2e-4)
    g.add_argument("--optim", default="adam",
                   choices=("adam", "adamax", "adamw", "rmsprop", "sgd",
                            "adagrad"))
    g.add_argument("--grad_clip", type=float, default=10.0,
                   help="global-norm clip; 0 disables")
    g.add_argument("--learning_rate_decay_rate", type=float, default=0.8)
    g.add_argument("--learning_rate_decay_every", type=int, default=3,
                   help="epochs between staircase lr decays; 0 disables")
    g.add_argument("--max_patience", type=int, default=5,
                   help="early-stop epochs without val improvement; 0 = off")
    g.add_argument("--min_epochs", type=int, default=0,
                   help="early stop cannot fire before this many epochs "
                        "have run.  Guards small-steps-per-epoch runs "
                        "where val scores tie at ~0 for many early epochs "
                        "(greedy decode emits nothing scoreable yet), "
                        "which otherwise exhausts patience before "
                        "learning starts — observed at 64-video probe "
                        "scale (4 steps/epoch)")
    g.add_argument("--seed", type=int, default=123)


# Shipped CST defaults — bench.py reads BOTH so bare `python bench.py`
# always measures the shipped trainer configuration.
# DEFAULT_DEVICE_REWARDS = 1: the whole CST iteration runs as ONE XLA
# program with CIDEr-D computed on device (ops/jax_ciderd.py) — strictly
# on-policy AND ~2x the throughput of the depth-1 host pipeline on real
# hardware (PARITY.md measurement table).  --device_rewards 0 selects the
# host reward path, whose pipeline depth is DEFAULT_OVERLAP_REWARDS.
DEFAULT_DEVICE_REWARDS = 1

# Host-path reward-pipeline depth (--overlap_rewards).  2, not 1: every
# in-flight rollout's fetch starts its device->host copy at dispatch
# (pipeline.py copy_to_host_async), so depth 2 double-buffers the copies —
# step t's transfer+scoring hides behind rollouts t+1 AND t+2, which is
# what the measured tunnel numbers need (~60ms RTT + ~20ms scoring vs
# ~43ms device work: one step of overlap cannot cover the gap; two can).
# Staleness grows to <= 2 updates (stale-sample REINFORCE, PARITY.md).
DEFAULT_OVERLAP_REWARDS = 2

# Rollout early-exit chunk (--decode_chunk).  The sampler/greedy/beam
# scans stop launching chunks once every row (beam) has emitted EOS;
# healthy trained captions finish in ~7-10 of the 30 max_len steps, so
# the fused-scan chunks turn the dominant masked-dead rollout work into
# skipped work.  Chunked output is bit-identical to the legacy full-length
# scan (tests/test_decode_fastpath.py); 0 restores the legacy path.
DEFAULT_DECODE_CHUNK = 8

# Decoder-scan unroll (--scan_unroll): measured on TPU v5 lite
# (scripts/unroll_probe.py, table in PARITY.md); numerics are identical at
# any value, so this is purely a measured-throughput default.
DEFAULT_SCAN_UNROLL = 1

# Decode-step cell (--decode_kernel): the flax reference cell, or the
# fused Pallas attention+LSTM decode kernel (ops/pallas_decode_cell.py).
# ONE constant shared by opts and bench.resolve_axes, so bench always
# measures the cell train.py would run (flipping the shipped default can
# never desynchronize the two).
DEFAULT_DECODE_KERNEL = "reference"

# Decoder-cell rematerialization (--remat_cell): recompute the per-step
# attention/LSTM cell in backward instead of storing (L,B,T,A) f32
# residuals.  On TPU v5 lite this trades trivial recompute FLOPs for
# ~2GB/step of HBM residual traffic: XE 26.9 -> 21.0 ms/step (+28%),
# fused CST 52.3 -> 45.8 ms/step (+14%); gradients identical
# (tests/test_model.py::test_remat_cell_preserves_numerics).
DEFAULT_REMAT_CELL = 1


def _add_cst_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("CST / REINFORCE")
    g.add_argument("--use_rl", type=int, default=0,
                   help="1 = CST/REINFORCE stage (CIDEr-D reward)")
    g.add_argument("--rl_baseline", default="greedy",
                   choices=("greedy", "scb-sample", "scb-gt"),
                   help="advantage baseline: SCST greedy decode or "
                        "self-consensus variants (paper's SCB)")
    g.add_argument("--scb_captions", type=int, default=0,
                   help="top-k consensus captions for the scb-gt baseline; "
                        "0 = all")
    g.add_argument("--temperature", type=float, default=1.0,
                   help="multinomial sampling temperature")
    g.add_argument("--overlap_rewards", type=int,
                   default=DEFAULT_OVERLAP_REWARDS,
                   help="host-path (--device_rewards 0) CST pipeline depth: "
                        "number of rollouts kept in flight while the host "
                        "scores rewards.  0 = strict reference semantics "
                        "(rollout -> reward -> grad serially); k >= 1 "
                        "overlaps the reward of step t with rollouts "
                        "t+1..t+k, making samples up to k updates stale for "
                        "the grad step (PARITY.md).  Default 2 double-"
                        "buffers the device->host fetches (each starts "
                        "async at dispatch), hiding transfer + scoring "
                        "behind two rollouts; the fetch_wait_ms/score_ms "
                        "step-phase gauges (--step_timing) show where the "
                        "overlap lands.  Ignored under --device_rewards 1 "
                        "(nothing to overlap)")
    g.add_argument("--device_rewards", type=int,
                   default=DEFAULT_DEVICE_REWARDS,
                   help="1 (default) = compute CIDEr-D rewards ON DEVICE and "
                        "fuse the whole CST iteration (rollout+reward+grad) "
                        "into one XLA program — no host boundary, strict "
                        "on-policy; 0 = host reward path (C++/Python scorer "
                        "+ --overlap_rewards pipeline), the reference's "
                        "serial semantics at depth 0")
    g.add_argument("--native_cider", type=int, default=1,
                   help="1 = C++ CIDEr-D reward scorer (token-id fast path);"
                        " 0 = pure-Python scorer honoring --train_cached_tokens")
    g.add_argument("--use_consensus_weights", type=int, default=0,
                   help="1 = WXE: weight each caption's XE loss by its "
                        "consensus score (needs --train_bcmrscores_pkl)")
    g.add_argument("--consensus_temperature", type=float, default=1.0,
                   help="softmax temperature for WXE weight normalization")


def _positive_int(flag: str):
    """argparse type: integer >= 1, rejected with a one-line usage error
    naming the flag (the --fault_plan validator pattern)."""

    def parse(text: str) -> int:
        try:
            value = int(text)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"{flag} expects an integer, got {text!r}") from None
        if value < 1:
            raise argparse.ArgumentTypeError(
                f"{flag} must be a positive integer (>= 1), got {value}")
        return value

    return parse


def _nonneg_int(flag: str, zero_means: str):
    """argparse type: integer >= 0 (0 is a documented mode, not a typo)."""

    def parse(text: str) -> int:
        try:
            value = int(text)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"{flag} expects an integer, got {text!r}") from None
        if value < 0:
            raise argparse.ArgumentTypeError(
                f"{flag} must be >= 0 (0 = {zero_means}), got {value}")
        return value

    return parse


def _ratio(flag: str, zero_means: str):
    """argparse type: float in [0, 1) — SLO targets and tolerated
    fractions (0 is a documented disable; 1.0 would make the error
    budget zero, so it is rejected too)."""

    def parse(text: str) -> float:
        try:
            value = float(text)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"{flag} expects a number, got {text!r}") from None
        if not (0.0 <= value < 1.0):
            raise argparse.ArgumentTypeError(
                f"{flag} must be in [0, 1) (0 = {zero_means}), got {value}")
        return value

    return parse


def _add_decode_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("decoding")
    g.add_argument("--beam_size", type=int, default=5,
                   help="test-time beam width (1 = greedy)")
    g.add_argument("--val_beam_size", type=int, default=1,
                   help="validation decode width (greedy keeps epochs fast)")
    g.add_argument("--max_length", type=int, default=30,
                   help="maximum decode length")
    g.add_argument("--length_norm", type=float, default=0.0,
                   help="beam score length-normalization exponent; 0 = off")
    g.add_argument("--decode_chunk",
                   type=_nonneg_int("--decode_chunk",
                                    "legacy full-length scan"),
                   default=DEFAULT_DECODE_CHUNK,
                   help="early-exit decode: run rollout/greedy/beam scans "
                        "as a while-loop over fused scan chunks of this "
                        "many steps, stopping once every row (every beam) "
                        "has emitted EOS — a batch whose captions end at "
                        "step 9 pays 16 steps, not max_length.  Output is "
                        "bit-identical to the full-length scan at any "
                        "value; 0 = legacy single full-length scan")
    g.add_argument("--decode_kernel", default=DEFAULT_DECODE_KERNEL,
                   choices=("reference", "pallas", "bf16"),
                   help="decode-step cell for samplers/beam/eval decode: "
                        "'reference' = the flax cell; 'pallas' = the fused "
                        "VMEM attention+LSTM decode kernel "
                        "(ops/pallas_decode_cell.py; single-layer "
                        "attention-LSTM only, other configs fall back with "
                        "a log line); 'bf16' = the low-precision decode "
                        "variant (ops/bf16_decode.py: the same cell with "
                        "bfloat16 compute, fp32 carry/logits at the "
                        "boundary — parity-gated by scripts/bf16_parity.py "
                        "against the declared CIDEr delta bound, with "
                        "'reference' pinned as the bit-exact fallback).  "
                        "Swept by the autotuner; the platform's tuning "
                        "record may set it as the default (PARITY.md "
                        "'Tuned configs')")


def _validated_buckets(text: str) -> str:
    """argparse type for ``--serve_buckets``: grammar errors become a
    one-line usage error (the --fault_plan validator pattern).  The
    validated TEXT is returned; the engine re-parses it."""
    from .serving.buckets import parse_buckets

    try:
        parse_buckets(text)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e)) from None
    return text


def _add_serving_args(p: argparse.ArgumentParser) -> None:
    # Env fallbacks (CST_SERVE_*) resolve as argparse defaults so an
    # operator can pin a fleet-wide bucket ladder without editing every
    # launch line; tier-1 conftest force-clears them for hermeticity
    # (same discipline as CST_TUNED_CONFIGS).
    g = p.add_argument_group("serving")
    g.add_argument("--engine", default="legacy",
                   choices=("legacy", "serving"),
                   help="decode engine for eval.py: 'serving' routes the "
                        "test-split decode through the continuous-batching "
                        "engine (serving/engine.py) at batch-offline load "
                        "and asserts caption-for-caption equality with the "
                        "legacy compiled decode — the end-to-end parity "
                        "drill (SERVING.md)")
    g.add_argument("--serve_buckets", type=_validated_buckets,
                   default=os.environ.get("CST_SERVE_BUCKETS") or "1,4,8",
                   help="comma-separated batch-shape bucket ladder for the "
                        "serving engine, e.g. '1,4,8': programs compile "
                        "once per bucket, the engine grows to the smallest "
                        "bucket that fits demand and never compiles under "
                        "steady load (SERVING.md 'Bucket policy').  Env "
                        "fallback: CST_SERVE_BUCKETS")
    # String env default + argparse `type` = the PR-4 env discipline: a
    # malformed CST_SERVE_QUEUE_LIMIT gets the same one-line usage error
    # as a malformed flag (argparse runs `type` on string defaults),
    # never a parser-build traceback in CLIs that don't even serve.
    g.add_argument("--serve_queue_limit",
                   type=_nonneg_int(
                       "--serve_queue_limit (or CST_SERVE_QUEUE_LIMIT)",
                       "unbounded queue"),
                   default=os.environ.get("CST_SERVE_QUEUE_LIMIT") or 64,
                   help="bounded admission queue: submits beyond this "
                        "depth are SHED with an explicit reject response "
                        "(backpressure, never silent latency).  0 = "
                        "unbounded (offline/parity mode).  Env fallback: "
                        "CST_SERVE_QUEUE_LIMIT")
    g.add_argument("--serve_port", type=int, default=0,
                   help="scripts/serve.py front end: 0 (default) serves "
                        "JSONL on stdin/stdout; N > 0 listens on "
                        "127.0.0.1:N; -1 binds an ephemeral port "
                        "(announced on stderr)")
    g.add_argument("--serve_demo", type=int, default=0,
                   help="scripts/serve.py: 1 = zero-setup demo backend "
                        "(tiny untrained EOS-biased model + synthetic "
                        "feature table; captions are gibberish, the "
                        "serving path is real)")
    g.add_argument("--serve_demo_eos_bias", type=float, default=0.2,
                   help="scripts/serve.py --serve_demo 1: EOS-logit bias "
                        "of the demo model.  The default terminates demo "
                        "captions in a few steps (snappy demo); negative "
                        "values suppress EOS so captions run the full "
                        "--max_length — the drain/deadline chaos drills "
                        "use this to hold residents in flight "
                        "deterministically")
    g.add_argument("--serve_deadline_ms",
                   type=_nonneg_int(
                       "--serve_deadline_ms (or CST_SERVE_DEADLINE_MS)",
                       "no deadline"),
                   default=os.environ.get("CST_SERVE_DEADLINE_MS") or 0,
                   help="default per-request deadline: a request not "
                        "completed this many ms after submission is "
                        "EVICTED mid-flight (slot recycled, response "
                        "'expired'), and a queued request whose deadline "
                        "cannot cover one p99 decode chunk is shed "
                        "(SERVING.md 'Deadlines').  A per-request "
                        "'deadline_ms' in the JSONL op overrides.  0 = "
                        "no deadline.  Env fallback: CST_SERVE_DEADLINE_MS")
    g.add_argument("--serve_recover", type=int, default=1,
                   help="1 (default) = arm the self-healing scheduler "
                        "(scripts/serve.py): garbled or failing decode "
                        "chunks are re-run deterministically, escalating "
                        "to an engine rebuild from the warm program "
                        "cache, escalating to exit 124 for supervised "
                        "restart (RESILIENCE.md 'Serving faults').  "
                        "Trades the serving programs' buffer donation "
                        "for a re-runnable pre-chunk state.  0 = legacy "
                        "donated fast path, detection only")
    g.add_argument("--serve_retry_limit",
                   type=_nonneg_int("--serve_retry_limit",
                                    "escalate straight to rebuild"),
                   default=2,
                   help="deterministic chunk re-runs (and per-request "
                        "admission retries) before the self-healing "
                        "scheduler escalates to an engine rebuild")
    g.add_argument("--serve_rebuild_limit",
                   type=_nonneg_int("--serve_rebuild_limit",
                                    "never rebuild; fail immediately"),
                   default=2,
                   help="consecutive failed engine rebuilds before the "
                        "server gives up as unrecoverable and exits 124 "
                        "(wedge in the exit-code taxonomy) for "
                        "supervised restart")
    g.add_argument("--serve_step_budget_ms", type=float, default=0.0,
                   help="soft per-chunk latency budget: a decode chunk "
                        "slower than this marks health 'degraded' and "
                        "bumps serve_slow_chunks — the step-progress "
                        "wedge signal below the hard --wedge_timeout "
                        "kill.  0 disables")
    g.add_argument("--serve_cache",
                   type=_nonneg_int(
                       "--serve_cache (or CST_SERVE_CACHE)",
                       "result cache disabled"),
                   default=os.environ.get("CST_SERVE_CACHE") or 256,
                   help="exact-result cache capacity (entries): repeated "
                        "requests for the same video (zipfian traffic) "
                        "replay the cached caption instead of paying the "
                        "encoder + decode again — bit-identical by "
                        "construction, keyed by feature hash + the bench "
                        "cache-config identity + a params fingerprint so "
                        "a tuned-config, kernel, beam, or checkpoint "
                        "change invalidates correctly (SERVING.md "
                        "'Streaming & result cache').  Bounded LRU; 0 = "
                        "disabled.  Env fallback: CST_SERVE_CACHE")
    g.add_argument("--serve_replicas",
                   type=_positive_int(
                       "--serve_replicas (or CST_SERVE_REPLICAS)"),
                   default=os.environ.get("CST_SERVE_REPLICAS") or 2,
                   help="scripts/serve_fleet.py: engine replicas behind "
                        "the health-aware fleet router (serving/"
                        "fleet.py) — per-device where devices exist, "
                        "in-process otherwise; one shared ProgramCache "
                        "and result cache across all of them (SERVING.md "
                        "'Fleet').  Env fallback: CST_SERVE_REPLICAS")
    g.add_argument("--serve_restart_limit",
                   type=_nonneg_int("--serve_restart_limit",
                                    "one strike: a replica's first "
                                    "unplanned restart removes it"),
                   default=3,
                   help="unplanned supervised restarts (in-process exit "
                        "124 or hard kill) each fleet replica may spend "
                        "before it is removed from service; when every "
                        "replica is out, the fleet front end exits 124 "
                        "for whole-process supervised restart (SERVING.md "
                        "'Fleet').  Planned rotations are free")
    g.add_argument("--serve_heartbeat_file", default=None,
                   help="scripts/serve.py: write a liveness "
                        "heartbeat.json here (watchdog discipline: "
                        "atomic, fsync'd) carrying the serving health "
                        "payload — status, queue depth, recovery "
                        "counters, and in fleet mode the per_replica "
                        "breakdown — once per second, plus the hard "
                        "wedge kill when --wedge_timeout is set")
    g.add_argument("--serve_lifecycle", type=int, default=1,
                   help="1 (default) = arm the request-lifecycle tracing "
                        "plane (telemetry/lifecycle.py): every request's "
                        "journey (received/queued/routed/admitted/decode "
                        "chunks/recovery/requeue/terminal) lands in a "
                        "bounded in-memory flight recorder, the "
                        "{'op': 'stats'} view gains per-request latency "
                        "attribution, and the {'op': 'dump'} wire op / "
                        "exit-124 path / hard-abort drain write "
                        "blackbox.json (OBSERVABILITY.md 'Request "
                        "lifecycle & flight recorder').  0 = every hook "
                        "disarmed at one is-None check")
    g.add_argument("--serve_lifecycle_events",
                   type=_positive_int("--serve_lifecycle_events"),
                   default=4096,
                   help="flight-recorder ring capacity (events): fixed "
                        "host memory holding the last-N lifecycle "
                        "events the blackbox dumps")
    g.add_argument("--serve_blackbox", default="blackbox.json",
                   help="where the flight recorder writes its forensic "
                        "blackbox.json (atomic): on ServingUnrecoverable/"
                        "FleetUnrecoverable (exit 124), on a hard-abort "
                        "drain, and on the {'op': 'dump'} wire op.  "
                        "Empty = never write")
    g.add_argument("--serve_telemetry_file", default=None,
                   help="write the registry's atomic telemetry.json exit "
                        "snapshot here on drain/exit (the train.py "
                        "discipline, so serving chaos drills leave the "
                        "same machine-auditable artifact).  Default: "
                        "<checkpoint_path>/telemetry.json in checkpoint "
                        "mode, off in demo mode")
    g.add_argument("--supervise_replicas",
                   type=_positive_int(
                       "--supervise_replicas (or CST_SUPERVISE_REPLICAS)"),
                   default=os.environ.get("CST_SUPERVISE_REPLICAS") or 3,
                   help="scripts/serve_supervisor.py: OS-process serve.py "
                        "replicas under the process-fleet supervisor "
                        "(serving/supervisor.py) — each a real child "
                        "process speaking the JSONL wire over its own "
                        "localhost socket, restarted/retired by the exit "
                        "taxonomy with crash-proof requeue (SERVING.md "
                        "'Process fleet').  Env fallback: "
                        "CST_SUPERVISE_REPLICAS")
    g.add_argument("--supervise_restart_limit",
                   type=_nonneg_int(
                       "--supervise_restart_limit (or "
                       "CST_SUPERVISE_RESTART_LIMIT)",
                       "one strike: a replica's first fatal exit "
                       "removes it"),
                   default=os.environ.get("CST_SUPERVISE_RESTART_LIMIT")
                   or 3,
                   help="fatal child exits (exitcodes classify 'fatal': "
                        "1, 130, uncatalogued) each supervised replica "
                        "may spend before it is dead; resumable (75/137/"
                        "143) and wedge (124) exits restart free with "
                        "bounded backoff.  All replicas dead = the "
                        "supervisor itself exits 124.  Env fallback: "
                        "CST_SUPERVISE_RESTART_LIMIT")
    g.add_argument("--supervise_backoff_ms",
                   type=_nonneg_int(
                       "--supervise_backoff_ms (or "
                       "CST_SUPERVISE_BACKOFF_MS)",
                       "restarts respawn immediately"),
                   default=os.environ.get("CST_SUPERVISE_BACKOFF_MS")
                   or 200,
                   help="base child-restart backoff (milliseconds): "
                        "doubles per consecutive death (capped at 25x) "
                        "and resets when the replica next completes a "
                        "request.  Env fallback: CST_SUPERVISE_BACKOFF_MS")
    g.add_argument("--supervise_dir", default=None,
                   help="scripts/serve_supervisor.py: root directory for "
                        "per-replica child workdirs (replica<K>/ with "
                        "blackbox.json, heartbeat.json, telemetry.json, "
                        "stderr.log) and the incidents/ evidence bundles "
                        "harvested from dead replicas (RESILIENCE.md "
                        "'Process faults').  Default: a fresh temp dir")
    g.add_argument("--supervise_probe", type=int, default=0,
                   help="1 = scripts/serve_supervisor.py runs the seeded "
                        "process-chaos drill instead of serving: N "
                        "replicas, proc_kill@replica=1 mid-stream, every "
                        "request answered, captions checked bit-identical "
                        "against a fault-free single-engine reference, "
                        "zero post-warmup compiles per surviving child, "
                        "blackbox harvested from the killed replica; "
                        "emits the benchmark record line")
    g.add_argument("--fleet_scrape_ms",
                   type=_positive_int(
                       "--fleet_scrape_ms (or CST_FLEET_SCRAPE_MS)"),
                   default=os.environ.get("CST_FLEET_SCRAPE_MS") or 1000,
                   help="scripts/serve_supervisor.py: fleet-observability "
                        "scrape cadence (milliseconds) — every interval "
                        "the supervisor snapshots ALL replica slots (live "
                        "or not: zero-gap series), appends a schema-"
                        "stamped line to <--supervise_dir>/"
                        "fleet_metrics.jsonl, paces per-child "
                        "{'op': 'stats'} queries and clock-sync pings "
                        "(OBSERVABILITY.md 'Fleet plane').  Env fallback: "
                        "CST_FLEET_SCRAPE_MS")
    g.add_argument("--slo_p99_ms",
                   type=_nonneg_int("--slo_p99_ms (or CST_SLO_P99_MS)",
                                    "p99 latency objective disabled"),
                   default=os.environ.get("CST_SLO_P99_MS") or 0,
                   help="SLO: target p99 request latency (ms); error "
                        "budget is the 1%% of requests allowed over it.  "
                        "Fires a burn-rate slo_alert (fast AND slow "
                        "window over threshold), flips fleet health to "
                        "'degraded', gates serve_report/fleet_report "
                        "exit 1 (OBSERVABILITY.md 'Fleet plane').  0 = "
                        "disabled.  Env fallback: CST_SLO_P99_MS")
    g.add_argument("--slo_availability",
                   type=_ratio("--slo_availability (or "
                               "CST_SLO_AVAILABILITY)",
                               "availability objective disabled"),
                   default=os.environ.get("CST_SLO_AVAILABILITY") or 0.0,
                   help="SLO: target success fraction in [0, 1), e.g. "
                        "0.99; the error budget is 1 - target and burn = "
                        "error_fraction / budget over the sliding "
                        "windows.  0 = disabled.  Env fallback: "
                        "CST_SLO_AVAILABILITY")
    g.add_argument("--slo_error_rate",
                   type=_ratio("--slo_error_rate (or CST_SLO_ERROR_RATE)",
                               "error-rate objective disabled"),
                   default=os.environ.get("CST_SLO_ERROR_RATE") or 0.0,
                   help="SLO: max tolerated error fraction in [0, 1); "
                        "burn = error_fraction / target over the sliding "
                        "windows.  0 = disabled.  Env fallback: "
                        "CST_SLO_ERROR_RATE")
    g.add_argument("--autoscale_min",
                   type=_positive_int(
                       "--autoscale_min (or CST_AUTOSCALE_MIN)"),
                   default=os.environ.get("CST_AUTOSCALE_MIN") or 1,
                   help="autoscaler (serving/autoscale.py): the fleet "
                        "never shrinks below this many replicas; with "
                        "--autoscale_max > 0 the fleet also STARTS "
                        "here.  Env fallback: CST_AUTOSCALE_MIN")
    g.add_argument("--autoscale_max",
                   type=_nonneg_int(
                       "--autoscale_max (or CST_AUTOSCALE_MAX)",
                       "autoscaler disabled (fixed-size fleet)"),
                   default=os.environ.get("CST_AUTOSCALE_MAX") or 0,
                   help="autoscaler: the ARM switch + upper bound — 0 "
                        "(default) = fixed --supervise_replicas fleet; "
                        "N >= --autoscale_min = grow/shrink between the "
                        "bounds from latency attribution (queue_wait "
                        "p99 burning while decode p99 stays flat adds a "
                        "replica; a full quiet slow window retires one) "
                        "and enter the brownout ladder when pinned at "
                        "max (SERVING.md 'Autoscaling & brownout').  "
                        "Env fallback: CST_AUTOSCALE_MAX")
    g.add_argument("--autoscale_queue_hi_ms",
                   type=_positive_int(
                       "--autoscale_queue_hi_ms "
                       "(or CST_AUTOSCALE_QUEUE_HI_MS)"),
                   default=(os.environ.get("CST_AUTOSCALE_QUEUE_HI_MS")
                            or 50),
                   help="autoscaler: queue_wait-attribution p99 (ms) "
                        "over which the dual-window up-signal burns; "
                        "the down-signal's quiet threshold is a tenth "
                        "of this (hysteresis).  Env fallback: "
                        "CST_AUTOSCALE_QUEUE_HI_MS")
    g.add_argument("--autoscale_up_cooldown_s",
                   type=_nonneg_int(
                       "--autoscale_up_cooldown_s "
                       "(or CST_AUTOSCALE_UP_COOLDOWN_S)",
                       "no scale-up cooldown"),
                   default=(os.environ.get("CST_AUTOSCALE_UP_COOLDOWN_S")
                            or 2),
                   help="autoscaler: seconds between scale-ups (thrash "
                        "damping; held decisions are counted, not "
                        "lost).  Env fallback: "
                        "CST_AUTOSCALE_UP_COOLDOWN_S")
    g.add_argument("--autoscale_down_cooldown_s",
                   type=_nonneg_int(
                       "--autoscale_down_cooldown_s "
                       "(or CST_AUTOSCALE_DOWN_COOLDOWN_S)",
                       "no scale-down cooldown"),
                   default=(os.environ.get(
                       "CST_AUTOSCALE_DOWN_COOLDOWN_S") or 10),
                   help="autoscaler: seconds between scale-downs — "
                        "deliberately longer than the up cooldown "
                        "(shrinking is cheap to defer, growing is "
                        "not).  Env fallback: "
                        "CST_AUTOSCALE_DOWN_COOLDOWN_S")
    g.add_argument("--autoscale_probe", type=int, default=0,
                   help="1 = scripts/serve_supervisor.py runs the "
                        "seeded 3-phase autoscale drill (idle -> 4x "
                        "burst -> idle) instead of serving: the fleet "
                        "starts at --autoscale_min, scales up within "
                        "the scrape budget, scales back down, every "
                        "request answered exactly once bit-identical "
                        "to a fixed-size fault-free reference, zero "
                        "post-warmup compiles on surviving children; "
                        "emits the benchmark record line")
    g.add_argument("--journal_dir",
                   default=os.environ.get("CST_JOURNAL_DIR") or None,
                   help="scripts/serve_supervisor.py: ARM the durable "
                        "intake journal (serving/journal.py) in this "
                        "directory — every accepted request is fsync'd "
                        "BEFORE placement, stream chunks and terminal "
                        "answers at send time, and a relaunch pointed "
                        "at the same directory replays unanswered "
                        "requests (TTLs preserved), answers duplicate "
                        "idempotency keys from the record with zero "
                        "decode work, and resumes streams from the "
                        "journaled watermark (SERVING.md 'Durable "
                        "intake journal').  Default off.  Env "
                        "fallback: CST_JOURNAL_DIR")
    g.add_argument("--journal_segment_bytes",
                   type=_positive_int(
                       "--journal_segment_bytes "
                       "(or CST_JOURNAL_SEGMENT_BYTES)"),
                   default=(os.environ.get("CST_JOURNAL_SEGMENT_BYTES")
                            or 1048576),
                   help="intake journal: rotate the active write-ahead "
                        "segment after it passes this many bytes "
                        "(rotation seals it through "
                        "integrity.durable_rename; with compaction on, "
                        "terminal records retire their entries so disk "
                        "stays bounded).  Env fallback: "
                        "CST_JOURNAL_SEGMENT_BYTES")
    g.add_argument("--journal_compact",
                   type=_nonneg_int(
                       "--journal_compact (or CST_JOURNAL_COMPACT)",
                       "keep every sealed segment (no compaction)"),
                   default=os.environ.get("CST_JOURNAL_COMPACT") or 1,
                   help="intake journal: 1 (default) = fold sealed "
                        "segments into one compact file at every "
                        "rotation, retiring journaled-terminal "
                        "entries; 0 = keep every sealed segment (the "
                        "forensic mode — disk grows with traffic).  "
                        "Env fallback: CST_JOURNAL_COMPACT")
    g.add_argument("--journal_probe", type=int, default=0,
                   help="1 = scripts/serve_supervisor.py runs the "
                        "supervisor-death drill instead of serving: "
                        "storm a journal-armed supervisor subprocess "
                        "with streams in flight, SIGKILL the "
                        "SUPERVISOR (not a child) mid-storm, relaunch "
                        "on the same --journal_dir, and pin every "
                        "accepted request answered exactly once, "
                        "captions bit-identical to a fault-free "
                        "single-engine twin, stream prefixes "
                        "consistent across the crash, duplicate ids "
                        "answered from the journal, zero post-warmup "
                        "compiles; emits the benchmark record line")


def _add_bookkeeping_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("bookkeeping")
    g.add_argument("--checkpoint_path", default="checkpoints/run",
                   help="checkpoint directory for this stage")
    g.add_argument("--start_from", default=None,
                   help="warm-start params from this stage dir's BEST "
                        "checkpoint (XE->WXE->CST chaining)")
    g.add_argument("--result_file", default=None,
                   help="where eval writes the scores JSON")
    g.add_argument("--eval_metric", default="CIDEr")
    g.add_argument("--fast_val", type=int, default=0,
                   help="1 = validation scores CIDEr only")
    g.add_argument("--max_checkpoints", type=int, default=2)
    g.add_argument("--log_every", type=int, default=20, help="steps")
    g.add_argument("--loglevel", default="INFO")
    g.add_argument("--save_every_steps", type=int, default=0,
                   help="extra checkpoint every N steps for failure "
                        "recovery (0 = epoch boundaries only)")
    g.add_argument("--save_interval_secs", type=float, default=0.0,
                   help="wall-clock twin of --save_every_steps: force a "
                        "recovery checkpoint when this many seconds have "
                        "passed since the last save of any kind, so long "
                        "CST stages bound preemption/crash loss by TIME "
                        "even when step rate drifts.  Checked at step "
                        "boundaries (real cadence = max(interval, one "
                        "step)); 0 disables")
    g.add_argument("--wedge_timeout", type=float, default=0.0,
                   help="seconds without training-loop progress before the "
                        "process exits with status 124 for checkpointed "
                        "resume (utils/watchdog.py). A remote-device "
                        "transport that wedges mid-step blocks forever in a "
                        "C++ call no exception can unwind; with "
                        "--save_every_steps, dying fast and resuming is "
                        "cheap while hanging costs the whole run. Set above "
                        "the worst legitimate gap (first remote compile can "
                        "take minutes); 0 disables")
    g.add_argument("--tensorboard", type=int, default=0,
                   help="1 = write TensorBoard scalars under "
                        "<checkpoint_path>/tb (train metrics + val scores); "
                        "a metrics.jsonl is always written regardless")
    g.add_argument("--profile_dir", default=None,
                   help="capture a jax.profiler trace of a few steady-state "
                        "steps into this directory (view with TensorBoard)")
    g.add_argument("--profile_start", type=int, default=10,
                   help="step at which the profiler trace starts")
    g.add_argument("--profile_steps", default="10",
                   help="either a step COUNT (trace --profile_start ..+N, "
                        "the historical form) or an explicit 'A:B' window "
                        "tracing steps A..B-1 (ignores --profile_start)")
    g.add_argument("--trace_dir", default=None,
                   help="write host-side span traces (data_wait/compute/"
                        "score/ckpt + loader prefetch + checkpoint commit) "
                        "to this directory as Chrome-trace JSON — load in "
                        "Perfetto or chrome://tracing (OBSERVABILITY.md).  "
                        "Implies --step_timing.  Unset = every span hook "
                        "disarmed at one is-None check")
    g.add_argument("--step_timing", type=int, default=None,
                   help="1 = per-log-interval step-phase gauges "
                        "(data_wait_ms/compute_ms/score_ms/ckpt_ms) and "
                        "live mfu_pct in metrics.jsonl, without span "
                        "tracing.  Default: on when --trace_dir is set, "
                        "else off (zero per-step overhead)")
    g.add_argument("--debug_nans", type=int, default=0,
                   help="1 = jax_debug_nans (crash on the FIRST NaN with a "
                        "traceback; debugging mode).  Mutually exclusive "
                        "with --divergence_guard: the crash preempts the "
                        "guard's skip-and-rollback, so setting both warns "
                        "and disables the guard")


def _validated_fault_plan(text: str) -> str:
    """argparse type for ``--fault_plan``: grammar errors become a
    single-line usage error naming the bad token and the expected grammar
    (argparse prints it and exits 2) instead of a Trainer-startup
    traceback.  The validated TEXT is returned — the trainer re-parses it
    into its own consumable plan instance."""
    from .resilience.faults import FaultPlan

    try:
        FaultPlan.parse(text)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e)) from None
    return text


def _add_resilience_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("resilience")
    g.add_argument("--divergence_guard", type=int, default=1,
                   help="1 (default) = fold a finite-check of loss + grad "
                        "global-norm into the compiled train step: a "
                        "non-finite step is skipped ON DEVICE (params/"
                        "optimizer state keep their pre-step values) and "
                        "after --divergence_max_bad consecutive bad steps "
                        "the trainer rolls back to the last verified "
                        "checkpoint with a re-seeded rollout key stream.  "
                        "Disabled automatically under --debug_nans "
                        "(which crashes on the first NaN instead)")
    g.add_argument("--divergence_max_bad", type=int, default=3,
                   help="consecutive non-finite steps before the guard "
                        "rolls back to the last known-good checkpoint")
    g.add_argument("--divergence_max_rollbacks", type=int, default=2,
                   help="rollbacks before the run aborts as unrecoverable "
                        "(a deterministic divergence would otherwise "
                        "replay forever)")
    g.add_argument("--abort_on_negative_advantage_window", type=int,
                   default=0,
                   help="1 = abort the run (train.py exit 4) when the "
                        "negative-advantage regime detector fires: every "
                        "logged advantage in the rolling window negative "
                        "with mean < -0.05 means the baseline dominates "
                        "the samples and REINFORCE can only suppress "
                        "typical sequences — an unattended chain should "
                        "stop and surface the collapsing stage instead of "
                        "burning its chip window on it (remedies in the "
                        "abort message: scb-sample baseline, lower "
                        "temperature/lr).  0 (default) = warn once and "
                        "continue")
    # The env-var fallback is resolved HERE, as the argparse default, so a
    # malformed CST_FAULT_PLAN gets the same one-line usage error as a
    # malformed --fault_plan (argparse runs `type` on string defaults)
    # instead of a Trainer-startup traceback.
    g.add_argument("--fault_plan",
                   default=os.environ.get("CST_FAULT_PLAN") or None,
                   type=_validated_fault_plan,
                   help="CHAOS TESTING ONLY: comma-separated deterministic "
                        "fault specs injected into this run, e.g. "
                        "'ckpt_torn@step=40,nan_grad@step=55,"
                        "loader_err@batch=12,wedge@step=70,preempt@step=80' "
                        "(kind@step=N, kind@batch=N, or kind@step=N*K for "
                        "K consecutive firings; grammar + taxonomy in "
                        "RESILIENCE.md).  Malformed specs are rejected "
                        "here, at parse time, with a one-line usage error. "
                        "Falls back to the CST_FAULT_PLAN env var; unset = "
                        "every hook disarmed at zero cost")


def _add_tpu_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("tpu / parallelism")
    g.add_argument("--num_devices", type=int, default=0,
                   help="devices in the data-parallel mesh; 0 = all")
    g.add_argument("--coordinator_address", default=None,
                   help="multi-host: jax.distributed coordinator")
    g.add_argument("--num_processes", type=int, default=0,
                   help="multi-host: total process count; 0 = single host")
    g.add_argument("--process_id", type=int, default=0)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native consensus-based sequence training "
                    "for video captioning",
        fromfile_prefix_chars="@",
    )
    _add_data_args(p)
    _add_model_args(p)
    _add_optim_args(p)
    _add_cst_args(p)
    _add_decode_args(p)
    _add_serving_args(p)
    _add_bookkeeping_args(p)
    _add_resilience_args(p)
    _add_tpu_args(p)
    return p


def _explicit_flags(argv: Optional[Sequence[str]]) -> set:
    """Which tunable axes the user set EXPLICITLY on this command line.

    A second mini-parser with SUPPRESS defaults (sharing the main parser's
    @file expansion and abbreviation rules) is the one argparse-honest way
    to tell "came from the CLI" apart from "came from a default" — tuned
    defaults must never override an operator's explicit choice.
    """
    aux = argparse.ArgumentParser(add_help=False, fromfile_prefix_chars="@")
    for axis in ("decode_chunk", "scan_unroll", "overlap_rewards",
                 "device_rewards", "decode_kernel", "serve_replicas",
                 "supervise_replicas"):
        aux.add_argument(f"--{axis}", default=argparse.SUPPRESS)
    try:
        ns, _ = aux.parse_known_args(argv)
    except SystemExit:  # pragma: no cover - main parse already errored
        return set()
    return set(vars(ns))


def apply_tuned_defaults(ns: argparse.Namespace,
                         argv: Optional[Sequence[str]] = None,
                         record_path: Optional[str] = None) -> None:
    """Resolve the platform tuning record into ``ns`` IN PLACE.

    Resolution order per axis (PARITY.md "Tuned configs"):
    explicit CLI flag > tuning record winner > built-in default.  The
    outcome is stamped on ``ns.tuned_provenance`` (JSON-serializable — it
    rides into checkpoint infos and the telemetry.json snapshot) so every
    run is auditable: which axes came from the record, which record,
    measured at which git SHA, and whether that SHA still matches HEAD.
    A missing/disabled/incomplete record leaves ``ns`` untouched with
    ``{"tuned": False}``.
    """
    if argv is None:
        argv = sys.argv[1:]
    from .tuning.record import resolved_tuned_defaults

    tuned, provenance = resolved_tuned_defaults(path=record_path)
    applied = {}
    if tuned:
        explicit = _explicit_flags(argv)
        for axis, value in tuned.items():
            if axis in explicit or not hasattr(ns, axis):
                continue
            setattr(ns, axis, value)
            applied[axis] = value
    if applied and provenance is not None:
        ns.tuned_provenance = {"tuned": True, "applied": applied,
                               **provenance}
    else:
        ns.tuned_provenance = {"tuned": False}


_warned_overlap_ignored = False


def _warn_overlap_under_device_rewards(ns: argparse.Namespace,
                                       argv: Optional[Sequence[str]]) -> None:
    """--overlap_rewards only exists on the host reward path; under the
    fused --device_rewards 1 step there is no host boundary to overlap.
    An explicitly-set value that will be ignored gets ONE stderr line
    (not silence, not a per-step nag)."""
    global _warned_overlap_ignored
    if _warned_overlap_ignored:
        return
    if argv is None:
        argv = sys.argv[1:]
    if not int(getattr(ns, "device_rewards", 0)):
        return
    if "overlap_rewards" in _explicit_flags(argv):
        _warned_overlap_ignored = True
        print("warning: --overlap_rewards is ignored under "
              "--device_rewards 1 (the fused step has no host reward "
              "boundary to overlap); pass --device_rewards 0 to use the "
              "host pipeline", file=sys.stderr)


_warned_serving_chunk = False


def warn_serving_decode_chunk(ns: argparse.Namespace) -> None:
    """--decode_chunk 0 (legacy full-length scan) combined with the
    serving engine: slot recycling needs the chunked while_loop path —
    with chunk 0 a slot only frees at a full max_length boundary, so one
    long caption holds every co-resident slot hostage.  ONE stderr line
    (argparse-usage style), not silence and not a per-request nag; the
    engine still runs, treating the rollout as a single max_length chunk."""
    global _warned_serving_chunk
    if _warned_serving_chunk:
        return
    if int(getattr(ns, "decode_chunk", 0)) == 0:
        _warned_serving_chunk = True
        print("warning: --decode_chunk 0 (legacy full-length scan) with "
              "the serving engine disables mid-flight slot recycling — "
              "slots only free every --max_length steps; pass a chunked "
              "--decode_chunk (e.g. 8) for continuous batching",
              file=sys.stderr)


_warned_stream_legacy = False


def warn_stream_legacy_scan() -> None:
    """``{"op": "stream"}`` traffic on an engine configured with
    ``--decode_chunk 0``: the legacy full-length scan has no mid-caption
    chunk boundary, so every token is harvested at once and "streaming"
    degenerates to ONE terminal chunk after the whole decode.  Called by
    the serving front end on the first stream request it sees in that
    configuration — one stderr line naming the fix (the --decode_chunk-0
    serving warn-once pattern), not silence and not a per-request nag."""
    global _warned_stream_legacy
    if _warned_stream_legacy:
        return
    _warned_stream_legacy = True
    print("warning: {\"op\": \"stream\"} with --decode_chunk 0 (legacy "
          "full-length scan) emits everything at once — streaming "
          "degenerates to one terminal chunk; pass a chunked "
          "--decode_chunk (e.g. 8) to stream tokens per chunk",
          file=sys.stderr)


_warned_serve_deadline = False


def warn_serve_deadline(ns: argparse.Namespace) -> None:
    """A request deadline below ONE decode-chunk budget can never be met:
    the scheduler's smallest unit of service is one compiled chunk over
    the slot batch, and the largest serve bucket pays the most per chunk
    — so with ``--serve_deadline_ms`` under ``--serve_step_budget_ms``
    (the operator's own per-chunk latency budget) every request is
    destined for the expired/shed path.  ONE stderr line at startup (the
    --decode_chunk-0 warn-once pattern), not silence and not a
    per-request nag; the server still runs, honoring the configured
    deadline literally."""
    global _warned_serve_deadline
    if _warned_serve_deadline:
        return
    deadline = float(getattr(ns, "serve_deadline_ms", 0) or 0)
    budget = float(getattr(ns, "serve_step_budget_ms", 0) or 0)
    if 0 < deadline < budget:
        _warned_serve_deadline = True
        try:
            from .serving.buckets import parse_buckets

            largest = parse_buckets(ns.serve_buckets)[-1]
            bucket = f"the largest serve bucket ({largest} slots)"
        except (ValueError, AttributeError):
            bucket = "the largest serve bucket"
        print(f"warning: --serve_deadline_ms {deadline:g} is below one "
              f"decode-chunk budget (--serve_step_budget_ms {budget:g}) "
              f"for {bucket} — such a deadline can never be met; every "
              "request will expire or be shed before completing",
              file=sys.stderr)


_warned_supervise_conflict = False


def warn_supervise_conflict(ns: argparse.Namespace,
                            argv: Optional[Sequence[str]] = None) -> None:
    """--serve_replicas (the IN-PROCESS fleet, scripts/serve_fleet.py)
    and --supervise_replicas (the OS-PROCESS fleet, scripts/
    serve_supervisor.py) size different topologies; each front end reads
    only its own knob.  Both set explicitly in one invocation almost
    always means the operator grabbed the wrong flag — ONE stderr line
    naming which knob this front end honors (the --overlap_rewards
    warn-once pattern), not silence and not an error."""
    global _warned_supervise_conflict
    if _warned_supervise_conflict:
        return
    if argv is None:
        argv = sys.argv[1:]
    explicit = _explicit_flags(argv)
    if "serve_replicas" in explicit and "supervise_replicas" in explicit:
        _warned_supervise_conflict = True
        print("warning: both --serve_replicas (in-process fleet, "
              "serve_fleet.py) and --supervise_replicas (OS-process "
              "fleet, serve_supervisor.py) are set; each front end "
              "honors only its own flag — the other is ignored",
              file=sys.stderr)


def _validate_shard_flags(parser: argparse.ArgumentParser,
                          ns: argparse.Namespace) -> None:
    """Cross-field shard validation as a one-line usage error (the
    --fault_plan pattern): per-flag `type` validators can't see each
    other, so the 0 <= id < shards relation is checked post-parse."""
    shards = int(getattr(ns, "data_shards", 0) or 0)
    shard_id = int(getattr(ns, "data_shard_id", 0) or 0)
    if shards == 0 and shard_id != 0:
        parser.error(f"--data_shard_id {shard_id} needs --data_shards >= 1 "
                     "(0 shards = the legacy per-process split, which has "
                     "no shard ids)")
    if shards and not (0 <= shard_id < shards):
        parser.error("--data_shard_id must satisfy 0 <= id < --data_shards, "
                     f"got id {shard_id} with {shards} shard(s)")


def parse_opts(argv: Optional[Sequence[str]] = None) -> argparse.Namespace:
    parser = build_parser()
    ns = parser.parse_args(argv)
    _validate_shard_flags(parser, ns)
    apply_tuned_defaults(ns, argv)
    _warn_overlap_under_device_rewards(ns, argv)
    warn_supervise_conflict(ns, argv)
    if getattr(ns, "engine", "legacy") == "serving":
        warn_serving_decode_chunk(ns)
        warn_serve_deadline(ns)
    return ns
