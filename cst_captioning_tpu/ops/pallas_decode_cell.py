"""Pallas TPU kernel: the ENTIRE LSTM decode step as one fused kernel.

``ops/pallas_attention.py`` fuses the additive-attention chain; this module
fuses the whole autoregressive decode cell around it — attention scores ->
softmax -> context -> gate matmuls -> LSTM state update — so one
``pallas_call`` per decode step keeps every intermediate (the (Bb, T, A)
tanh activation, the context vector, the 4H gate pre-activations) in VMEM.
Unfused, XLA bounces each of those through HBM between kernels; at rollout
shapes the per-step tensors are small enough that the HBM round trips, not
FLOPs, dominate the step (PARITY.md rollout breakdown), which is exactly
the regime kernel fusion pays in.

Scope (deliberate):

- **Decode/rollout only, forward only.**  Sampling, greedy baseline, beam
  search and eval decode all drive ``make_decode_step``; none of them
  differentiates (the RL grad recomputes log-probs with the teacher-forced
  ``model.__call__`` — see ops/sampling.py module doc), so the kernel
  carries no VJP.  Teacher-forced training keeps the existing nn.scan cell
  (with the optional fused-attention kernel) untouched.
- **Single-layer attention-LSTM** (the shipped architecture).  Other
  configurations (num_layers > 1, pooled/no-attention, transformer) fall
  back to the reference cell — ``pallas_decode_supported`` is the one
  eligibility gate, and the fallback is logged once, not silent.

Numerics: the kernel mirrors the composed pallas-attention path
bit-for-bit — attention math in fp32 exactly as ``_attention_kernel``
(VPU multiply+reduce, NOT an MXU dot: Mosaic lowers fp32 MXU dots through
bf16 passes, and batch-dim dot_generals fail to lower at all — see
ops/pallas_attention.py), context cast back to the model dtype, then the
gate algebra in the model dtype in flax ``OptimizedLSTMCell``'s exact op
order (h-side concat-dense + bias first, input-side concat-dense second,
``sigmoid(h + i)`` gates in i, f, g, o order).  Interpret mode executes the
very same jnp ops, so CPU tests pin the kernel path bit-identical to the
composed cell (tests/test_pallas_decode_cell.py); the einsum-based plain
XLA cell differs from both by float32 ULPs only.  On hardware the gate
matmuls lower to the MXU in the storage dtype (bf16 models run bf16 MXU
dots natively; fp32 pays Mosaic's multi-pass lowering — the sweepable
flag exists precisely so the autotuner measures whether that trade wins
per platform).

Layout (pallas_guide.md: grid/BlockSpec, VMEM, MXU for the gate GEMMs):
grid over batch blocks; per block the kernel holds the step inputs
(x, c, h, q), the (Bb, T, A)+(Bb, T, H) attention operands, and the full
gate weights (E+H, 4H) + (H, 4H) in VMEM — weights use a constant
index_map so every block reads the same buffer.  The embedding gather and
the query/vocab projections stay OUTSIDE the kernel (a gather wants XLA's
native lowering; the projections are single dense GEMMs the MXU already
runs at peak, and hoisting the vocab head mirrors ``DecoderCell``'s own
design).
"""

from __future__ import annotations

import logging
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_attention import _block_spec, default_interpret

log = logging.getLogger("cst_captioning_tpu.ops.pallas_decode_cell")

_GATES = ("i", "f", "g", "o")  # flax OptimizedLSTMCell concat order
_warned_fallback = set()


def _decode_cell_kernel(x_ref, c_ref, h_ref, q_ref, pm_ref, mem_ref, v_ref,
                        wi_ref, wh_ref, b_ref, c_out, h_out):
    """One decode step for a batch block, entirely in VMEM.

    Attention follows ops/pallas_attention._attention_kernel op-for-op
    (fp32 math, VPU reductions); the LSTM follows flax
    OptimizedLSTMCell op-for-op in the storage dtype.
    """
    # -- additive attention (fp32, exactly as the attention kernel) -------
    q = q_ref[:].astype(jnp.float32)                     # (Bb, A)
    pm = pm_ref[:].astype(jnp.float32)                   # (Bb, T, A)
    v = v_ref[:].astype(jnp.float32)                     # (1, A)
    tanh = jnp.tanh(pm + q[:, None, :])
    scores = jnp.sum(tanh * v[0][None, None, :], axis=2)
    w = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.sum(w[:, :, None] * mem_ref[:].astype(jnp.float32), axis=1)

    # -- LSTM gates (storage dtype, flax OptimizedLSTMCell op order) ------
    x = x_ref[:]                                         # (Bb, E)
    h = h_ref[:]                                         # (Bb, H)
    c = c_ref[:]                                         # (Bb, H)
    inp = jnp.concatenate([x, ctx.astype(x.dtype)], axis=-1)
    # h-side concat-dense carries the bias (flax: use_bias on the h
    # kernels only), i-side is bias-free; gates add h-part + i-part.
    gh = jnp.dot(h, wh_ref[:]) + b_ref[:]                # (Bb, 4H)
    gi = jnp.dot(inp, wi_ref[:])                         # (Bb, 4H)
    hidden = h.shape[-1]
    parts = []
    for k in range(4):
        sl = slice(k * hidden, (k + 1) * hidden)
        parts.append(gh[:, sl] + gi[:, sl])
    i = jax.nn.sigmoid(parts[0])
    f = jax.nn.sigmoid(parts[1])
    g = jnp.tanh(parts[2])
    o = jax.nn.sigmoid(parts[3])
    new_c = f * c + i * g
    new_h = o * jnp.tanh(new_c)
    c_out[:] = new_c.astype(c_out.dtype)
    h_out[:] = new_h.astype(h_out.dtype)


def fused_decode_cell(
    x: jnp.ndarray,            # (B, E) embedded input token
    c: jnp.ndarray,            # (B, H) LSTM cell state
    h: jnp.ndarray,            # (B, H) LSTM hidden state
    query_proj: jnp.ndarray,   # (B, A) W_q h — projected by the caller
    proj_mem: jnp.ndarray,     # (B, T, A) W_m memory, projected once
    memory: jnp.ndarray,       # (B, T, H)
    score_v: jnp.ndarray,      # (A,)
    wi: jnp.ndarray,           # (E+H, 4H) input gate kernels, i|f|g|o
    wh: jnp.ndarray,           # (H, 4H) recurrent gate kernels, i|f|g|o
    bias: jnp.ndarray,         # (4H,) gate biases (h-side), i|f|g|o
    block_b: int = 8,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (new_c (B, H), new_h (B, H)): one fused decode step."""
    b, t, a = proj_mem.shape
    hid = memory.shape[-1]
    bb = min(block_b, b)
    pad = (-b) % bb
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        c = jnp.pad(c, ((0, pad), (0, 0)))
        h = jnp.pad(h, ((0, pad), (0, 0)))
        query_proj = jnp.pad(query_proj, ((0, pad), (0, 0)))
        proj_mem = jnp.pad(proj_mem, ((0, pad), (0, 0), (0, 0)))
        memory = jnp.pad(memory, ((0, pad), (0, 0), (0, 0)))
    bp = b + pad
    e = x.shape[-1]
    new_c, new_h = pl.pallas_call(
        _decode_cell_kernel,
        grid=(bp // bb,),
        in_specs=[
            _block_spec((bb, e), lambda i: (i, 0)),
            _block_spec((bb, hid), lambda i: (i, 0)),
            _block_spec((bb, hid), lambda i: (i, 0)),
            _block_spec((bb, a), lambda i: (i, 0)),
            _block_spec((bb, t, a), lambda i: (i, 0, 0)),
            _block_spec((bb, t, hid), lambda i: (i, 0, 0)),
            _block_spec((1, a), lambda i: (0, 0)),
            _block_spec((e + hid, 4 * hid), lambda i: (0, 0)),
            _block_spec((hid, 4 * hid), lambda i: (0, 0)),
            _block_spec((1, 4 * hid), lambda i: (0, 0)),
        ],
        out_specs=[
            _block_spec((bb, hid), lambda i: (i, 0)),
            _block_spec((bb, hid), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, hid), c.dtype),
            jax.ShapeDtypeStruct((bp, hid), h.dtype),
        ],
        interpret=interpret,
    )(x, c, h, query_proj, proj_mem, memory, score_v.reshape(1, -1),
      wi, wh, bias.reshape(1, -1))
    return new_c[:b], new_h[:b]


def pallas_decode_supported(model) -> Tuple[bool, str]:
    """(eligible, reason): the fused cell covers the shipped architecture —
    single-layer attention-LSTM — and everything else must fall back to
    the reference cell rather than silently compute something different."""
    if getattr(model, "decoder_type", "lstm") != "lstm":
        return False, "decoder_type != lstm"
    if getattr(model, "num_layers", 1) != 1:
        return False, "num_layers != 1"
    if not getattr(model, "use_attention", True):
        return False, "use_attention=0 (pooled context has no attention chain)"
    return True, ""


def warn_fallback_once(reason: str) -> None:
    """--decode_kernel pallas on an ineligible model: log ONCE per reason
    per process (the decode step is rebuilt every trace) and continue on
    the reference cell — a tuned record from another config must degrade,
    not crash."""
    if reason not in _warned_fallback:
        _warned_fallback.add(reason)
        log.warning("decode_kernel=pallas unsupported here (%s); "
                    "falling back to the reference decode cell", reason)


def make_pallas_decode_step(model, variables, memory: jnp.ndarray,
                            proj_mem: jnp.ndarray,
                            block_b: int = 8) -> Callable:
    """Build ``step(carry, token (N,)) -> (carry, logits (N, V))`` on the
    fused kernel — the same contract as ``ops.sampling.make_decode_step``.

    Reads the cell's raw parameters straight from ``variables`` (the
    param-tree layout is part of the model's stable surface — bench's
    ``rollout_step_probe`` already indexes it) and mirrors the flax
    modules' dtype promotion around the kernel: embedding gather and the
    query/vocab projections in the model compute dtype, attention fp32
    inside the kernel, gates in the model dtype.
    """
    params = variables["params"]
    cell = params["cell"]
    dtype = getattr(model, "dtype", jnp.float32)
    emb = cell["embed"]["embedding"].astype(dtype)
    wq = cell["attn"]["query_proj"]["kernel"].astype(dtype)
    score_v = cell["attn"]["score_v"]                    # fp32 by design
    lstm = cell["lstm0"]
    wi = jnp.concatenate([lstm[f"i{g}"]["kernel"] for g in _GATES],
                         axis=-1).astype(dtype)
    wh = jnp.concatenate([lstm[f"h{g}"]["kernel"] for g in _GATES],
                         axis=-1).astype(dtype)
    bias = jnp.concatenate([lstm[f"h{g}"]["bias"] for g in _GATES],
                           axis=-1).astype(dtype)
    w_logit = params["logit"]["kernel"].astype(dtype)
    b_logit = params["logit"]["bias"].astype(dtype)
    interpret = default_interpret()

    def step(carry, token):
        (c, h), = carry
        x = jnp.take(emb, token, axis=0)                 # (N, E)
        q = jnp.dot(h.astype(dtype), wq)                 # (N, A)
        new_c, new_h = fused_decode_cell(
            x, c, h, q, proj_mem, memory, score_v, wi, wh, bias,
            block_b=block_b, interpret=interpret,
        )
        logits = jnp.dot(new_h.astype(dtype), w_logit) \
            + jnp.reshape(b_logit, (1, -1))
        return ((new_c, new_h),), logits

    return step
