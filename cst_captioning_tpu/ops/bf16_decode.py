"""Low-precision (bfloat16) decode-step variant behind ``--decode_kernel``.

The rollout/eval decode step is latency-bound at serving shapes: small
per-step matmuls whose cost on MXU-bearing hardware is dominated by
operand traffic, which bfloat16 halves.  ``--decode_kernel bf16`` keeps
the model's stored parameters fp32 and swaps ONLY the decode-step
compute to bfloat16 — the same flax machinery ``--use_bfloat16`` uses
for training, scoped to ``make_decode_step`` so teacher forcing, the RL
gradient, and every checkpoint stay untouched.

Boundary contract (what keeps the variant drop-in):

- **fp32 at the seams.**  The step receives the fp32 carry the callers
  allocate (samplers, beam, the serving engine's slot buffers), casts it
  to bf16 for the cell, and casts the result back.  The round trip is
  numerically free: bf16 -> fp32 is exact, and fp32 -> bf16 of an
  exactly-representable value is the identity — so the fp32-carry
  formulation computes the SAME sequence a bf16-carry one would.
- **fp32 logits.**  Scores/argmax/log-softmax downstream (beam's score
  buffers are fp32 by design) see fp32 logits; only the cell math is
  low-precision.

Parity gate (the honesty rule): bf16 decode is NOT bit-identical to
fp32 — captions may differ where fp32 logit margins are below bf16
resolution.  It therefore ships gated, never silently: the declared
bound is :data:`DEFAULT_CIDER_DELTA_BOUND` on the corpus CIDEr delta
vs the fp32 decode of the same checkpoint (``scripts/bf16_parity.py``
measures it — the cpu512_healthy protocol is the record of evidence),
:func:`parity_gate` is the one decision rule, and its failure mode is
pinned: fall back to ``reference``, the bit-exact path.  Whether the
variant actually pays is a platform question — it rides the tuner's
``decode_kernel`` axis (tuning/sweep.py) so ``TUNED_CONFIGS.json``
records a measured per-platform winner with provenance.

Unsupported configurations (a model already computing in bfloat16 has
nothing to gain and would double-cast) fall back to the reference cell
with one log line — the ``pallas_decode_cell`` fallback discipline.
"""

from __future__ import annotations

import logging
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

log = logging.getLogger("cst_captioning_tpu.ops.bf16_decode")

#: Declared CIDEr-delta bound for the parity gate: |CIDEr(bf16) -
#: CIDEr(fp32)| on the same checkpoint + split must stay within this, or
#: the recommendation is the bit-exact ``reference`` fallback.  0.02
#: CIDEr is well inside the run-to-run spread of the training protocol
#: itself (cpu512_healthy stage deltas are ~0.2-0.8), so a pass means
#: the precision change is lost in training noise.
DEFAULT_CIDER_DELTA_BOUND = 0.02

_warned_fallback = set()


def bf16_decode_supported(model) -> Tuple[bool, str]:
    """(eligible, reason): the bf16 variant wraps the reference flax cell,
    so every decoder configuration the reference step serves is eligible —
    EXCEPT a model whose compute dtype is already bfloat16 (the variant
    would be an identity wrapper paying two extra casts per step)."""
    if jnp.dtype(getattr(model, "dtype", jnp.float32)) == \
            jnp.dtype(jnp.bfloat16):
        return False, "model compute dtype is already bfloat16"
    return True, ""


def warn_fallback_once(reason: str) -> None:
    """--decode_kernel bf16 on an ineligible model: log ONCE per reason
    per process and continue on the reference cell (the pallas-fallback
    discipline — a tuned record from another config degrades, not
    crashes)."""
    if reason not in _warned_fallback:
        _warned_fallback.add(reason)
        log.warning("decode_kernel=bf16 unsupported here (%s); "
                    "falling back to the reference decode cell", reason)


def make_bf16_decode_step(model, variables, memory: jnp.ndarray,
                          proj_mem: jnp.ndarray,
                          pooled: jnp.ndarray) -> Callable:
    """Build ``step(carry, token (N,)) -> (carry, logits (N, V))`` with
    bfloat16 cell compute — the same contract as
    ``ops.sampling.make_decode_step``.

    The cloned module (``dtype=bfloat16``, ``decode_kernel="reference"``
    so the clone can never re-enter kernel routing) shares the caller's
    fp32 parameter tree; flax casts per-op to the module dtype, exactly
    as ``--use_bfloat16`` does in training.  Encodings are cast once at
    closure build (not per step); carry and logits are fp32 at the
    boundary (module doc).
    """
    m = model.clone(dtype=jnp.bfloat16, decode_kernel="reference")
    bf16 = jnp.bfloat16
    mem_b = memory.astype(bf16)
    proj_b = proj_mem.astype(bf16)
    pooled_b = pooled.astype(bf16)

    def cast(tree, dtype):
        # Float leaves only: the transformer carry holds int32 (token
        # buffer, position) leaves that must keep their dtype — casting
        # them would crash its dynamic_update_slice (and mean nothing).
        return jax.tree_util.tree_map(
            lambda x: x.astype(dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def to_bf16(tree):
        return cast(tree, bf16)

    def to_f32(tree):
        return cast(tree, jnp.float32)

    def step(carry, token):
        carry, logits = m.apply(
            variables, to_bf16(carry), token[:, None], mem_b, proj_b,
            pooled_b, method="decode",
        )
        return to_f32(carry), logits[:, 0, :].astype(jnp.float32)

    return step


def parity_gate(cider_fp32: float, cider_bf16: float,
                bound: float = DEFAULT_CIDER_DELTA_BOUND) -> dict:
    """The ONE decision rule for shipping the bf16 decode variant.

    -> {"delta", "bound", "within_bound", "kernel_recommendation"}:
    within the declared bound the low-precision variant is eligible (the
    tuner then decides whether it *pays*); outside it the recommendation
    is pinned to ``reference`` — the bit-exact path is always the
    fallback, never a worse-quality caption shipped silently.
    """
    delta = float(cider_bf16) - float(cider_fp32)
    within = abs(delta) <= float(bound)
    return {
        "cider_fp32": float(cider_fp32),
        "cider_bf16": float(cider_bf16),
        "delta": delta,
        "bound": float(bound),
        "within_bound": within,
        "kernel_recommendation": "bf16" if within else "reference",
    }
