"""Pallas TPU kernel: fused additive-attention step for the decoder.

The attention-LSTM hot loop computes, per decode step,
``softmax(v . tanh(proj_mem + W_q h)) @ memory``.  Unfused, XLA materializes
the (B, T, A) tanh activation in HBM between two kernels; this Pallas
kernel keeps the whole score -> softmax -> context chain in VMEM per batch
block, reading proj_mem/memory once (the op is HBM-bandwidth-bound — the
tanh tensor alone is B*T*A*4 bytes per step).

Layout (pallas_guide.md: grid/BlockSpec, VMEM, MXU preferred_element_type):
- caller performs the (B,H)x(H,A) query projection as a plain GEMM (MXU
  likes one big matmul; fusing it here would re-load W_q per block);
- grid over batch blocks; per block the kernel holds (Bb, T, A) proj_mem +
  (Bb, T, H) memory in VMEM;
- score reduction and the context weighted-sum both lower to MXU dots.

Training needs gradients and ``pallas_call`` is not auto-differentiable, so
the op carries a custom VJP whose backward is plain fused XLA (recomputes
tanh from the saved inputs — cheaper than storing it, same recompute trade
as jax.checkpoint).

``interpret=True`` (automatic off-TPU) runs the kernel through the Pallas
interpreter so CPU tests cover the exact kernel code path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces; unavailable in some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None


def _block_spec(shape, index_map):
    if _VMEM is None:  # pragma: no cover - interpret mode only
        return pl.BlockSpec(shape, index_map)
    return pl.BlockSpec(shape, index_map, memory_space=_VMEM)


def _attention_kernel(q_ref, pm_ref, mem_ref, v_ref, ctx_ref, w_ref):
    # HBM reads stay in the storage dtype (bf16 inputs read bf16); all the
    # math below runs in fp32 registers/VMEM.
    q = q_ref[:].astype(jnp.float32)             # (Bb, A)
    pm = pm_ref[:].astype(jnp.float32)           # (Bb, T, A)
    v = v_ref[:].astype(jnp.float32)             # (1, A)
    tanh = jnp.tanh(pm + q[:, None, :])
    # scores as a VPU multiply+reduce, not an MXU dot: Mosaic lowers fp32
    # MXU dots through bf16 passes (measured ~1e-2 error on hardware),
    # which breaks parity with the XLA fallback; the op is bandwidth-bound
    # so the VPU reduction costs nothing extra.
    scores = jnp.sum(tanh * v[0][None, None, :], axis=2)
    w = jax.nn.softmax(scores, axis=-1)
    # context = sum_t w[b,t] * mem[b,t,:] as a broadcast multiply + T-sum.
    # NOT a batched dot_general: Mosaic's TPU_DotDimensionNumbersAttr
    # cannot lower batch-dimension dots ((Bb,T)x(Bb,T,H) fails to parse —
    # judge-verified on hardware, VERDICT.md round 2 item 3).  The op is
    # VMEM-bandwidth-bound, so the VPU reduction costs the same as an MXU
    # dot would here.
    ctx = jnp.sum(
        w[:, :, None] * mem_ref[:].astype(jnp.float32), axis=1
    )
    ctx_ref[:] = ctx.astype(ctx_ref.dtype)
    w_ref[:] = w.astype(w_ref.dtype)


def _forward(query_proj, proj_mem, memory, score_v, block_b, interpret):
    b, t, a = proj_mem.shape
    h = memory.shape[-1]
    bb = min(block_b, b)
    pad = (-b) % bb
    if pad:
        query_proj = jnp.pad(query_proj, ((0, pad), (0, 0)))
        proj_mem = jnp.pad(proj_mem, ((0, pad), (0, 0), (0, 0)))
        memory = jnp.pad(memory, ((0, pad), (0, 0), (0, 0)))
    bp = b + pad
    grid = (bp // bb,)
    ctx, w = pl.pallas_call(
        _attention_kernel,
        grid=grid,
        in_specs=[
            _block_spec((bb, a), lambda i: (i, 0)),
            _block_spec((bb, t, a), lambda i: (i, 0, 0)),
            _block_spec((bb, t, h), lambda i: (i, 0, 0)),
            _block_spec((1, a), lambda i: (0, 0)),
        ],
        out_specs=[
            _block_spec((bb, h), lambda i: (i, 0)),
            _block_spec((bb, t), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, h), memory.dtype),
            jax.ShapeDtypeStruct((bp, t), memory.dtype),
        ],
        interpret=interpret,
    )(query_proj, proj_mem, memory, score_v.reshape(1, -1))
    return ctx[:b], w[:b]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_additive_attention(
    query_proj: jnp.ndarray,   # (B, A) — W_q h, projected by the caller
    proj_mem: jnp.ndarray,     # (B, T, A) — W_m memory, projected once
    memory: jnp.ndarray,       # (B, T, H)
    score_v: jnp.ndarray,      # (A,) score vector
    block_b: int = 8,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (context (B, H), weights (B, T))."""
    return _forward(query_proj, proj_mem, memory, score_v, block_b, interpret)


def _fwd(query_proj, proj_mem, memory, score_v, block_b, interpret):
    ctx, w = _forward(query_proj, proj_mem, memory, score_v, block_b,
                      interpret)
    return (ctx, w), (query_proj, proj_mem, memory, score_v)


def _bwd(block_b, interpret, res, grads):
    query_proj, proj_mem, memory, score_v = res
    g_ctx = grads[0].astype(jnp.float32)
    g_w = grads[1].astype(jnp.float32)
    memory_f = memory.astype(jnp.float32)
    # Recompute tanh and the softmax weights in fp32 exactly as the forward
    # kernel computed them (operands cast BEFORE the add) — checkpoint-style
    # recompute, and no bf16-rounded residual enters the gradient.
    tanh = jnp.tanh(proj_mem.astype(jnp.float32)
                    + query_proj.astype(jnp.float32)[:, None, :])  # (B, T, A)
    scores = jnp.einsum("bta,a->bt", tanh, score_v.astype(jnp.float32))
    w = jax.nn.softmax(scores, axis=-1)
    g_w_total = g_w + jnp.einsum("bh,bth->bt", g_ctx, memory_f)
    # softmax backward: ds = w * (g - sum_t w g)
    ds = w * (g_w_total - jnp.sum(w * g_w_total, axis=-1, keepdims=True))
    dt = (ds[:, :, None] * score_v.astype(jnp.float32)[None, None, :]
          * (1.0 - tanh * tanh))
    g_pm = dt
    g_q = dt.sum(axis=1)
    g_v = jnp.einsum("bta,bt->a", tanh, ds)
    g_mem = jnp.einsum("bt,bh->bth", w, g_ctx)
    return (g_q.astype(query_proj.dtype), g_pm.astype(proj_mem.dtype),
            g_mem.astype(memory.dtype), g_v.astype(score_v.dtype))


fused_additive_attention.defvjp(_fwd, _bwd)


def default_interpret() -> bool:
    """Interpret off-TPU so CPU tests execute the kernel path."""
    return jax.default_backend() != "tpu"
