"""Attention primitives for the caption decoders.

Additive (Bahdanau) attention for the attention-LSTM decoder — the
north-star architecture ("feature encoder and attention-LSTM decoder",
BASELINE.json) — expressed as pure batched tensor ops so XLA fuses the
score computation into MXU matmuls + a softmax, with no per-step Python.

Split for the scan: the memory projection (W_m · memory) depends only on the
encoder output, so the *caller* computes it once per sequence with a plain
``nn.Dense`` and passes it into every step; this module holds only the
per-step parameters (query projection + score vector), keeping the inner
decode loop at one (B,H)x(H,A) matmul.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class AdditiveAttention(nn.Module):
    """score(h, m_t) = v . tanh(proj_mem_t + W_q h); returns (context, weights)."""

    attn_size: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        query: jnp.ndarray,             # (B, H) decoder state
        memory: jnp.ndarray,            # (B, T, H) encoder output
        projected_memory: jnp.ndarray,  # (B, T, A) precomputed W_m . memory
    ):
        q = nn.Dense(self.attn_size, use_bias=False, dtype=self.dtype,
                     name="query_proj")(query)[:, None, :]           # (B, 1, A)
        scores = nn.Dense(1, use_bias=False, dtype=self.dtype, name="score")(
            jnp.tanh(projected_memory + q)
        )[..., 0]                                                     # (B, T)
        weights = nn.softmax(scores, axis=-1)
        context = jnp.einsum("bt,bth->bh", weights, memory.astype(self.dtype))
        return context, weights
