"""Attention primitives for the caption decoders.

Additive (Bahdanau) attention for the attention-LSTM decoder — the
north-star architecture ("feature encoder and attention-LSTM decoder",
BASELINE.json) — expressed as pure batched tensor ops so XLA fuses the
score computation into MXU matmuls + a softmax, with no per-step Python.

Split for the scan: the memory projection (W_m · memory) depends only on the
encoder output, so the *caller* computes it once per sequence with a plain
``nn.Dense`` and passes it into every step; this module holds only the
per-step parameters (query projection + score vector), keeping the inner
decode loop at one (B,H)x(H,A) matmul.

``use_pallas=True`` routes the score -> softmax -> context chain through
the fused VMEM kernel (ops/pallas_attention.py): same parameters, same
math, custom-VJP gradients.  Interpret-mode parity with the XLA path is
pinned by tests/test_pallas_attention.py.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class AdditiveAttention(nn.Module):
    """score(h, m_t) = v . tanh(proj_mem_t + W_q h); returns (context, weights)."""

    attn_size: int
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False

    @nn.compact
    def __call__(
        self,
        query: jnp.ndarray,             # (B, H) decoder state
        memory: jnp.ndarray,            # (B, T, H) encoder output
        projected_memory: jnp.ndarray,  # (B, T, A) precomputed W_m . memory
    ):
        q = nn.Dense(self.attn_size, use_bias=False, dtype=self.dtype,
                     name="query_proj")(query)                       # (B, A)
        # The score vector is a bare (A,) param shared by the pallas and XLA
        # branches — one param-tree layout regardless of the flag.
        v = self.param(
            "score_v",
            nn.initializers.normal(stddev=self.attn_size ** -0.5),
            (self.attn_size,), jnp.float32,
        )
        if self.use_pallas and not self.is_initializing():
            from .pallas_attention import (
                default_interpret,
                fused_additive_attention,
            )

            # Inputs stay in their storage dtype (bf16 reads bf16 from HBM);
            # the kernel accumulates scores/softmax/context in fp32.
            context, weights = fused_additive_attention(
                q, projected_memory, memory, v,
                interpret=default_interpret(),
            )
            return context.astype(self.dtype), weights.astype(self.dtype)
        # Match the kernel's numerics: operands cast to fp32 BEFORE the add
        # (not after a bf16 add), fp32 scores, softmax and context.
        scores = jnp.einsum(
            "bta,a->bt",
            jnp.tanh(projected_memory.astype(jnp.float32)
                     + q.astype(jnp.float32)[:, None, :]), v
        )
        weights = jax.nn.softmax(scores, axis=-1)
        context = jnp.einsum("bt,bth->bh", weights,
                             memory.astype(jnp.float32))
        return context.astype(self.dtype), weights.astype(self.dtype)
