"""Greedy / multinomial caption sampling — one compiled `lax.scan`.

The reference's ``model.sample`` (SURVEY.md §2 "Captioning model") runs a
Python loop of per-step LSTM calls with ``torch.multinomial`` on device,
flag-switched between argmax (``sample_max=1``) and multinomial rollout
(``sample_max=0``).  TPU-first restatement:

- the whole rollout is ONE ``lax.scan`` over the model's ``decode`` step —
  traced once, compiled once, no Python-per-timestep dispatch;
- greedy vs multinomial is a static flag (two jit specializations);
- ``jax.random.categorical`` replaces torch.multinomial; the key is split
  per step inside the scan;
- sequences are 0-terminated to match the label convention
  (``ops.losses.sequence_mask``): the first sampled EOS (id 0) is kept,
  everything after is forced to 0 with logprob 0.

Gradient note: rollouts are sampling-only (no grad).  The RL stage
recomputes log p(sampled) with the teacher-forced ``model.__call__`` under
``jax.grad`` — the reference instead kept the rollout graph alive
(SURVEY.md §3.2); recomputation is the XLA-native equivalent and lets the
rollout run in a fused scan without storing activations.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

# NOTE: the model is referenced only through its method NAMES ("decode",
# "encode", "init_carry") to keep ops <-> models import-acyclic; any module
# exposing those three surfaces works (CaptionModel is the one that does).


def repeat_for_captions(x: jnp.ndarray, seq_per_img: int) -> jnp.ndarray:
    """(B, ...) -> (B*S, ...): align per-video encodings with caption rows."""
    if seq_per_img == 1:
        return x
    return jnp.repeat(x, seq_per_img, axis=0)


def finished_mask(finished: jnp.ndarray) -> jnp.ndarray:
    """Per-ITEM finished predicate from a decode loop's finished buffer.

    The samplers carry a per-row ``(N,)`` bool; beam search carries a
    per-beam ``(B, k)`` bool where an item (video) is finished only once
    EVERY beam has emitted EOS.  One helper owns that reduction so the
    early-exit chunk predicate (here and in ``ops/beam.py``) and the
    serving engine's slot recycler (``serving/engine.py``, which frees a
    slot the moment its item's mask goes True) can never disagree on what
    "finished" means.
    """
    if finished.ndim <= 1:
        return finished
    return jnp.all(finished, axis=-1)


def all_finished(finished: jnp.ndarray) -> jnp.ndarray:
    """Scalar: every item finished — the chunked while_loop's early-exit
    predicate (shared by sampler and beam fast paths)."""
    return jnp.all(finished_mask(finished))


def make_decode_step(
    model,
    variables,
    memory: jnp.ndarray,
    proj_mem: jnp.ndarray,
    pooled: jnp.ndarray,
) -> Callable:
    """Bind encodings + params into a pure per-step function.

    Returned ``step(carry, token(N,)) -> (carry, logits (N, V))`` is what
    both the samplers and the beam search drive.

    ``model.decode_kernel == "pallas"`` (--decode_kernel, sweepable by the
    autotuner) routes the step through the fused Pallas decode cell
    (ops/pallas_decode_cell.py) — attention + LSTM state update as ONE
    kernel, bit-identical to the composed pallas-attention cell and
    fp32-ULP-close to this reference cell (test-pinned).  Unsupported
    configurations (multi-layer, pooled, transformer) fall back here with
    a one-time log line.

    ``model.decode_kernel == "bf16"`` routes through the low-precision
    decode variant (ops/bf16_decode.py): bfloat16 cell compute, fp32
    carry/logits at the boundary — NOT bit-identical to fp32, so it
    ships behind the CIDEr-delta parity gate with this reference cell
    pinned as the fallback (same one-time-log fallback discipline).
    """
    kernel = getattr(model, "decode_kernel", "reference")
    if kernel == "pallas":
        from .pallas_decode_cell import (
            make_pallas_decode_step,
            pallas_decode_supported,
            warn_fallback_once,
        )

        ok, reason = pallas_decode_supported(model)
        if ok:
            return make_pallas_decode_step(model, variables, memory,
                                           proj_mem)
        warn_fallback_once(reason)
    elif kernel == "bf16":
        from .bf16_decode import (
            bf16_decode_supported,
            make_bf16_decode_step,
            warn_fallback_once,
        )

        ok, reason = bf16_decode_supported(model)
        if ok:
            return make_bf16_decode_step(model, variables, memory,
                                         proj_mem, pooled)
        warn_fallback_once(reason)

    def step(carry, token):
        carry, logits = model.apply(
            variables, carry, token[:, None], memory, proj_mem, pooled,
            method="decode",
        )
        return carry, logits[:, 0, :]

    return step


def sample_tokens(
    step: Callable,
    init_carry,
    batch: int,
    max_len: int,
    rng: jax.Array,
    greedy=False,
    temperature: float = 1.0,
    unroll: int = 1,
    decode_chunk: int = 0,
    return_steps: bool = False,
):
    """Roll out ``max_len`` steps from BOS (=0).

    ``greedy`` is either a python bool (whole batch) or a per-row (N,) bool
    array — the latter lets one scan carry multinomial rollout rows and
    greedy baseline rows together (``sample_with_baseline``).

    ``unroll`` is forwarded to ``lax.scan`` (see
    ``models.decoder_lstm.scan_decoder``: same numerics, amortized
    per-step overhead for small per-step matmuls).

    ``decode_chunk`` > 0 enables the early-exit fast path: the rollout
    runs as a ``lax.while_loop`` over fixed-size scan chunks of that many
    steps, stopping once EVERY row has emitted its EOS — a batch whose
    captions end at step 9 pays for 2 chunks of 8, not all 30 steps.  The
    inner chunk stays a fused ``lax.scan`` so the TPU keeps its
    pipelining, the per-step computation (keys included) is exactly the
    legacy scan's, and the skipped steps' outputs are the zeros the
    legacy path would have emitted for finished rows — so the outputs are
    BIT-IDENTICAL to ``decode_chunk=0`` (pinned by
    tests/test_decode_fastpath.py).  0 = legacy single full-length scan.

    Returns (tokens (N, L) int32 0-terminated, logprobs (N, L) float32 of
    the emitted tokens, 0 past the first EOS); with ``return_steps=True``
    also an int32 scalar of decode steps actually executed (== max_len on
    the legacy path, a multiple of ``decode_chunk`` capped at max_len on
    the early-exit path).
    """
    per_row = not isinstance(greedy, bool)

    def body(state, key):
        carry, prev, finished = state
        carry, logits = step(carry, prev)
        logp = jax.nn.log_softmax(logits, axis=-1)
        if greedy is True:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            nxt = jax.random.categorical(
                key, logits / jnp.maximum(temperature, 1e-6), axis=-1
            ).astype(jnp.int32)
            if per_row:
                nxt = jnp.where(
                    greedy, jnp.argmax(logits, axis=-1).astype(jnp.int32), nxt
                )
        tok_logp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
        emit = jnp.where(finished, 0, nxt)
        emit_logp = jnp.where(finished, 0.0, tok_logp)
        finished = finished | (emit == 0)
        return (carry, emit, finished), (emit, emit_logp)

    keys = jax.random.split(rng, max_len)
    init = (
        init_carry,
        jnp.zeros((batch,), dtype=jnp.int32),        # BOS
        jnp.zeros((batch,), dtype=bool),
    )
    if decode_chunk <= 0 or decode_chunk >= max_len:
        _, (tokens, logprobs) = jax.lax.scan(body, init, keys, unroll=unroll)
        out = (tokens.T, logprobs.T)                  # (L, N) -> (N, L)
        return out + (jnp.int32(max_len),) if return_steps else out

    chunk = int(decode_chunk)
    n_chunks = -(-max_len // chunk)
    padded = n_chunks * chunk
    if padded > max_len:
        # The final chunk's trailing steps run but land past max_len in
        # the padded buffers and are sliced off below (their extra keys
        # are zeros; nothing they compute feeds an earlier position).
        keys = jnp.concatenate(
            [keys, jnp.zeros((padded - max_len,) + keys.shape[1:],
                             keys.dtype)], axis=0)

    def chunk_body(loop):
        t, state, toks, logps = loop
        ks = jax.lax.dynamic_slice_in_dim(keys, t, chunk, axis=0)
        state, (ctoks, clogps) = jax.lax.scan(body, state, ks, unroll=unroll)
        # In-place carry updates: XLA aliases while-loop carries, so the
        # (L, N) buffers are written, never copied.
        toks = jax.lax.dynamic_update_slice_in_dim(toks, ctoks, t, axis=0)
        logps = jax.lax.dynamic_update_slice_in_dim(logps, clogps, t, axis=0)
        return t + chunk, state, toks, logps

    def chunk_cond(loop):
        t, state, _, _ = loop
        return (t < max_len) & ~all_finished(state[2])

    # Output buffers must match the legacy scan's stacked dtypes exactly
    # (bf16 models emit bf16 logprobs) — derive them without running.
    _, (tok_aval, logp_aval) = jax.eval_shape(body, init, keys[0])
    t_end, _, tokens, logprobs = jax.lax.while_loop(
        chunk_cond, chunk_body,
        (jnp.int32(0), init,
         jnp.zeros((padded, batch), tok_aval.dtype),
         jnp.zeros((padded, batch), logp_aval.dtype)),
    )
    out = (tokens[:max_len].T, logprobs[:max_len].T)
    if return_steps:
        return out + (jnp.minimum(t_end, max_len),)
    return out


def sample_captions(
    model,
    variables,
    feats: Sequence[jnp.ndarray],
    rng: jax.Array,
    max_len: int,
    seq_per_img: int = 1,
    greedy: bool = False,
    temperature: float = 1.0,
    decode_chunk: int = 0,
    return_steps: bool = False,
):
    """Encode once, roll out ``seq_per_img`` captions per video.

    -> (tokens (B*seq_per_img, L), logprobs (B*seq_per_img, L)).
    Greedy rollouts with seq_per_img>1 are identical per video (used with
    seq_per_img=1 for the SCST baseline / eval decode).  ``decode_chunk``
    / ``return_steps``: see ``sample_tokens`` (early-exit fast path).
    """
    memory, proj_mem, pooled = model.apply(
        variables, feats, method="encode"
    )
    memory = repeat_for_captions(memory, seq_per_img)
    proj_mem = repeat_for_captions(proj_mem, seq_per_img)
    pooled = repeat_for_captions(pooled, seq_per_img)
    n = pooled.shape[0]
    carry = model.apply(
        variables, pooled, max_len, method="init_carry"
    )
    step = make_decode_step(model, variables, memory, proj_mem, pooled)
    return sample_tokens(step, carry, n, max_len, rng,
                         greedy=greedy, temperature=temperature,
                         unroll=getattr(model, "scan_unroll", 1),
                         decode_chunk=decode_chunk,
                         return_steps=return_steps)


def sample_with_baseline(
    model,
    variables,
    feats: Sequence[jnp.ndarray],
    rng: jax.Array,
    max_len: int,
    seq_per_img: int,
    temperature: float = 1.0,
    decode_chunk: int = 0,
    return_steps: bool = False,
):
    """Multinomial rollout + greedy SCST baseline in ONE fused scan.

    The CST iteration needs both the (B*S) policy samples and the (B)
    greedy baseline decodes.  Two sequential scans pay the scan's
    per-step latency twice (the per-step matmuls are tiny, so the rollout
    is latency- not FLOP-bound on TPU); concatenating the greedy rows onto
    the sampled rows and flag-selecting argmax per row halves it.

    -> (sampled (B*S, L), sampled_logprobs (B*S, L), greedy (B, L)), plus
    an executed-step scalar when ``return_steps`` (see ``sample_tokens``;
    the early-exit predicate requires sampled AND greedy rows finished).
    """
    memory, proj_mem, pooled = model.apply(variables, feats, method="encode")
    b = pooled.shape[0]
    ns = b * seq_per_img
    memory = jnp.concatenate(
        [repeat_for_captions(memory, seq_per_img), memory], axis=0)
    proj_mem = jnp.concatenate(
        [repeat_for_captions(proj_mem, seq_per_img), proj_mem], axis=0)
    pooled = jnp.concatenate(
        [repeat_for_captions(pooled, seq_per_img), pooled], axis=0)
    carry = model.apply(variables, pooled, max_len, method="init_carry")
    step = make_decode_step(model, variables, memory, proj_mem, pooled)
    greedy_rows = jnp.arange(ns + b) >= ns
    out = sample_tokens(
        step, carry, ns + b, max_len, rng,
        greedy=greedy_rows, temperature=temperature,
        unroll=getattr(model, "scan_unroll", 1),
        decode_chunk=decode_chunk, return_steps=return_steps,
    )
    tokens, logprobs = out[:2]
    res = (tokens[:ns], logprobs[:ns], tokens[ns:])
    return res + (out[2],) if return_steps else res


def greedy_decode(model, variables, feats, max_len: int,
                  decode_chunk: int = 0) -> jnp.ndarray:
    """Deterministic argmax decode -> (B, L) tokens (eval fast path)."""
    tokens, _ = sample_captions(
        model, variables, feats,
        jax.random.PRNGKey(0), max_len, greedy=True,
        decode_chunk=decode_chunk,
    )
    return tokens


def jit_sampler(model, max_len: int, seq_per_img: int = 1,
                greedy: bool = False, temperature: float = 1.0,
                decode_chunk: int = 0):
    """jit-compiled sampler: (variables, feats, rng) -> (tokens, logprobs)."""

    @jax.jit
    def fn(variables, feats, rng):
        return sample_captions(
            model, variables, feats, rng, max_len,
            seq_per_img=seq_per_img, greedy=greedy, temperature=temperature,
            decode_chunk=decode_chunk,
        )

    return fn
