"""CIDEr-D on the TPU — the reward computed inside jit, no host round trip.

The reference's defining structural cost is the per-iteration
device->host->device trip for string-space rewards (SURVEY.md §3.2).  The
host path (``training/rewards.py`` + the C++ scorer) removes the Python
cost; THIS module removes the boundary itself: scores are computed from
token ids on device, so the whole CST iteration fuses into one XLA program
(rollout -> reward -> advantage -> grad) with strict on-policy semantics
and zero tunnel latency.

Design (everything static-shape, VPU-friendly):

- **Corpus df as a device hash table** (built host-side by
  ``training/device_rewards.py``): open addressing, double hashing, keys
  are 2x32-bit mixes of the id-encoded n-gram (order included), probe
  length bounded at build time so a lookup is ``PROBES`` gathers+compares,
  fully vectorized.  Each occupied slot also carries a dense ``slot id``
  unique per distinct corpus n-gram — hypothesis/reference matching then
  reduces to integer equality on slot ids.
- **Reference vectors as dense per-video tables**: per (video, ref) a
  padded list of distinct n-grams as (slot, count, idf, order) plus
  per-order norms and the ref length — gathered per batch by dataset
  video index INSIDE jit.
- **Hypothesis side**: n-gram extraction is static slicing; per-occurrence
  self-counts give tf without dedup (sum_i tf_i * idf_i^2 == sum over
  distinct (tf*idf)^2); df lookups give idf and slot; the clipped TF-IDF
  cosine + gaussian length penalty follow pyciderevalcap semantics
  exactly (parity-tested against metrics/ciderd.py at 1e-4).

Float note: scores are f32 on device (the host scorers are f64); CIDEr-D
values are O(0..10) so rewards agree to ~1e-5 relative — far below the
reward noise REINFORCE sees.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

MAX_N = 4
PROBES = 8          # max open-addressing probe length, enforced at build
_MIX1 = np.uint32(0x85EBCA6B)
_MIX2 = np.uint32(0xC2B2AE35)
_SEED2 = np.uint32(0x9E3779B9)


def _mix32(h, x, mult):
    """One multiply-xor-shift round; works for np.uint32 and jnp.uint32."""
    h = (h ^ x) * mult
    return h ^ (h >> 13)


def hash_ngrams_np(ids: np.ndarray, order: int):
    """(..., order) int arrays -> (h1, h2) uint32 pairs (numpy twin of the
    jnp path below — the two MUST stay in lockstep for table lookups)."""
    ids = ids.astype(np.uint32)
    h1 = np.full(ids.shape[:-1], np.uint32(order), dtype=np.uint32)
    h2 = np.full(ids.shape[:-1], np.uint32(order) ^ _SEED2, dtype=np.uint32)
    with np.errstate(over="ignore"):
        for k in range(order):
            h1 = _mix32(h1, ids[..., k], _MIX1)
            h2 = _mix32(h2, ids[..., k], _MIX2)
    return h1, h2


def hash_ngrams_jnp(ids: jnp.ndarray, order: int):
    ids = ids.astype(jnp.uint32)
    h1 = jnp.full(ids.shape[:-1], np.uint32(order), dtype=jnp.uint32)
    h2 = jnp.full(ids.shape[:-1], np.uint32(order) ^ _SEED2, dtype=jnp.uint32)
    for k in range(order):
        h1 = _mix32(h1, ids[..., k], jnp.uint32(_MIX1))
        h2 = _mix32(h2, ids[..., k], jnp.uint32(_MIX2))
    return h1, h2


class CorpusTable(NamedTuple):
    """Open-addressed corpus df table (device arrays; see module doc)."""

    key1: jnp.ndarray        # (S,) uint32, 0 in EMPTY slots is allowed —
    key2: jnp.ndarray        # (S,) uint32   occupancy is tracked separately
    occupied: jnp.ndarray    # (S,) bool
    df: jnp.ndarray          # (S,) f32 document frequency
    log_ref_len: jnp.ndarray  # () f32


class RefTables(NamedTuple):
    """Dense per-video reference TF-IDF tables (device arrays)."""

    slot: jnp.ndarray        # (V, R, G) int32 corpus slot id, -1 = pad
    count: jnp.ndarray       # (V, R, G) f32 n-gram count in this ref
    idf: jnp.ndarray         # (V, R, G) f32
    order: jnp.ndarray       # (V, R, G) int32 1..4, 0 = pad
    norm: jnp.ndarray        # (V, R, MAX_N) f32 per-order vector norms
    length: jnp.ndarray      # (V, R) f32 ref token length
    ref_mask: jnp.ndarray    # (V, R) f32 1 for real refs, 0 for padding


def table_lookup(table: CorpusTable, h1: jnp.ndarray, h2: jnp.ndarray):
    """Vectorized double-hash probe -> (df (...,) f32, slot (...,) int32).

    Missing keys get df=0 (idf = log_ref_len, pyciderevalcap's behavior
    for unseen n-grams) and slot=-1 (matches nothing).
    """
    size = table.key1.shape[0]
    pos = (h1 % jnp.uint32(size)).astype(jnp.int32)
    step = (1 + (h2 % jnp.uint32(size - 1))).astype(jnp.int32)
    df = jnp.zeros(h1.shape, jnp.float32)
    slot = jnp.full(h1.shape, -1, jnp.int32)
    found = jnp.zeros(h1.shape, bool)
    dead = jnp.zeros(h1.shape, bool)   # hit an empty slot -> key absent
    for _ in range(PROBES):
        k1 = table.key1[pos]
        k2 = table.key2[pos]
        occ = table.occupied[pos]
        hit = occ & (k1 == h1) & (k2 == h2) & ~found & ~dead
        df = jnp.where(hit, table.df[pos], df)
        slot = jnp.where(hit, pos, slot)
        found = found | hit
        dead = dead | (~occ & ~found)
        pos = (pos + step) % size
    return df, slot


def _hyp_ngrams(tokens: jnp.ndarray, table: CorpusTable):
    """(N, L) 0-terminated rows -> flat per-occurrence n-gram features.

    Returns (valid (N, P) f32, tf (N, P) f32, idf (N, P) f32,
    slot (N, P) int32, hyp_len (N,) f32) with P = sum over orders of
    (L - k + 1) occurrence positions, padded entries valid=0.
    """
    n, L = tokens.shape
    lengths = jnp.sum(jnp.cumprod(tokens != 0, axis=1), axis=1)  # (N,)
    valids, h1s, h2s = [], [], []
    for order in range(1, MAX_N + 1):
        p = L - order + 1
        if p <= 0:
            continue
        # (N, p, order) static strided slices
        grams = jnp.stack(
            [tokens[:, i:i + p] for i in range(order)], axis=-1
        )
        ok = (jnp.arange(p)[None, :] + order) <= lengths[:, None]
        h1, h2 = hash_ngrams_jnp(grams, order)
        valids.append(ok)
        h1s.append(h1)
        h2s.append(h2)
    valid = jnp.concatenate(valids, axis=1)
    h1 = jnp.concatenate(h1s, axis=1)
    h2 = jnp.concatenate(h2s, axis=1)
    # per-occurrence term frequency: how many occurrences share my n-gram
    same = (h1[:, :, None] == h1[:, None, :]) & \
           (h2[:, :, None] == h2[:, None, :]) & \
           valid[:, None, :]
    tf = jnp.sum(same, axis=2).astype(jnp.float32)
    df, slot = table_lookup(table, h1, h2)
    idf = table.log_ref_len - jnp.log(jnp.maximum(df, 1.0))
    # orders per occurrence (for the per-order norm split)
    order_tags = jnp.concatenate([
        jnp.full((L - k + 1,), k, jnp.int32)
        for k in range(1, MAX_N + 1) if L - k + 1 > 0
    ])
    return (valid.astype(jnp.float32), tf, idf, slot,
            order_tags, lengths.astype(jnp.float32))


def match_tensor_bytes(n_hyps: int, max_len: int, refs: RefTables) -> int:
    """HBM bytes of the transient (N, R, G, P) hyp-ref match tensor — the
    dominant term of this module's memory envelope (everything else is
    linear in N·P or N·R·G).  P grows with caption length (≈ MAX_N·L) and
    G with reference length, so batch-size or length growth can push this
    to GBs; ``make_fused_cst_step`` logs it and chunks the contraction
    over the R axis past a threshold (VERDICT r3 #3)."""
    P = sum(max(max_len - k + 1, 0) for k in range(1, MAX_N + 1))
    _, R, G = refs.slot.shape
    return n_hyps * R * G * P  # XLA bools are 1 byte each


def auto_ref_chunk(n_hyps: int, max_len: int, refs: RefTables,
                   budget_bytes: int = 256 << 20) -> int | None:
    """Pick the ``ref_chunk`` that keeps the match tensor's transient under
    ``budget_bytes``: None when it already fits (one-shot contraction is
    fastest), else the largest chunk within budget (>= 1)."""
    total = match_tensor_bytes(n_hyps, max_len, refs)
    if total <= budget_bytes:
        return None
    R = refs.slot.shape[1]
    per_ref = max(total // R, 1)
    return max(1, min(int(budget_bytes // per_ref), R))


def ciderd_scores(
    tokens: jnp.ndarray,       # (N, L) int32, 0-terminated hypothesis rows
    video_ix: jnp.ndarray,     # (N,) int32 dataset video index per row
    table: CorpusTable,
    refs: RefTables,
    sigma: float = 6.0,
    ref_chunk: int | None = None,
) -> jnp.ndarray:
    """-> (N,) f32 CIDEr-D x10, matching metrics/ciderd.py corpus mode.

    ``ref_chunk``: compute the (N, R, G, P) hyp-ref match contraction in
    slices of at most this many references at a time, bounding the peak
    transient to N·ref_chunk·G·P bytes.  The math is element-for-element
    identical to the unchunked path (the R axis carries no reduction
    until the final masked mean); the only difference XLA may introduce
    is the reduction tiling of the G-axis sum for the smaller shape,
    which is float32 ULP-level — pinned at <= ~4 ULP by
    tests/test_jax_ciderd.py.  None = one shot.
    """
    valid, tf, idf, slot, order_tags, hyp_len = _hyp_ngrams(tokens, table)
    n, P = slot.shape

    # Per-order hyp norms: sum_i valid * tf_i * idf_i^2 over occurrences
    # of order k == sum over distinct (tf*idf)^2.
    contrib = valid * tf * idf * idf                          # (N, P)
    order_onehot = (order_tags[None, :, None]
                    == jnp.arange(1, MAX_N + 1)[None, None, :])  # (1,P,4)
    hnorm = jnp.sqrt(jnp.maximum(
        jnp.sum(contrib[:, :, None] * order_onehot, axis=1), 0.0
    ))                                                        # (N, 4)

    # Gather this batch's reference tables by hypothesis video.
    r_slot = refs.slot[video_ix]          # (N, R, G)
    r_count = refs.count[video_ix]
    r_idf = refs.idf[video_ix]
    r_order = refs.order[video_ix]
    r_norm = refs.norm[video_ix]          # (N, R, 4)
    r_len = refs.length[video_ix]         # (N, R)
    r_mask = refs.ref_mask[video_ix]      # (N, R)

    def num_for_ref_slice(sl: slice) -> jnp.ndarray:
        """Per-order clipped TF-IDF dot for a slice of references.

        h_count per ref entry: occurrences of the entry's n-gram in the
        hyp.  slot == -1 on either side never matches (-1 entries are
        pads or out-of-corpus hyp n-grams, which cannot appear in any
        ref vector)."""
        rs, rc, ri, ro = r_slot[:, sl], r_count[:, sl], r_idf[:, sl], \
            r_order[:, sl]
        match = (rs[:, :, :, None] == slot[:, None, None, :]) & \
                (rs[:, :, :, None] >= 0) & \
                (valid[:, None, None, :] > 0)                 # (N, Rc, G, P)
        h_count = jnp.sum(match, axis=3).astype(jnp.float32)  # (N, Rc, G)
        #   num_k = sum_{entries of order k} idf^2 * min(h_c, r_c) * r_c
        clipped = jnp.minimum(h_count, rc) * rc * ri * ri
        ord_onehot = (ro[:, :, :, None]
                      == jnp.arange(1, MAX_N + 1)[None, None, None, :])
        return jnp.sum(clipped[:, :, :, None] * ord_onehot, axis=2)

    R = r_slot.shape[1]
    if ref_chunk is None or ref_chunk >= R:
        num = num_for_ref_slice(slice(None))                    # (N, R, 4)
    else:
        num = jnp.concatenate(
            [num_for_ref_slice(slice(s, min(s + ref_chunk, R)))
             for s in range(0, R, ref_chunk)], axis=1)

    denom = hnorm[:, None, :] * r_norm                          # (N, R, 4)
    sims = jnp.where(denom > 0, num / jnp.maximum(denom, 1e-12), 0.0)
    delta = hyp_len[:, None] - r_len                            # (N, R)
    penalty = jnp.exp(-(delta * delta) / (2.0 * sigma * sigma))
    per_ref = jnp.mean(sims, axis=2) * penalty * r_mask         # (N, R)
    n_refs = jnp.maximum(jnp.sum(r_mask, axis=1), 1.0)
    return jnp.sum(per_ref, axis=1) / n_refs * 10.0
