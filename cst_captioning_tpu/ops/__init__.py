"""TPU compute ops: attention, losses, sampling, beam search."""

from .attention import AdditiveAttention
from .losses import cross_entropy_loss, reward_loss, sequence_mask, token_logprobs

__all__ = [
    "AdditiveAttention",
    "cross_entropy_loss",
    "reward_loss",
    "sequence_mask",
    "token_logprobs",
]
