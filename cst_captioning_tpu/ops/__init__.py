"""TPU compute ops: attention, losses, sampling, beam search."""

from .attention import AdditiveAttention
from .beam import beam_search, beam_search_tokens, jit_beam_search
from .losses import cross_entropy_loss, reward_loss, sequence_mask, token_logprobs
from .sampling import (
    greedy_decode,
    jit_sampler,
    make_decode_step,
    sample_captions,
    sample_tokens,
)

__all__ = [
    "AdditiveAttention",
    "beam_search",
    "beam_search_tokens",
    "cross_entropy_loss",
    "greedy_decode",
    "jit_beam_search",
    "jit_sampler",
    "make_decode_step",
    "reward_loss",
    "sample_captions",
    "sample_tokens",
    "sequence_mask",
    "token_logprobs",
]
