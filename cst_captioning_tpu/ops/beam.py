"""Batched beam search as one compiled XLA program.

The reference's ``sample_beam`` (SURVEY.md §3.3) loops in Python per video:
expand state ×k, step the LSTM, topk over (beam × vocab), reorder states,
collect finished hypotheses.  That shape — data-dependent control flow per
item — is exactly what kills TPU utilization, so here the WHOLE batch of
beams advances in a single ``lax.scan``:

- decoder state lives as a pytree with leading dim ``B*k``; beam reordering
  is a batched gather over that axis (scalar leaves, e.g. the transformer
  position counter, pass through untouched);
- finished beams are forced to extend with EOS (id 0) at zero cost, so
  token buffers stay 0-padded in the label convention and no per-item
  "collect at EOS" bookkeeping exists;
- step 0 masks beams 1..k-1 to -inf so the k initial hypotheses are the k
  distinct top tokens, not k copies;
- ranking uses optional length normalization ``score / len**alpha``
  (alpha=0 reproduces raw total-logprob ranking; the reference's
  normalization behavior is unverified [SURVEY.md §7 hard part (c)] so it
  is a flag, default off).
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

from .sampling import all_finished, make_decode_step

NEG_INF = -1e9


def _expand_to_beams(tree, beam_size: int, batch: int):
    """Tile each (B, ...) leaf to (B*k, ...); leave scalar leaves alone."""

    def tile(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == batch:
            return jnp.repeat(x, beam_size, axis=0)
        return x

    return jax.tree_util.tree_map(tile, tree)


def _reorder_beams(tree, parent: jnp.ndarray, batch: int, beam_size: int):
    """Gather (B*k, ...) leaves by per-batch parent beam index (B, k)."""
    flat_ix = (
        jnp.arange(batch)[:, None] * beam_size + parent
    ).reshape(-1)                                            # (B*k,)

    def gather(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == batch * beam_size:
            return jnp.take(x, flat_ix, axis=0)
        return x

    return jax.tree_util.tree_map(gather, tree)


def beam_search_tokens(
    step: Callable,
    init_carry,
    batch: int,
    beam_size: int,
    max_len: int,
    length_norm: float = 0.0,
    decode_chunk: int = 0,
    return_steps: bool = False,
):
    """Run beam search over a bound decode ``step``.

    ``init_carry`` must already be expanded to ``B*k`` rows (use
    ``_expand_to_beams``).  Returns (best (B, L), all_beams (B, k, L),
    scores (B, k)) with beams sorted best-first; with ``return_steps=True``
    also an int32 scalar of decode steps actually executed.

    ``decode_chunk`` > 0 is the early-exit fast path: a ``lax.while_loop``
    over fixed-size scan chunks with an all-beams-finished predicate.  An
    all-finished legacy step is a provable no-op that extends every beam
    with EOS at parent=identity (scores descending from the previous
    ``top_k``, EOS at cost 0 beats every non-EOS at NEG_INF, ties broken
    toward lower flat index = lower parent) — so pre-filling the skipped
    steps' buffers with token 0 / parent identity reproduces the legacy
    backtrack bit-exactly (pinned by tests/test_decode_fastpath.py).
    """
    k = beam_size

    def body(state, t):
        carry, prev, scores, finished, lengths = state
        carry, logits = step(carry, prev.reshape(-1))         # (B*k, V)
        vocab = logits.shape[-1]
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(batch, k, vocab)
        # Finished beams: only EOS continues, at zero cost.
        eos_only = jnp.full((vocab,), NEG_INF).at[0].set(0.0)
        logp = jnp.where(finished[:, :, None], eos_only[None, None, :], logp)
        # Step 0: all beams share the same state; keep only beam 0 live.
        init_mask = jnp.where(
            (t == 0) & (jnp.arange(k) > 0), NEG_INF, 0.0
        )
        total = scores[:, :, None] + logp + init_mask[None, :, None]
        total = total.reshape(batch, k * vocab)
        new_scores, flat = jax.lax.top_k(total, k)            # (B, k)
        parent = flat // vocab
        token = (flat % vocab).astype(jnp.int32)
        carry = _reorder_beams(carry, parent, batch, k)
        was_finished = jnp.take_along_axis(finished, parent, axis=1)
        lengths = jnp.take_along_axis(lengths, parent, axis=1)
        lengths = lengths + jnp.where(was_finished, 0, 1)     # count incl. EOS
        finished = was_finished | (token == 0)
        return (carry, token, new_scores, finished, lengths), (token, parent)

    init = (
        init_carry,
        jnp.zeros((batch, k), dtype=jnp.int32),               # BOS
        jnp.zeros((batch, k)),
        jnp.zeros((batch, k), dtype=bool),
        jnp.zeros((batch, k), dtype=jnp.int32),
    )
    if decode_chunk <= 0 or decode_chunk >= max_len:
        (_, _, scores, _, lengths), (tokens, parents) = jax.lax.scan(
            body, init, jnp.arange(max_len)
        )
        steps_executed = jnp.int32(max_len)
    else:
        chunk = int(decode_chunk)
        padded = -(-max_len // chunk) * chunk
        step_ix = jnp.arange(padded)

        def body_clamped(state, t):
            # The last chunk can overrun max_len; unlike the sampler
            # (whose overrun outputs are sliced off), beam scores/lengths
            # live in the CARRY, so overrun steps must be the all-finished
            # no-op step — forcing finished makes every beam extend with
            # EOS at cost 0 (scores, lengths, order all unchanged).
            carry, prev, scores, finished, lengths = state
            state = (carry, prev, scores, finished | (t >= max_len), lengths)
            return body(state, t)

        def chunk_body(loop):
            t, state, toks, pars = loop
            ts = jax.lax.dynamic_slice_in_dim(step_ix, t, chunk, axis=0)
            state, (ctoks, cpars) = jax.lax.scan(body_clamped, state, ts)
            toks = jax.lax.dynamic_update_slice_in_dim(toks, ctoks, t, axis=0)
            pars = jax.lax.dynamic_update_slice_in_dim(pars, cpars, t, axis=0)
            return t + chunk, state, toks, pars

        def chunk_cond(loop):
            t, state, _, _ = loop
            # all_finished reduces the (B, k) per-beam buffer per item
            # first (ops/sampling.py finished_mask) — same predicate the
            # serving engine's slot recycler reads per row.
            return (t < max_len) & ~all_finished(state[3])

        # Skipped steps pre-filled with the all-finished step's provable
        # output: token 0, parent identity (docstring above).
        ident = jnp.broadcast_to(jnp.arange(k)[None, None, :],
                                 (padded, batch, k))
        t_end, state, tokens, parents = jax.lax.while_loop(
            chunk_cond, chunk_body,
            (jnp.int32(0), init,
             jnp.zeros((padded, batch, k), jnp.int32), ident),
        )
        scores, lengths = state[2], state[4]
        tokens, parents = tokens[:max_len], parents[:max_len]
        steps_executed = jnp.minimum(t_end, max_len)
    # Backtrack (L, B, k) token/parent chains into (B, k, L) sequences.
    def back(beam_ix, tp):                                     # beam_ix (B, k)
        tok_t, par_t = tp                                      # each (B, k)
        toks = jnp.take_along_axis(tok_t, beam_ix, axis=1)
        beam_ix = jnp.take_along_axis(par_t, beam_ix, axis=1)
        return beam_ix, toks

    # Walk from the last step to the first; tokens come out reversed.
    last_ix = jnp.tile(jnp.arange(k)[None, :], (batch, 1))
    _, rev = jax.lax.scan(back, last_ix, (tokens[::-1], parents[::-1]))
    seqs = rev[::-1].transpose(1, 2, 0)                        # (B, k, L)

    ranked = scores
    if length_norm > 0:
        ranked = scores / jnp.maximum(lengths, 1) ** length_norm
    order = jnp.argsort(-ranked, axis=1)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    ranked = jnp.take_along_axis(ranked, order, axis=1)
    out = (seqs[:, 0, :], seqs, ranked)
    return out + (steps_executed,) if return_steps else out


def beam_search(
    model,
    variables,
    feats: Sequence[jnp.ndarray],
    beam_size: int,
    max_len: int,
    length_norm: float = 0.0,
    decode_chunk: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Encode + beam-decode a batch of videos.

    -> (best (B, L) 0-terminated, all beams (B, k, L), scores (B, k)).
    """
    memory, proj_mem, pooled = model.apply(
        variables, feats, method="encode"
    )
    batch = pooled.shape[0]
    memory, proj_mem, pooled = _expand_to_beams(
        (memory, proj_mem, pooled), beam_size, batch
    )
    carry = model.apply(
        variables, pooled, max_len, method="init_carry"
    )
    step = make_decode_step(model, variables, memory, proj_mem, pooled)
    return beam_search_tokens(step, carry, batch, beam_size, max_len,
                              length_norm=length_norm,
                              decode_chunk=decode_chunk)


def jit_beam_search(model, beam_size: int, max_len: int,
                    length_norm: float = 0.0, decode_chunk: int = 0):
    """jit-compiled beam search: (variables, feats) -> (best, beams, scores)."""

    @jax.jit
    def fn(variables, feats):
        return beam_search(model, variables, feats, beam_size, max_len,
                           length_norm=length_norm,
                           decode_chunk=decode_chunk)

    return fn
