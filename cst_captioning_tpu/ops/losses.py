"""Sequence losses: masked XE, consensus-weighted XE, REINFORCE.

Pure functions over (logits, labels, ...) — the reference's
``CrossEntropyCriterion`` / ``RewardCriterion`` modules (SURVEY.md §2)
become jit-compatible functions with no state, differentiable end to end.

Masking convention (matches the reference's 0=EOS labels): position t is
supervised iff every earlier target token is nonzero — i.e. tokens up to
AND INCLUDING the first 0 (the model must learn to emit EOS), everything
after is padding.  Implemented with a cumulative product, no Python loops.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def sequence_mask(targets: jnp.ndarray) -> jnp.ndarray:
    """(N, L) 0-terminated targets -> float mask covering words + first EOS.

    mask[:, 0] = 1 always; mask[:, t] = all(targets[:, :t] != 0).
    """
    nonzero = (targets != 0).astype(jnp.float32)
    leading = jnp.cumprod(nonzero[:, :-1], axis=1)
    return jnp.concatenate(
        [jnp.ones_like(nonzero[:, :1]), leading], axis=1
    )


def token_logprobs(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """log p(target_t) per position: (N, L, V), (N, L) -> (N, L)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def cross_entropy_loss(
    logits: jnp.ndarray,                 # (N, L, V)
    targets: jnp.ndarray,                # (N, L) 0-terminated
    weights: Optional[jnp.ndarray] = None,  # (N,) per-caption consensus weights
) -> jnp.ndarray:
    """Masked sequence XE; with ``weights`` this is the WXE criterion
    (per-caption scalar multiplies that caption's token losses).

    Normalized by the *unweighted* mask total so XE and WXE are on the same
    scale (normalize_weights keeps mean weight at 1), and learning rates
    transfer between the XE -> WXE stages.
    """
    mask = sequence_mask(targets)
    nll = -token_logprobs(logits, targets) * mask
    if weights is not None:
        nll = nll * weights[:, None]
    return nll.sum() / jnp.maximum(mask.sum(), 1.0)


def reward_loss(
    sample_logprobs: jnp.ndarray,        # (N, L) log p of the sampled tokens
    sampled: jnp.ndarray,                # (N, L) sampled token ids, 0-terminated
    advantage: jnp.ndarray,              # (N,) reward - baseline, no gradient
) -> jnp.ndarray:
    """REINFORCE: -E[advantage * log p(sampled)], masked to the sampled
    sequence (words + first EOS).  ``advantage`` is treated as a constant
    (stop_gradient), matching the reference RewardCriterion semantics.
    """
    mask = sequence_mask(sampled)
    adv = jax.lax.stop_gradient(advantage)[:, None]
    loss = -(sample_logprobs * adv * mask)
    return loss.sum() / jnp.maximum(mask.sum(), 1.0)
