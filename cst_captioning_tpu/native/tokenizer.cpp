// PTB-style caption tokenizer — C++ twin of metrics/tokenizer.py.
//
// The reference's tokenizer is NATIVE code (the Stanford CoreNLP
// PTBTokenizer jar, invoked as a subprocess by coco-caption; SURVEY.md §2
// native table).  metrics/tokenizer.py reimplements its observable
// contract in pure Python; this file is the same contract in C++ for the
// bulk corpus paths (trainer startup tokenizes every training caption,
// language_eval every prediction).  Parity with the Python implementation
// is pinned token-for-token by tests/test_native_tokenizer.py (golden
// cases + random fuzz); the Python module remains the oracle and the
// fallback, and non-ASCII captions are always routed to Python (C++ would
// need ICU for unicode case folding).
//
// Contract (mirrors metrics/tokenizer.py EXACTLY, quirks included):
//   1. isolate "..."/"--" and the punctuation set , ; : @ # $ % & ? ! "
//      ( ) { } [ ] < > = + / \ * ^ ~ |
//   2. split contraction suffixes ('ll 're 've n't 's 'm 'd) off a
//      preceding letter when followed by a non-word char, left to right,
//      non-overlapping
//   3. per whitespace token: special splits (cannot -> can not, ...);
//      else drop ONE sentence-terminal period unless the token is
//      abbreviation-shaped (([a-z].)+); strip surrounding apostrophes
//      unless the token is itself a kept contraction token; map brackets
//      to -LRB-/-RRB-/-LCB-/-RCB-; drop coco-caption's punctuation set;
//      lowercase.
//
// extern "C" surface (ctypes, no pybind11 per environment constraints):
//   ptb_tokenize(in, out, cap) -> bytes written to out (space-joined
//   tokens), or -1 if out is too small.  ASCII-only input expected.

#include <cctype>
#include <cstring>
#include <string>
#include <vector>

namespace {

bool is_isolate_char(char c) {
    switch (c) {
        case ',': case ';': case ':': case '@': case '#': case '$':
        case '%': case '&': case '?': case '!': case '"': case '(':
        case ')': case '{': case '}': case '[': case ']': case '<':
        case '>': case '=': case '+': case '/': case '\\': case '*':
        case '^': case '~': case '|':
            return true;
        default:
            return false;
    }
}

bool is_word_char(char c) {
    return std::isalnum(static_cast<unsigned char>(c)) || c == '_';
}

char lower(char c) {
    return static_cast<char>(std::tolower(static_cast<unsigned char>(c)));
}

// Contraction suffixes, longest first ("n't" before "'d" etc. is not
// required for correctness — matches start at a fixed position — but keep
// the regex's alternation order for identical left-to-right semantics.
const char* kSuffixes[] = {"'ll", "'re", "'ve", "n't", "'s", "'m", "'d"};

bool match_ci(const std::string& s, size_t pos, const char* pat) {
    size_t n = std::strlen(pat);
    if (pos + n > s.size()) return false;
    for (size_t k = 0; k < n; ++k) {
        if (lower(s[pos + k]) != pat[k]) return false;
    }
    return true;
}

struct SpecialSplit {
    const char* word;
    const char* a;
    const char* b;
};
const SpecialSplit kSpecial[] = {
    {"cannot", "can", "not"}, {"gonna", "gon", "na"},
    {"gotta", "got", "ta"},   {"wanna", "wan", "na"},
    {"lemme", "lem", "me"},   {"gimme", "gim", "me"},
    {"d'ye", "d'", "ye"},     {"'tis", "'t", "is"},
    {"'twas", "'t", "was"},
};

const char* kContractionTokens[] = {"'s", "'re", "'ve", "'ll",
                                    "'m", "'d", "n't", "'t"};

// Original case, matching the Python set exactly: a LITERAL input token
// "-lrb-" is kept by the oracle (the set holds only "-LRB-", and the
// lowercase membership test compares against the uppercase entries), while
// the bracket-mapped "-LRB-" matches case-sensitively and is dropped.
const char* kPunctuations[] = {
    "''", "'", "``", "`", "-LRB-", "-RRB-", "-LCB-", "-RCB-",
    ".", "?", "!", ",", ":", "-", "--", "...", ";",
};

bool is_abbrev(const std::string& t) {  // ^([a-z]\.)+$ case-insensitive
    if (t.empty() || t.size() % 2 != 0) return false;
    for (size_t i = 0; i < t.size(); i += 2) {
        if (!std::isalpha(static_cast<unsigned char>(t[i])) ||
            t[i + 1] != '.') {
            return false;
        }
    }
    return true;
}

std::string to_lower(const std::string& t) {
    std::string out(t);
    for (char& c : out) c = lower(c);
    return out;
}

void emit(std::vector<std::string>& out, const std::string& raw) {
    std::string tok = raw;
    std::string low = to_lower(tok);
    for (const auto& sp : kSpecial) {
        if (low == sp.word) {
            out.push_back(sp.a);
            out.push_back(sp.b);
            return;
        }
    }
    // Sentence-terminal period: split off ONE unless abbreviation-shaped
    // or the token is dots-only (strip('.') empty in the Python source).
    if (!tok.empty() && tok.back() == '.') {
        bool all_dots = tok.find_first_not_of('.') == std::string::npos;
        if (!all_dots && !is_abbrev(tok)) tok.pop_back();
    }
    // Surrounding apostrophes are quote chars; contraction tokens exempt.
    low = to_lower(tok);
    bool keep_apostrophes = false;
    for (const char* ct : kContractionTokens) {
        if (low == ct) { keep_apostrophes = true; break; }
    }
    if (!keep_apostrophes) {
        size_t b = tok.find_first_not_of('\'');
        if (b == std::string::npos) {
            tok.clear();
        } else {
            size_t e = tok.find_last_not_of('\'');
            tok = tok.substr(b, e - b + 1);
        }
    }
    if (tok.empty()) return;
    if (tok == "(" || tok == "[") tok = "-LRB-";
    else if (tok == ")" || tok == "]") tok = "-RRB-";
    else if (tok == "{") tok = "-LCB-";
    else if (tok == "}") tok = "-RCB-";
    low = to_lower(tok);
    // Mirror Python: tok in PUNCTUATIONS or low in PUNCTUATIONS or low == '"'
    for (const char* p : kPunctuations) {
        if (tok == p || low == p) return;
    }
    if (low == "\"") return;
    out.push_back(low);
}

// Python str.split() whitespace within ASCII: \t\n\v\f\r, \x1c-\x1f, space
// (C isspace misses the information-separator range \x1c-\x1f).
bool is_py_space(char c) {
    unsigned char u = static_cast<unsigned char>(c);
    return (u >= 0x09 && u <= 0x0d) || (u >= 0x1c && u <= 0x1f) || u == ' ';
}

std::vector<std::string> tokenize(const std::string& caption) {
    // Pass 1: newline -> space; isolate .../--/punctuation chars.
    std::string s;
    s.reserve(caption.size() * 2);
    for (size_t i = 0; i < caption.size();) {
        char c = caption[i];
        if (c == '\n') {
            s += ' ';
            ++i;
        } else if (c == '.' && i + 2 < caption.size() &&
                   caption[i + 1] == '.' && caption[i + 2] == '.') {
            s += " ... ";
            i += 3;
        } else if (c == '-' && i + 1 < caption.size() &&
                   caption[i + 1] == '-') {
            s += " -- ";
            i += 2;
        } else if (is_isolate_char(c)) {
            s += ' ';
            s += c;
            s += ' ';
            ++i;
        } else {
            s += c;
            ++i;
        }
    }
    // Pass 2: contraction suffix splitting, left to right, non-overlapping.
    // re.sub resumes scanning AFTER each match, and the match includes the
    // preceding letter (group 1) — so a suffix whose letter was consumed by
    // the previous match must NOT split ("can't've" -> "ca n't've", the
    // 've stays attached).  last_end tracks the consumed frontier.
    std::string t;
    t.reserve(s.size() + 16);
    size_t last_end = 0;
    for (size_t i = 0; i < s.size();) {
        bool matched = false;
        if (i > 0 && i - 1 >= last_end &&
            std::isalpha(static_cast<unsigned char>(s[i - 1]))) {
            for (const char* suf : kSuffixes) {
                if (match_ci(s, i, suf)) {
                    size_t end = i + std::strlen(suf);
                    if (end >= s.size() || !is_word_char(s[end])) {
                        t += ' ';
                        t.append(s, i, std::strlen(suf));
                        i = end;
                        last_end = end;
                        matched = true;
                        break;
                    }
                }
            }
        }
        if (!matched) {
            t += s[i];
            ++i;
        }
    }
    // Pass 3: whitespace split + per-token normalization.
    std::vector<std::string> out;
    size_t i = 0;
    while (i < t.size()) {
        while (i < t.size() && is_py_space(t[i])) ++i;
        size_t start = i;
        while (i < t.size() && !is_py_space(t[i])) ++i;
        if (i > start) emit(out, t.substr(start, i - start));
    }
    return out;
}

}  // namespace

extern "C" {

// Tokenize one ASCII caption; write space-joined tokens to out.
// Returns bytes written (excluding NUL), or -1 if out_cap is too small.
int ptb_tokenize(const char* in, char* out, int out_cap) {
    std::vector<std::string> toks = tokenize(std::string(in));
    size_t need = 0;
    for (const auto& t : toks) need += t.size() + 1;
    if (need + 1 > static_cast<size_t>(out_cap)) return -1;
    char* p = out;
    for (size_t k = 0; k < toks.size(); ++k) {
        if (k) *p++ = ' ';
        std::memcpy(p, toks[k].data(), toks[k].size());
        p += toks[k].size();
    }
    *p = '\0';
    return static_cast<int>(p - out);
}

// Batch form: caption i is buf[offs[i]..offs[i+1]).  Outputs are written
// back-to-back into out with out_offs[i]..out_offs[i+1] delimiting
// caption i's space-joined tokens (out_offs has n+1 entries).  Returns
// total bytes written, or -1 if out_cap is too small.  One call replaces
// n ctypes round trips on the corpus-tokenization path.
int ptb_tokenize_batch(const char* buf, const int* offs, int n,
                       char* out, int out_cap, int* out_offs) {
    size_t pos = 0;
    out_offs[0] = 0;
    for (int i = 0; i < n; ++i) {
        std::string caption(buf + offs[i], buf + offs[i + 1]);
        std::vector<std::string> toks = tokenize(caption);
        size_t need = 0;
        for (const auto& t : toks) need += t.size() + 1;
        if (pos + need > static_cast<size_t>(out_cap)) return -1;
        for (size_t k = 0; k < toks.size(); ++k) {
            if (k) out[pos++] = ' ';
            std::memcpy(out + pos, toks[k].data(), toks[k].size());
            pos += toks[k].size();
        }
        out_offs[i + 1] = static_cast<int>(pos);
    }
    return static_cast<int>(pos);
}

}  // extern "C"
