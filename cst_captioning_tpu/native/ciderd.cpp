// Native CIDEr-D reward scorer — the RL hot loop's host-side kernel.
//
// The reference's per-iteration reward cost is pure-Python n-gram TF-IDF
// (vendored pyciderevalcap; SURVEY.md §3.2).  This implementation keeps the
// same math (CIDEr-D: 1..4-grams, clipped TF-IDF cosine, gaussian length
// penalty, corpus document frequencies, x10 scale — parity-tested against
// metrics/ciderd.py) but works directly on int32 token-id sequences, so the
// sampled rollout never round-trips through Python strings.
//
// Contract (ctypes, see native/__init__.py):
//   h = ciderd_new(n, sigma)
//   ciderd_add_video(h, tokens_flat, ref_lens, n_refs)   // repeat per video
//   ciderd_finalize(h)                                   // df + ref vectors
//   ciderd_score(h, video_ix, hyps, max_len, n_hyps, out)
//   ciderd_free(h)
// Token id 0 terminates a hypothesis row (the framework's PAD/EOS id);
// reference captions are length-prefixed and may contain any nonzero id.

#include <cmath>
#include <cstdint>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kMaxN = 4;

// FNV-1a over (order, ids...) — order is mixed in so the 1-gram (a) and the
// leading token of the 2-gram (a,b) hash differently.
inline uint64_t ngram_hash(const int32_t* ids, int k) {
  uint64_t h = 1469598103934665603ULL ^ static_cast<uint64_t>(k);
  for (int i = 0; i < k; ++i) {
    h ^= static_cast<uint64_t>(static_cast<uint32_t>(ids[i])) + 0x9e3779b9ULL;
    h *= 1099511628211ULL;
  }
  return h;
}

using CountMap = std::unordered_map<uint64_t, int>;
using WeightMap = std::unordered_map<uint64_t, double>;

struct Cooked {
  CountMap counts[kMaxN];  // per order k-1
  int length = 0;          // unigram count
};

void cook(const int32_t* ids, int len, int n, Cooked* out) {
  out->length = len;
  for (int k = 1; k <= n; ++k) {
    CountMap& m = out->counts[k - 1];
    for (int i = 0; i + k <= len; ++i) {
      ++m[ngram_hash(ids + i, k)];
    }
  }
}

struct RefVec {
  WeightMap vec[kMaxN];
  double norm[kMaxN] = {0, 0, 0, 0};
  int length = 0;
};

struct Scorer {
  int n = kMaxN;
  double sigma = 6.0;
  bool finalized = false;
  std::unordered_map<uint64_t, double> df;
  double log_ref_len = 0.0;
  std::vector<std::vector<Cooked>> raw;    // per video, per ref (pre-df)
  std::vector<std::vector<RefVec>> videos; // post-finalize TF-IDF

  double idf(uint64_t h) const {
    auto it = df.find(h);
    double d = it == df.end() ? 0.0 : it->second;
    return log_ref_len - std::log(d < 1.0 ? 1.0 : d);
  }
};

void to_tfidf(const Scorer& s, const Cooked& c, RefVec* out) {
  out->length = c.length;
  for (int k = 0; k < s.n; ++k) {
    double norm2 = 0.0;
    for (const auto& [h, tf] : c.counts[k]) {
      double w = tf * s.idf(h);
      out->vec[k][h] = w;
      norm2 += w * w;
    }
    out->norm[k] = std::sqrt(norm2);
  }
}

}  // namespace

extern "C" {

void* ciderd_new(int n, double sigma) {
  auto* s = new Scorer();
  s->n = n > kMaxN ? kMaxN : (n < 1 ? 1 : n);
  s->sigma = sigma;
  return s;
}

void ciderd_free(void* handle) { delete static_cast<Scorer*>(handle); }

// tokens_flat: concatenation of the video's reference captions;
// ref_lens[i] = length of reference i.
void ciderd_add_video(void* handle, const int32_t* tokens_flat,
                      const int32_t* ref_lens, int n_refs) {
  auto* s = static_cast<Scorer*>(handle);
  std::vector<Cooked> cooked(n_refs);
  const int32_t* p = tokens_flat;
  for (int r = 0; r < n_refs; ++r) {
    cook(p, ref_lens[r], s->n, &cooked[r]);
    p += ref_lens[r];
  }
  s->raw.push_back(std::move(cooked));
}

namespace {

// (Re)build every reference's TF-IDF vector from the current df table.
void build_vectors(Scorer* s) {
  s->videos.clear();
  s->videos.resize(s->raw.size());
  for (size_t v = 0; v < s->raw.size(); ++v) {
    s->videos[v].resize(s->raw[v].size());
    for (size_t r = 0; r < s->raw[v].size(); ++r) {
      to_tfidf(*s, s->raw[v][r], &s->videos[v][r]);
    }
  }
  s->finalized = true;
}

}  // namespace

// Builds corpus document frequencies (df = number of videos whose reference
// set contains the n-gram) and the per-reference TF-IDF vectors.
void ciderd_finalize(void* handle) {
  auto* s = static_cast<Scorer*>(handle);
  s->df.clear();
  for (const auto& video : s->raw) {
    std::unordered_map<uint64_t, char> seen;
    for (const auto& ref : video) {
      for (int k = 0; k < s->n; ++k) {
        for (const auto& [h, tf] : ref.counts[k]) seen.emplace(h, 1);
      }
    }
    for (const auto& [h, one] : seen) s->df[h] += 1.0;
  }
  double nd = static_cast<double>(s->raw.size());
  s->log_ref_len = std::log(nd < 1.0 ? 1.0 : nd);
  build_vectors(s);
}

// Replace the document-frequency table with an EXTERNAL corpus df (the
// reference's --train_cached_tokens pickle): hashes[i] (ngram_hash of the
// id-encoded n-gram) -> counts[i], over ref_len documents.  Rebuilds the
// reference TF-IDF vectors under the new weights.  Call after add_video
// (+finalize); scoring then matches a Python CiderD loaded from the pickle.
int ciderd_set_df(void* handle, const uint64_t* hashes, const double* counts,
                  int n_entries, double ref_len) {
  auto* s = static_cast<Scorer*>(handle);
  if (n_entries < 0 || ref_len < 1.0) return -1;
  s->df.clear();
  for (int i = 0; i < n_entries; ++i) s->df[hashes[i]] = counts[i];
  s->log_ref_len = std::log(ref_len);
  build_vectors(s);
  return 0;
}

int ciderd_num_videos(void* handle) {
  return static_cast<int>(static_cast<Scorer*>(handle)->raw.size());
}

// hyps: (n_hyps, max_len) row-major int32, rows 0-terminated (id 0 = EOS;
// everything at and after the first 0 is ignored).  video_ix[i] selects the
// reference set for hypothesis i.  out[i] = CIDEr-D score x10.
int ciderd_score(void* handle, const int32_t* video_ix, const int32_t* hyps,
                 int max_len, int n_hyps, double* out) {
  auto* s = static_cast<Scorer*>(handle);
  if (!s->finalized) return -1;
  const double inv_2sig2 = 1.0 / (2.0 * s->sigma * s->sigma);

  for (int i = 0; i < n_hyps; ++i) {
    int v = video_ix[i];
    if (v < 0 || v >= static_cast<int>(s->videos.size())) return -2;
    const int32_t* row = hyps + static_cast<int64_t>(i) * max_len;
    int len = 0;
    while (len < max_len && row[len] != 0) ++len;

    Cooked c;
    cook(row, len, s->n, &c);
    WeightMap hv[kMaxN];
    double hnorm[kMaxN];
    for (int k = 0; k < s->n; ++k) {
      double norm2 = 0.0;
      for (const auto& [h, tf] : c.counts[k]) {
        double w = tf * s->idf(h);
        hv[k][h] = w;
        norm2 += w * w;
      }
      hnorm[k] = std::sqrt(norm2);
    }

    const auto& refs = s->videos[v];
    double total = 0.0;
    for (const auto& ref : refs) {
      double delta = static_cast<double>(len - ref.length);
      double penalty = std::exp(-delta * delta * inv_2sig2);
      double per_ref = 0.0;
      for (int k = 0; k < s->n; ++k) {
        if (hnorm[k] == 0.0 || ref.norm[k] == 0.0) continue;
        double acc = 0.0;
        for (const auto& [h, hw] : hv[k]) {
          auto it = ref.vec[k].find(h);
          if (it == ref.vec[k].end()) continue;
          double rw = it->second;
          acc += (hw < rw ? hw : rw) * rw;  // CIDEr-D count clipping
        }
        per_ref += acc / (hnorm[k] * ref.norm[k]);
      }
      total += per_ref / s->n * penalty;
    }
    out[i] = refs.empty() ? 0.0 : total / refs.size() * 10.0;
  }
  return 0;
}

// Leave-one-out consensus: out[j] = CIDEr-D of video's reference j scored
// against its R-1 siblings (df = full corpus) — the offline artifact behind
// WXE weights and the SCB baseline.  out must hold the video's ref count.
int ciderd_score_loo(void* handle, int video, double* out) {
  auto* s = static_cast<Scorer*>(handle);
  if (!s->finalized) return -1;
  if (video < 0 || video >= static_cast<int>(s->videos.size())) return -2;
  const auto& refs = s->videos[video];
  const int R = static_cast<int>(refs.size());
  const double inv_2sig2 = 1.0 / (2.0 * s->sigma * s->sigma);

  for (int j = 0; j < R; ++j) {
    const RefVec& hyp = refs[j];
    double total = 0.0;
    for (int r = 0; r < R; ++r) {
      if (r == j) continue;
      const RefVec& ref = refs[r];
      double delta = static_cast<double>(hyp.length - ref.length);
      double penalty = std::exp(-delta * delta * inv_2sig2);
      double per_ref = 0.0;
      for (int k = 0; k < s->n; ++k) {
        if (hyp.norm[k] == 0.0 || ref.norm[k] == 0.0) continue;
        double acc = 0.0;
        for (const auto& [h, hw] : hyp.vec[k]) {
          auto it = ref.vec[k].find(h);
          if (it == ref.vec[k].end()) continue;
          double rw = it->second;
          acc += (hw < rw ? hw : rw) * rw;
        }
        per_ref += acc / (hyp.norm[k] * ref.norm[k]);
      }
      total += per_ref / s->n * penalty;
    }
    out[j] = R > 1 ? total / (R - 1) * 10.0 : 0.0;
  }
  return 0;
}

int ciderd_num_refs(void* handle, int video) {
  auto* s = static_cast<Scorer*>(handle);
  if (video < 0 || video >= static_cast<int>(s->raw.size())) return -1;
  return static_cast<int>(s->raw[video].size());
}

}  // extern "C"
