"""Native (C++) reward scorer binding — ctypes, compiled on first use.

The RL stage calls CIDEr-D once per training step on every sampled +
baseline caption (SURVEY.md §3.2 hot loop).  ``NativeCiderD`` keeps that
work in C++ and consumes token-id arrays straight from the device rollout —
no id->string->split round trip.  Scores are parity-tested against
``metrics.ciderd.CiderD`` (tests/test_native_ciderd.py).

Build model: a single translation unit compiled with g++ into a shared
library next to the source, rebuilt automatically when the .cpp is newer
(no pybind11 — plain ``extern "C"`` + ctypes, per the environment's
toolchain constraints).  Callers that must run without a toolchain catch
``NativeUnavailable`` and fall back to the pure-Python scorer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from ..utils.locksan import named_lock

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ciderd.cpp")
_LIB = os.path.join(_DIR, "libciderd.so")
# One build/load lock for BOTH libraries; library handles are guarded so
# two threads racing first-use can never double-build or load a
# half-written .so (cstlint:guarded-by).
_LOCK = named_lock("native.build")
_loaded: Optional[ctypes.CDLL] = None  # cstlint: guarded_by=_LOCK


class NativeUnavailable(RuntimeError):
    """Raised when the shared library cannot be built/loaded."""


def _build(src: str = _SRC, lib_path: str = _LIB) -> None:
    # No -march=native: the .so is cached on disk and a host-specific ISA
    # would SIGILL (uncatchable) if the cache ever moved between machines.
    # Build to a per-process temp name + rename so concurrent processes
    # (multi-host shared storage, parallel test workers) never load a
    # half-written library.
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, lib_path)
    except FileNotFoundError as e:
        raise NativeUnavailable("g++ not available") from e
    except subprocess.CalledProcessError as e:
        raise NativeUnavailable(f"native build failed:\n{e.stderr}") from e
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _build_if_stale(src: str, lib_path: str) -> ctypes.CDLL:
    if (not os.path.exists(lib_path)
            or os.path.getmtime(lib_path) < os.path.getmtime(src)):
        _build(src, lib_path)
    try:
        return ctypes.CDLL(lib_path)
    except OSError as e:
        raise NativeUnavailable(f"cannot load {lib_path}: {e}") from e


def load_library() -> ctypes.CDLL:
    """Compile (if stale) and load libciderd.so; cached per process."""
    global _loaded
    with _LOCK:
        if _loaded is not None:
            return _loaded
        lib = _build_if_stale(_SRC, _LIB)
        lib.ciderd_new.restype = ctypes.c_void_p
        lib.ciderd_new.argtypes = [ctypes.c_int, ctypes.c_double]
        lib.ciderd_free.argtypes = [ctypes.c_void_p]
        lib.ciderd_add_video.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ]
        lib.ciderd_finalize.argtypes = [ctypes.c_void_p]
        lib.ciderd_num_videos.restype = ctypes.c_int
        lib.ciderd_num_videos.argtypes = [ctypes.c_void_p]
        lib.ciderd_score.restype = ctypes.c_int
        lib.ciderd_score.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.ciderd_score_loo.restype = ctypes.c_int
        lib.ciderd_score_loo.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_double),
        ]
        lib.ciderd_num_refs.restype = ctypes.c_int
        lib.ciderd_num_refs.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ciderd_set_df.restype = ctypes.c_int
        lib.ciderd_set_df.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_double,
        ]
        _loaded = lib
        return lib


def _as_i32_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


_U64 = (1 << 64) - 1


def fnv_ngram_hash(ids) -> int:
    """Python replica of ciderd.cpp's ``ngram_hash`` (FNV-1a over
    (order, ids...)) — MUST stay bit-identical to the C++ so external df
    tables hash into the same buckets as the library's own cooking."""
    h = (1469598103934665603 ^ len(ids)) & _U64
    for i in ids:
        h ^= ((int(i) & 0xFFFFFFFF) + 0x9E3779B9) & _U64
        h = (h * 1099511628211) & _U64
    return h


class NativeCiderD:
    """Corpus-df CIDEr-D over token ids, references fixed at construction.

    Args:
      tokenized_refs: {video_id: [pre-tokenized caption string, ...]} — the
        training references (the corpus that defines document frequencies,
        like the reference's ``--train_cached_tokens`` pickle).
      word_to_ix: seed word->id mapping (the model vocab).  Reference words
        outside it get fresh ids here — they can never match a hypothesis
        (hyp ids come from the model vocab) but must still contribute to
        reference norms and df, exactly as in the string scorer.
    """

    def __init__(
        self,
        tokenized_refs: Mapping[str, Sequence[str]],
        word_to_ix: Optional[Mapping[str, int]] = None,
        n: int = 4,
        sigma: float = 6.0,
    ):
        self._lib = load_library()
        self.n = n
        self.sigma = sigma
        self._w2i: Dict[str, int] = dict(word_to_ix or {})
        self._next_id = max(self._w2i.values(), default=0) + 1
        self._video_ix: Dict[str, int] = {}
        self._handle = self._lib.ciderd_new(n, sigma)
        try:
            for vid, caps in tokenized_refs.items():
                rows = [self._encode(c) for c in caps]
                lens = np.asarray([len(r) for r in rows], dtype=np.int32)
                flat = (np.concatenate(rows).astype(np.int32)
                        if rows else np.zeros(0, np.int32))
                self._lib.ciderd_add_video(
                    self._handle, _as_i32_ptr(flat), _as_i32_ptr(lens),
                    len(rows),
                )
                self._video_ix[vid] = len(self._video_ix)
            self._lib.ciderd_finalize(self._handle)
        except Exception:
            self.close()
            raise

    def _word_id(self, w: str) -> int:
        ix = self._w2i.get(w)
        if ix is None:
            ix = self._next_id
            self._w2i[w] = ix
            self._next_id += 1
        return ix

    def _encode(self, caption: str) -> np.ndarray:
        return np.asarray(
            [self._word_id(w) for w in caption.split()], dtype=np.int32
        )

    def load_df(self, df, ref_len: float) -> None:
        """Install an external corpus document-frequency table — the
        reference's ``--train_cached_tokens`` pickle
        (``metrics.ciderd.load_corpus_df`` format: {ngram word tuple:
        doc count}, ref_len documents).  Replaces the df built from this
        run's references and rebuilds the reference TF-IDF vectors, so
        scores match a Python ``CiderD(df_mode="corpus", df_path=...)``
        exactly (tests/test_native_ciderd.py pickle-path parity)."""
        hashes = np.asarray(
            [fnv_ngram_hash([self._word_id(w) for w in ng]) for ng in df],
            dtype=np.uint64,
        )
        counts = np.asarray(list(df.values()), dtype=np.float64)
        rc = self._lib.ciderd_set_df(
            self._handle,
            hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(hashes), float(ref_len),
        )
        if rc != 0:
            raise RuntimeError(f"ciderd_set_df failed with code {rc}")

    # -- scoring -----------------------------------------------------------

    def score_ids(self, video_ids: Sequence[str],
                  hyps: np.ndarray) -> np.ndarray:
        """Score 0-terminated id rows (N, L); N must be a multiple of
        len(video_ids), rows grouped per video (the rollout layout): row i
        belongs to ``video_ids[i // (N // len(video_ids))]``."""
        hyps = np.ascontiguousarray(hyps, dtype=np.int32)
        n_hyps, max_len = hyps.shape
        if n_hyps % len(video_ids) != 0:
            raise ValueError(
                f"{n_hyps} hypothesis rows not a multiple of "
                f"{len(video_ids)} videos — rows must be grouped per video"
            )
        per_vid = n_hyps // len(video_ids)
        ix = np.asarray(
            [self._video_ix[video_ids[i // per_vid]] for i in range(n_hyps)],
            dtype=np.int32,
        )
        out = np.zeros(n_hyps, dtype=np.float64)
        rc = self._lib.ciderd_score(
            self._handle, _as_i32_ptr(ix), _as_i32_ptr(hyps),
            max_len, n_hyps,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
        if rc != 0:
            raise RuntimeError(f"ciderd_score failed with code {rc}")
        return out

    def score_strings(self, video_ids: Sequence[str],
                      captions: Sequence[str]) -> np.ndarray:
        """Tokenized caption strings -> scores (parity/test path)."""
        rows = [self._encode(c) for c in captions]
        max_len = max((len(r) for r in rows), default=0) + 1
        mat = np.zeros((len(rows), max_len), dtype=np.int32)
        for i, r in enumerate(rows):
            mat[i, : len(r)] = r
        return self.score_ids(video_ids, mat)

    def consensus_scores(self) -> Dict[str, np.ndarray]:
        """Leave-one-out CIDEr-D of every reference vs its siblings, for all
        videos — the native fast path behind
        ``metrics.consensus.compute_consensus_scores``."""
        out: Dict[str, np.ndarray] = {}
        for vid, v in self._video_ix.items():
            r = int(self._lib.ciderd_num_refs(self._handle, v))
            buf = np.zeros(max(r, 1), dtype=np.float64)
            rc = self._lib.ciderd_score_loo(
                self._handle, v,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            )
            if rc != 0:
                raise RuntimeError(f"ciderd_score_loo failed with code {rc}")
            out[vid] = buf[:r] if r else np.zeros(1)
        return out

    @property
    def num_videos(self) -> int:
        return int(self._lib.ciderd_num_videos(self._handle))

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.ciderd_free(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


# -- native PTB tokenizer (tokenizer.cpp) ---------------------------------

_TOK_SRC = os.path.join(_DIR, "tokenizer.cpp")
_TOK_LIB = os.path.join(_DIR, "libptbtok.so")
_tok_loaded: Optional[ctypes.CDLL] = None  # cstlint: guarded_by=_LOCK


def load_tokenizer_library() -> ctypes.CDLL:
    """Compile (if stale) and load libptbtok.so; cached per process."""
    global _tok_loaded
    with _LOCK:
        if _tok_loaded is not None:
            return _tok_loaded
        lib = _build_if_stale(_TOK_SRC, _TOK_LIB)
        lib.ptb_tokenize.restype = ctypes.c_int
        lib.ptb_tokenize.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.ptb_tokenize_batch.restype = ctypes.c_int
        lib.ptb_tokenize_batch.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
        ]
        _tok_loaded = lib
        return lib


def ptb_tokenize_str(caption: str) -> str:
    """C++ twin of ``metrics.tokenizer.tokenize_to_str`` for ASCII input.

    Raises NativeUnavailable if the library cannot build/load and
    ValueError for non-ASCII input (unicode case folding needs the Python
    path) — callers fall back to the Python tokenizer either way.
    """
    if not caption.isascii():
        raise ValueError("native tokenizer is ASCII-only")
    lib = load_tokenizer_library()
    raw = caption.encode("ascii")
    cap = max(2 * len(raw) + 64, 256)
    buf = ctypes.create_string_buffer(cap)
    n = lib.ptb_tokenize(raw, buf, cap)
    if n < 0:  # output larger than 2x input cannot happen by construction
        raise NativeUnavailable("tokenizer output buffer overflow")
    return buf.raw[:n].decode("ascii")


def ptb_tokenize_batch(captions: Sequence[str]) -> List[str]:
    """Batch form of ``ptb_tokenize_str``: one C call for the whole list
    (the corpus-tokenization hot path makes one call per run instead of
    one per caption).  ASCII-only; raises like the scalar form."""
    if not captions:
        return []
    encoded = []
    for c in captions:
        if not c.isascii():
            raise ValueError("native tokenizer is ASCII-only")
        encoded.append(c.encode("ascii"))
    lib = load_tokenizer_library()
    total = sum(len(e) for e in encoded)
    cap = max(2 * total + 64 * len(encoded), 256)
    # The C ABI uses int32 offsets and an int output capacity: a >2 GiB
    # blob would otherwise overflow to negative offsets silently (np.cumsum
    # into int32 down-casts without a check).  Fail loudly instead —
    # callers (tokenize_corpus) fall back to the Python path (ADVICE r3).
    if cap > np.iinfo(np.int32).max:
        raise ValueError(
            f"native tokenizer batch too large for int32 offsets "
            f"({total} input bytes, {cap} output capacity); split the "
            "batch or use the Python tokenizer")
    offs = np.zeros(len(encoded) + 1, dtype=np.int32)
    np.cumsum([len(e) for e in encoded], out=offs[1:])
    blob = b"".join(encoded)
    out = ctypes.create_string_buffer(cap)
    out_offs = np.zeros(len(encoded) + 1, dtype=np.int32)
    n = lib.ptb_tokenize_batch(
        blob, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(encoded), out, cap,
        out_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if n < 0:
        raise NativeUnavailable("tokenizer output buffer overflow")
    raw = out.raw
    return [raw[out_offs[i]:out_offs[i + 1]].decode("ascii")
            for i in range(len(encoded))]
