"""cst_captioning_tpu — TPU-native consensus-based sequence training for video captioning.

A ground-up JAX/XLA/Flax rebuild of the capabilities of
``Tsingzao/cst_captioning`` (arXiv:1712.09532): HDF5 multimodal feature
pipeline, Flax encoder + LSTM/Transformer caption decoders, XE → weighted-XE
→ CST/REINFORCE training with CIDEr-D consensus rewards, XLA-compiled
greedy/multinomial/beam decoding, pure-Python metric stack, and
``shard_map`` data parallelism over a TPU mesh.  See SURVEY.md for the
blueprint and provenance notes.
"""

__version__ = "0.1.0"
