"""Host-side builder for the on-device CIDEr-D tables (ops/jax_ciderd.py).

Runs ONCE at trainer setup: encodes the tokenized training references to
ids, builds the corpus document-frequency hash table and the dense
per-video reference TF-IDF tables, and ships them to device memory.  After
this, the CST reward needs no host at all — ``ops.jax_ciderd.ciderd_scores``
runs inside the fused train step.

Supports the same df modes as the host scorers:
- refs-derived corpus df (default), identical to NativeCiderD /
  build_corpus_df semantics: df = number of videos whose reference set
  contains the n-gram;
- an external ``--train_cached_tokens`` pickle (df over word-tuple
  n-grams): its keys are id-encoded and installed as the table, with all
  reference n-grams inserted too (df 0 if absent) so hyp<->ref matching
  still works for n-grams outside the pickle corpus.
"""

from __future__ import annotations

import math
from typing import Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..metrics.ngrams import precook_tokens
from ..ops.jax_ciderd import MAX_N, PROBES, CorpusTable, RefTables, hash_ngrams_np


class _Encoder:
    """word -> id, extending for OOV reference words (cannot ever match a
    model-vocab hypothesis id but must still weigh norms/df) — the same
    scheme as native.NativeCiderD."""

    def __init__(self, word_to_ix: Optional[Mapping[str, int]] = None):
        self.w2i: Dict[str, int] = dict(word_to_ix or {})
        self._next = max(self.w2i.values(), default=0) + 1

    def __call__(self, w: str) -> int:
        ix = self.w2i.get(w)
        if ix is None:
            ix = self._next
            self.w2i[w] = ix
            self._next += 1
        return ix


def _cook(ids: Sequence[int]) -> Dict[Tuple[int, ...], int]:
    """Distinct n-grams (1..MAX_N) of an id sequence -> counts (the shared
    metrics.ngrams cooking loop, over ids instead of words)."""
    return precook_tokens(ids, MAX_N)


def _build_hash_table(keys_df: Dict[Tuple[int, ...], float], num_docs: float):
    """Open-addressed (key1, key2) -> df table with probe length <= PROBES.

    Returns numpy arrays (key1, key2, occupied, df, slot_of) where slot_of
    maps each n-gram tuple to its table position (the dense 'slot id' used
    for device-side matching).
    """
    n = max(len(keys_df), 1)
    size = 1 << max(8, math.ceil(math.log2(n * 2 + 1)))
    while True:
        key1 = np.zeros(size, np.uint32)
        key2 = np.zeros(size, np.uint32)
        occupied = np.zeros(size, bool)
        df = np.zeros(size, np.float32)
        slot_of: Dict[Tuple[int, ...], int] = {}
        ok = True
        for g, d in keys_df.items():
            arr = np.asarray(g, np.int64).reshape(1, -1)
            h1, h2 = hash_ngrams_np(arr, len(g))
            h1, h2 = int(h1[0]), int(h2[0])
            pos = h1 % size
            step = 1 + (h2 % (size - 1))
            placed = False
            for _ in range(PROBES):
                if not occupied[pos]:
                    key1[pos], key2[pos] = h1, h2
                    occupied[pos] = True
                    df[pos] = d
                    slot_of[g] = pos
                    placed = True
                    break
                if key1[pos] == h1 and key2[pos] == h2:
                    # genuine duplicate key (or a 64-bit collision, odds
                    # ~2^-64 per pair): merge df, reuse the slot
                    df[pos] = max(df[pos], np.float32(d))
                    slot_of[g] = pos
                    placed = True
                    break
                pos = (pos + step) % size
            if not placed:
                ok = False
                break
        if ok:
            return key1, key2, occupied, df, slot_of, float(num_docs)
        size *= 2  # probe bound exceeded: grow and rebuild


def build_device_tables(
    tokenized_refs: Mapping[str, Sequence[str]],
    word_to_ix: Optional[Mapping[str, int]] = None,
    external_df: Optional[Mapping[Tuple[str, ...], float]] = None,
    external_ref_len: Optional[float] = None,
    telemetry=None,
) -> Tuple[CorpusTable, RefTables, Dict[str, int]]:
    """-> (CorpusTable, RefTables, {video_id: row index}) as DEVICE arrays.

    Row order follows ``tokenized_refs`` iteration order; pass an ordered
    mapping in dataset order so ``Batch.video_ix`` indexes rows directly.

    ``telemetry``: a ``--trace_dir`` run records the one-time table build
    as a ``device_reward_tables`` span — it is the fused path's dominant
    startup cost at real corpus scale, and naming it keeps a slow startup
    diagnosable from the trace alone.
    """
    if telemetry is not None:
        with telemetry.span("device_reward_tables",
                            videos=len(tokenized_refs)):
            return _build_device_tables(tokenized_refs, word_to_ix,
                                        external_df, external_ref_len)
    return _build_device_tables(tokenized_refs, word_to_ix,
                                external_df, external_ref_len)


def _build_device_tables(
    tokenized_refs: Mapping[str, Sequence[str]],
    word_to_ix: Optional[Mapping[str, int]] = None,
    external_df: Optional[Mapping[Tuple[str, ...], float]] = None,
    external_ref_len: Optional[float] = None,
) -> Tuple[CorpusTable, RefTables, Dict[str, int]]:
    import jax.numpy as jnp

    enc = _Encoder(word_to_ix)
    cooked = []                       # per video: [(ngram counts, length)]
    for caps in tokenized_refs.values():
        refs = []
        for c in caps:
            ids = [enc(w) for w in c.split()]
            refs.append((_cook(ids), len(ids)))
        cooked.append(refs)

    if external_df is not None:
        if external_ref_len is None:
            raise ValueError("external df requires its ref_len (num docs)")
        keys_df: Dict[Tuple[int, ...], float] = {
            tuple(enc(w) for w in g): float(d) for g, d in external_df.items()
        }
        # reference n-grams outside the pickle corpus still need a slot
        # (df 0 -> max idf) so hyp<->ref matching keeps working
        for refs in cooked:
            for counts, _ in refs:
                for g in counts:
                    keys_df.setdefault(g, 0.0)
        num_docs = float(external_ref_len)
    else:
        keys_df = {}
        for refs in cooked:
            seen = set()
            for counts, _ in refs:
                seen.update(counts.keys())
            for g in seen:
                keys_df[g] = keys_df.get(g, 0.0) + 1.0
        num_docs = float(len(cooked))

    key1, key2, occupied, df, slot_of, num_docs = _build_hash_table(
        keys_df, num_docs)
    log_ref_len = math.log(max(num_docs, 1.0))

    V = len(cooked)
    R = max((len(r) for r in cooked), default=1)
    G = max((len(c) for refs in cooked for c, _ in refs), default=1)
    slot = np.full((V, R, G), -1, np.int32)
    count = np.zeros((V, R, G), np.float32)
    idf_a = np.zeros((V, R, G), np.float32)
    order_a = np.zeros((V, R, G), np.int32)
    norm = np.zeros((V, R, MAX_N), np.float32)
    length = np.zeros((V, R), np.float32)
    ref_mask = np.zeros((V, R), np.float32)
    for v, refs in enumerate(cooked):
        for r, (counts, rlen) in enumerate(refs):
            ref_mask[v, r] = 1.0
            length[v, r] = rlen
            norm2 = np.zeros(MAX_N)
            for g_i, (g, c) in enumerate(counts.items()):
                s = slot_of[g]
                w_idf = log_ref_len - math.log(max(df[s], 1.0))
                slot[v, r, g_i] = s
                count[v, r, g_i] = c
                idf_a[v, r, g_i] = w_idf
                order_a[v, r, g_i] = len(g)
                norm2[len(g) - 1] += (c * w_idf) ** 2
            norm[v, r] = np.sqrt(norm2)

    corpus = CorpusTable(
        key1=jnp.asarray(key1), key2=jnp.asarray(key2),
        occupied=jnp.asarray(occupied), df=jnp.asarray(df),
        log_ref_len=jnp.asarray(log_ref_len, jnp.float32),
    )
    tables = RefTables(
        slot=jnp.asarray(slot), count=jnp.asarray(count),
        idf=jnp.asarray(idf_a), order=jnp.asarray(order_a),
        norm=jnp.asarray(norm), length=jnp.asarray(length),
        ref_mask=jnp.asarray(ref_mask),
    )
    video_row = {vid: i for i, vid in enumerate(tokenized_refs.keys())}
    return corpus, tables, video_row
