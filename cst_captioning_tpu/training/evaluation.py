"""Validation / test evaluation: compiled decode -> predictions -> metrics.

The reference's ``test.py``/``validate`` path (SURVEY.md §3.3): decode every
video of a split (greedy fast path or beam search), dedupe the loader's
static-shape padding, build coco-format predictions, run ``language_eval``.
Both decoders are single compiled XLA programs (one ``lax.scan`` each).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..data.loader import CaptionLoader
from ..data.vocab import Vocab
from ..metrics.coco_eval import language_eval
from ..ops.beam import jit_beam_search
from ..ops.sampling import jit_sampler

# Flax modules hash by configuration, so this memoizes the *compiled* decode
# programs across validate() calls — without it every epoch's validation
# would rebuild the jit closure and recompile the whole decode scan.
_DECODER_CACHE: dict = {}


def _compiled_decoder(model, beam_size: int, max_len: int, length_norm: float,
                      mesh=None, decode_chunk: int = 0):
    """Compile (and memoize) the greedy/beam decoder; with ``mesh`` the
    batch is sharded over the ``data`` axis so validation/eval decode
    scales with the device count instead of idling every chip but one
    (VERDICT.md round 2 item 7 / SURVEY §6 config 5).  ``decode_chunk``
    > 0 = early-exit chunked decode (ops.sampling/ops.beam; bit-identical
    tokens, fewer executed steps once the whole batch has terminated)."""
    key = (model, beam_size, max_len, length_norm, mesh, decode_chunk)
    fn = _DECODER_CACHE.get(key)
    if fn is None:
        if beam_size > 1:
            if mesh is None:
                fn = jit_beam_search(model, beam_size, max_len, length_norm,
                                     decode_chunk=decode_chunk)
            else:
                from ..ops.beam import beam_search
                from ..parallel.dp import data_parallel_jit

                fn = data_parallel_jit(
                    lambda variables, feats: beam_search(
                        model, variables, feats, beam_size, max_len,
                        length_norm, decode_chunk=decode_chunk),
                    mesh, batch_argnums=(1,), donate_argnums=(),
                )
        else:
            if mesh is None:
                fn = jit_sampler(model, max_len, seq_per_img=1, greedy=True,
                                 decode_chunk=decode_chunk)
            else:
                from ..ops.sampling import sample_captions
                from ..parallel.dp import data_parallel_jit

                fn = data_parallel_jit(
                    lambda variables, feats, rng: sample_captions(
                        model, variables, feats, rng, max_len, greedy=True,
                        decode_chunk=decode_chunk),
                    mesh, batch_argnums=(1,), donate_argnums=(),
                )
        _DECODER_CACHE[key] = fn
    return fn


def _decode_local(
    model, params, loader: CaptionLoader, max_len: int,
    beam_size: int, length_norm: float, mesh=None, beat=None,
    decode_chunk: int = 0,
) -> Tuple[List[str], List[np.ndarray]]:
    """Decode THIS host's loader shard -> (video_ids, token rows), deduped
    of the static-shape wrap padding, in shard (dataset) order."""
    if mesh is not None and (loader.process_count > 1
                             or loader.batch_size % mesh.shape["data"] != 0):
        # Sharded decode only on single-host meshes: under multi-host each
        # process feeds a DIFFERENT local batch, and jitting that against a
        # global-mesh sharding would stitch unrelated hosts' rows into one
        # bogus global batch.  Pods decode one-device-per-host and rely on
        # gather_strided_predictions for consistency; batches that don't
        # divide the mesh also fall back to single-device decode.
        mesh = None
    variables = {"params": params}
    if beam_size > 1:
        beam = _compiled_decoder(model, beam_size, max_len, length_norm, mesh,
                                 decode_chunk)
        decode = lambda feats: beam(variables, feats)[0]
    else:
        sampler = _compiled_decoder(model, 1, max_len, length_norm, mesh,
                                    decode_chunk)
        decode = lambda feats: sampler(variables, feats,
                                       jax.random.PRNGKey(0))[0]
    seen = set()
    ids: List[str] = []
    rows: List[np.ndarray] = []
    for batch in loader.iter_eval():
        tokens = np.asarray(jax.device_get(decode(batch.feats)))
        if beat is not None:
            beat()  # each fetched batch is watchdog-visible progress
        for vid, row in zip(batch.video_ids, tokens):
            if vid in seen:
                continue
            seen.add(vid)
            ids.append(vid)
            rows.append(row)
    return ids, rows


def gather_strided_predictions(
    local_tokens: np.ndarray,
    all_video_ids: Sequence[str],
    process_index: int,
    process_count: int,
    allgather=None,
) -> Tuple[List[str], List[np.ndarray]]:
    """Reassemble the FULL split's decoded tokens from per-host shards.

    The loader strides the split deterministically (host q owns dataset
    indices ``q::process_count`` — data/loader.py), so every host can
    reconstruct which rows the others hold from the stride alone; only the
    token arrays cross hosts.  Shards are padded to a common row count so
    the all-gather has one static shape.

    This is what makes multi-host validation CONSISTENT: every process
    scores the identical full prediction set, so best-checkpoint
    bookkeeping (trainer best_step / early stop) cannot diverge across
    hosts (VERDICT.md round 2 item 4).

    ``allgather``: (maxn, L) -> (P, maxn, L); defaults to
    ``jax.experimental.multihost_utils.process_allgather`` (injectable so
    single-process tests can simulate a pod).
    """
    n_total = len(all_video_ids)
    shards = [list(range(q, n_total, process_count))
              for q in range(process_count)]
    if len(local_tokens) != len(shards[process_index]):
        raise ValueError(
            f"host {process_index} decoded {len(local_tokens)} rows, "
            f"expected {len(shards[process_index])} for its stride"
        )
    maxn = max(len(s) for s in shards)
    padded = np.zeros((maxn,) + local_tokens.shape[1:], local_tokens.dtype)
    padded[: len(local_tokens)] = local_tokens
    if allgather is None:
        from jax.experimental import multihost_utils

        allgather = multihost_utils.process_allgather
    gathered = np.asarray(allgather(padded))          # (P, maxn, L)
    ids: List[str] = []
    rows: List[np.ndarray] = []
    for q, shard in enumerate(shards):
        for j, ix in enumerate(shard):
            ids.append(all_video_ids[ix])
            rows.append(gathered[q, j])
    return ids, rows


def decode_split(
    model,
    params,
    loader: CaptionLoader,
    vocab: Vocab,
    max_len: int,
    beam_size: int = 1,
    length_norm: float = 0.0,
    allgather=None,
    mesh=None,
    beat=None,
    decode_chunk: int = 0,
) -> List[Dict[str, str]]:
    """One ordered pass over the split -> [{"image_id", "caption"}].

    beam_size == 1 uses the greedy sampler; > 1 the batched beam search.
    With ``mesh`` the decode batch shards over the ``data`` axis.  Under
    multi-host (loader.process_count > 1) each host decodes its own shard
    and the shards are all-gathered, so EVERY host returns the full
    split's predictions in the same order.  ``beat`` (optional zero-arg
    callable) is invoked after each decoded batch — the trainer threads
    its wedge-watchdog heartbeat through so a long validation is not
    mistaken for a hang.
    """
    ids, rows = _decode_local(model, params, loader, max_len,
                              beam_size, length_norm, mesh, beat=beat,
                              decode_chunk=decode_chunk)
    if loader.process_count > 1:
        ids, rows = gather_strided_predictions(
            np.stack(rows), loader.ds.video_ids,
            loader.process_index, loader.process_count, allgather,
        )
    return [{"image_id": v, "caption": vocab.decode(r)}
            for v, r in zip(ids, rows)]


def eval_split(
    model,
    params,
    loader: CaptionLoader,
    vocab: Vocab,
    max_len: int,
    refs,                                   # {vid: [caption,...]} or cocofmt path
    beam_size: int = 1,
    length_norm: float = 0.0,
    scorers: Optional[Sequence[str]] = None,
    mesh=None,
    beat=None,
    decode_chunk: int = 0,
) -> Tuple[List[Dict[str, str]], Dict[str, float]]:
    """Decode + score one split -> (predictions, metric dict)."""
    preds = decode_split(model, params, loader, vocab, max_len,
                         beam_size=beam_size, length_norm=length_norm,
                         mesh=mesh, beat=beat, decode_chunk=decode_chunk)
    if beat is not None:
        beat()  # decode done; host-side scoring gets a fresh full window
    scores = language_eval(preds, refs, scorers=scorers)
    return preds, scores
