"""Validation / test evaluation: compiled decode -> predictions -> metrics.

The reference's ``test.py``/``validate`` path (SURVEY.md §3.3): decode every
video of a split (greedy fast path or beam search), dedupe the loader's
static-shape padding, build coco-format predictions, run ``language_eval``.
Both decoders are single compiled XLA programs (one ``lax.scan`` each).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..data.loader import CaptionLoader
from ..data.vocab import Vocab
from ..metrics.coco_eval import language_eval
from ..ops.beam import jit_beam_search
from ..ops.sampling import jit_sampler

# Flax modules hash by configuration, so this memoizes the *compiled* decode
# programs across validate() calls — without it every epoch's validation
# would rebuild the jit closure and recompile the whole decode scan.
_DECODER_CACHE: dict = {}


def _compiled_decoder(model, beam_size: int, max_len: int, length_norm: float):
    key = (model, beam_size, max_len, length_norm)
    fn = _DECODER_CACHE.get(key)
    if fn is None:
        if beam_size > 1:
            fn = jit_beam_search(model, beam_size, max_len, length_norm)
        else:
            fn = jit_sampler(model, max_len, seq_per_img=1, greedy=True)
        _DECODER_CACHE[key] = fn
    return fn


def decode_split(
    model,
    params,
    loader: CaptionLoader,
    vocab: Vocab,
    max_len: int,
    beam_size: int = 1,
    length_norm: float = 0.0,
) -> List[Dict[str, str]]:
    """One ordered pass over ``loader``'s split -> [{"image_id", "caption"}].

    beam_size == 1 uses the greedy sampler; > 1 the batched beam search.
    Wrap-padding rows (loader.iter_eval keeps shapes static) are deduped by
    video id, keeping the first occurrence.
    """
    variables = {"params": params}
    if beam_size > 1:
        beam = _compiled_decoder(model, beam_size, max_len, length_norm)
        decode = lambda feats: beam(variables, feats)[0]
    else:
        sampler = _compiled_decoder(model, 1, max_len, length_norm)
        decode = lambda feats: sampler(variables, feats,
                                       jax.random.PRNGKey(0))[0]

    seen = set()
    preds: List[Dict[str, str]] = []
    for batch in loader.iter_eval():
        tokens = np.asarray(jax.device_get(decode(batch.feats)))
        for vid, row in zip(batch.video_ids, tokens):
            if vid in seen:
                continue
            seen.add(vid)
            preds.append({"image_id": vid, "caption": vocab.decode(row)})
    return preds


def eval_split(
    model,
    params,
    loader: CaptionLoader,
    vocab: Vocab,
    max_len: int,
    refs,                                   # {vid: [caption,...]} or cocofmt path
    beam_size: int = 1,
    length_norm: float = 0.0,
    scorers: Optional[Sequence[str]] = None,
) -> Tuple[List[Dict[str, str]], Dict[str, float]]:
    """Decode + score one split -> (predictions, metric dict)."""
    preds = decode_split(model, params, loader, vocab, max_len,
                         beam_size=beam_size, length_norm=length_norm)
    scores = language_eval(preds, refs, scorers=scorers)
    return preds, scores
