"""Overlapped CST reward pipeline — ONE implementation, shared.

The CST iteration is two device programs with a host gap: rollout ->
host CIDEr-D advantage -> grad step (SURVEY.md §3.2).  Run serially, the
device idles through the host work plus (on remote-TPU tunnels) a full
round trip per transfer.  ``RewardPipeline`` keeps up to ``depth`` rollouts
in flight: the reward of step t is computed while the device already runs
rollouts t+1..t+depth, so steady-state step time is the device time alone.

Semantics: depth 0 reproduces the reference's strictly serial loop; depth
k >= 1 grades each sample under params up to k updates newer than the ones
that drew it (stale-sample REINFORCE; decision + measurements in
PARITY.md).  ``drain()`` flushes the queue so checkpoints/validation always
see fully-updated params.

Both ``training.trainer.Trainer`` and the root ``bench.py`` drive THIS
class, so the benchmark cannot drift from the shipped trainer semantics
(VERDICT.md round 2, next-round item 1).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import jax
import numpy as np


class RewardPipeline:
    """k-deep rollout -> host advantage -> grad-step pipeline.

    Args:
      rollout_fn: ``(params, feats, rng) -> (sampled, fetch)`` device
        program (``steps.make_rollout_fused``): ``sampled`` stays on device
        for the grad step, ``fetch`` is the single host-bound array —
        ``concat([sampled, greedy])`` rows under the greedy baseline, just
        the sampled rows otherwise.
      rl_step_fn: ``(state, feats, sampled, advantage, rng) ->
        (state, metrics)`` device program (``steps.make_rl_grad_step``).
      advantage_fn: host callback ``(ctx, sampled_rows, greedy_rows|None)
        -> (advantage (N,), stats dict)`` — the RewardComputer call; ``ctx``
        is whatever per-batch payload it needs (video ids).
      depth: rollouts kept in flight (``--overlap_rewards``); 0 = serial.
        Every in-flight fetch's device->host copy starts asynchronously at
        dispatch (``copy_to_host_async`` in ``push``), so depth >= 2 keeps
        the copies double-buffered: by the time step t completes, its
        fetch has been streaming while rollouts t+1..t+depth ran, and the
        blocking ``fetch_wait`` shrinks toward zero.
      telemetry: optional ``telemetry.Telemetry`` — the fetch that blocks
        on the device rollout gets a ``fetch_wait`` phase+span (surfacing
        as a ``fetch_wait_ms`` step gauge under ``--step_timing``, next to
        ``data_wait_ms``/``score_ms``; the reward compute itself is the
        ``score`` phase inside the RewardComputer), making where the
        overlap lands visible without a trace.  None = one is-None check.
    """

    def __init__(
        self,
        rollout_fn: Callable,
        rl_step_fn: Callable,
        advantage_fn: Callable,
        depth: int,
        telemetry=None,
    ):
        self.rollout_fn = rollout_fn
        self.rl_step_fn = rl_step_fn
        self.advantage_fn = advantage_fn
        self.depth = max(0, int(depth))
        self._telemetry = telemetry
        self._pending: List[Tuple] = []

    def __len__(self) -> int:
        return len(self._pending)

    def push(self, state, feats, roll_rng, step_rng, ctx: Any
             ) -> Tuple[Any, List[Tuple[Any, Dict[str, float]]]]:
        """Dispatch one rollout; complete the oldest step once more than
        ``depth`` are in flight.  Returns the (possibly updated) state and
        the list of steps completed by this call as ``(ctx, metrics)``
        pairs — empty while the pipeline fills, one entry at steady state.
        Callers attribute metrics to the completing step's own ctx (e.g.
        its step index) so logs stay honest under the pipeline lag."""
        sampled, fetch = self.rollout_fn(state.params, feats, roll_rng)
        try:  # start the device->host copy early; np.asarray later reaps it
            fetch.copy_to_host_async()
        except AttributeError:  # backend without async host copies
            pass
        self._pending.append((sampled, fetch, feats, step_rng, ctx))
        if len(self._pending) > self.depth:
            state, done = self._complete_one(state)
            return state, [done]
        return state, []

    def _complete_one(self, state) -> Tuple[Any, Tuple[Any, Dict[str, float]]]:
        sampled, fetch, feats, step_rng, ctx = self._pending.pop(0)
        inflight = len(self._pending)  # rollouts still covering this wait
        tel = self._telemetry
        # TraceAnnotations make the host gap legible in a --profile_dir
        # trace: fetch-wait (device + transfer latency) vs reward compute.
        with jax.profiler.TraceAnnotation("cst/fetch_wait"):
            if tel is None:
                fetched = np.asarray(jax.device_get(fetch))
            else:
                # phase, not bare span: surfaces as fetch_wait_ms in the
                # --step_timing gauges so the overlap's residual blocking
                # is measurable without loading a trace.
                with tel.phase("fetch_wait"):
                    fetched = np.asarray(jax.device_get(fetch))
        n = sampled.shape[0]
        greedy_rows = fetched[n:] if fetched.shape[0] > n else None
        with jax.profiler.TraceAnnotation("cst/host_reward"):
            advantage, stats = self.advantage_fn(ctx, fetched[:n], greedy_rows)
        state, metrics = self.rl_step_fn(
            state, feats, sampled, advantage, step_rng
        )
        metrics = dict(metrics)
        metrics.update(stats)
        metrics["overlap_inflight"] = float(inflight)
        return state, (ctx, metrics)

    def drain(self, state) -> Tuple[Any, List[Tuple[Any, Dict[str, float]]]]:
        """Flush all in-flight steps (epoch boundary / checkpoint / end)."""
        completed: List[Tuple[Any, Dict[str, float]]] = []
        while self._pending:
            state, done = self._complete_one(state)
            completed.append(done)
        return state, completed

    def abort(self) -> int:
        """Discard every in-flight rollout WITHOUT completing its grad step;
        returns how many were dropped.  Used by the divergence-guard
        rollback: pending rollouts were drawn from the diverged params, and
        grading them against the restored checkpoint would apply stale,
        possibly non-finite updates to the very state the rollback just
        recovered."""
        dropped = len(self._pending)
        self._pending.clear()
        return dropped
