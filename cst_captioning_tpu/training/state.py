"""Train state + optimizer factory.

The reference builds a torch optimizer from ``--optim`` with manual lr decay
every ``--lr_update`` epochs and grad clipping in the loop (SURVEY.md §2
"Train loop").  Here those are one optax chain: global-norm clip ->
optimizer-with-schedule; the schedule is baked into the update so the jitted
step needs no lr argument, and the current lr is recomputable host-side for
logging.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state


class TrainState(train_state.TrainState):
    """Flax TrainState; dropout rng derives from ``step`` via fold_in."""


def lr_schedule(
    base_lr: float,
    decay_rate: float = 1.0,
    decay_every_steps: int = 0,
) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Staircase exponential decay (reference: lr *= rate every N epochs)."""
    if decay_rate >= 1.0 or decay_every_steps <= 0:
        return optax.constant_schedule(base_lr)
    return optax.exponential_decay(
        init_value=base_lr,
        transition_steps=decay_every_steps,
        decay_rate=decay_rate,
        staircase=True,
    )


_OPTIMIZERS = {
    "adam": optax.adam,
    "adamax": optax.adamax,
    "adamw": optax.adamw,
    "rmsprop": optax.rmsprop,
    "sgd": optax.sgd,
    "adagrad": optax.adagrad,
}


def make_optimizer(
    optim: str = "adam",
    learning_rate: float = 2e-4,
    grad_clip: float = 0.0,
    decay_rate: float = 1.0,
    decay_every_steps: int = 0,
) -> Tuple[optax.GradientTransformation, Callable]:
    """-> (optax chain, lr schedule fn) for the reference's ``--optim`` set."""
    if optim not in _OPTIMIZERS:
        raise ValueError(f"unknown optimizer {optim!r}; choose {sorted(_OPTIMIZERS)}")
    sched = lr_schedule(learning_rate, decay_rate, decay_every_steps)
    parts = []
    if grad_clip and grad_clip > 0:
        parts.append(optax.clip_by_global_norm(grad_clip))
    parts.append(_OPTIMIZERS[optim](learning_rate=sched))
    return optax.chain(*parts), sched


def create_train_state(
    model,
    rng: jax.Array,
    feat_shapes: Sequence[Tuple[int, ...]],
    seq_length: int,
    seq_per_img: int,
    tx: optax.GradientTransformation,
    batch_size: int = 2,
) -> TrainState:
    """Initialize parameters with dummy batch shapes and wrap in TrainState.

    ``feat_shapes`` are per-modality (T, D) — batch dim is added here.
    """
    feats = [jnp.zeros((batch_size, t, d), jnp.float32) for t, d in feat_shapes]
    labels = jnp.zeros((batch_size * seq_per_img, seq_length), jnp.int32)
    params = model.init(rng, feats, labels, seq_per_img)["params"]
    return TrainState.create(apply_fn=model.apply, params=params, tx=tx)


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
