"""Training layer: optimizer/state, jitted steps, RL rewards, checkpoints,
validation, and the stage trainer (XE -> WXE -> CST).

TPU restatement of the reference's ``train.py`` internals (SURVEY.md §3.1,
§3.2): everything device-side is a pure jitted function compiled once; the
only host round-trip is the RL stage's string-space CIDEr-D reward,
deliberately kept *outside* jit (SURVEY.md §7 hard part (a)).
"""

from .state import create_train_state, make_optimizer
from .steps import (
    make_rl_grad_step,
    make_rollout,
    make_rollout_fused,
    make_xe_step,
)
from .rewards import RewardComputer, decode_sequences
from .checkpoint import CheckpointManager
from .evaluation import eval_split
from .trainer import Trainer

__all__ = [
    "CheckpointManager",
    "RewardComputer",
    "Trainer",
    "create_train_state",
    "decode_sequences",
    "eval_split",
    "make_optimizer",
    "make_rl_grad_step",
    "make_rollout",
    "make_rollout_fused",
    "make_xe_step",
]
