"""Checkpoint/resume with orbax — best-metric selection + stage chaining.

Reference semantics to preserve (SURVEY.md §5 "Checkpoint / resume"): save
model (+ optimizer) state each validation, track the best val score
(CIDEr by default), keep "best" retrievable so the next stage can
warm-start from it (WXE loads XE's best, CST loads WXE's best), and store
an "infos" side record (opts, step, scores) that eval re-reads so test-time
model hyperparams come from the checkpoint, not the CLI.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


class CheckpointManager:
    """Orbax-backed manager writing ``step``-numbered checkpoints.

    Layout: ``<dir>/<step>/state`` (orbax standard pytree) plus
    ``<dir>/infos.json`` holding {"best_step", "best_score", "opts", ...}.
    The infos file is tiny and host-written — the reference's infos.pkl
    equivalent, readable without orbax.
    """

    def __init__(self, directory: str, max_to_keep: int = 2, keep_best: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._infos_path = os.path.join(self.directory, "infos.json")
        self.infos: Dict[str, Any] = {"best_step": None, "best_score": None}
        if os.path.exists(self._infos_path):
            with open(self._infos_path) as f:
                self.infos = json.load(f)

        def best_fn(metrics: Dict[str, float]) -> float:
            return metrics.get("score", float("-inf"))

        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=best_fn if keep_best else None,
                best_mode="max",
                create=True,
            ),
        )
        # Periodic failure-recovery saves live in their own manager: with
        # best_fn set, orbax exempts metric-less checkpoints from trimming,
        # so mixing them into the main manager would grow disk unboundedly.
        self._recovery: Optional[ocp.CheckpointManager] = None

    def _recovery_mgr(self) -> ocp.CheckpointManager:
        if self._recovery is None:
            self._recovery = ocp.CheckpointManager(
                os.path.join(self.directory, "recovery"),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=1, create=True,
                ),
            )
        return self._recovery

    # -- save --------------------------------------------------------------

    def save(self, step: int, state, score: Optional[float] = None,
             extra: Optional[Dict[str, Any]] = None) -> None:
        """Save state; update best bookkeeping when ``score`` improves.

        Scored saves go to the best_fn-managed main manager.  Score-less
        saves (stage without a val split) go to the recovery manager —
        orbax exempts metric-less checkpoints from best_fn trimming, so
        keeping them in the main manager would grow disk one full
        TrainState per epoch regardless of max_to_keep.
        """
        if score is None:
            mgr, metrics = self._recovery_mgr(), None
        else:
            mgr, metrics = self._mgr, {"score": float(score)}
        # ``params`` is saved as its own entry so the next stage can
        # warm-start weights without matching this stage's optimizer
        # structure (XE -> WXE -> CST chaining, SURVEY.md §5).
        mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                params=ocp.args.StandardSave(state.params),
            ),
            metrics=metrics,
        )
        mgr.wait_until_finished()
        if score is not None and (
            self.infos["best_score"] is None or score > self.infos["best_score"]
        ):
            self.infos["best_score"] = float(score)
            self.infos["best_step"] = int(step)
        if score is not None:
            # Per-step score record, pruned to the steps orbax actually
            # retained: best_fn trimming keeps the top-k by SCORE with ties
            # broken arbitrarily, so the strict-> best_step above can be
            # trimmed when scores tie (plateau) — restore(best=True) then
            # falls back to the best RETAINED step via this record.
            kept = set(self._mgr.all_steps())
            scores = {s: v for s, v in
                      self.infos.get("step_scores", {}).items()
                      if int(s) in kept}
            scores[str(int(step))] = float(score)
            self.infos["step_scores"] = scores
        if extra:
            self.infos.update(extra)
        self.infos["last_step"] = int(step)
        # Atomic replace: the wedge-recovery paths (watchdog os._exit,
        # harness SIGKILL) can land mid-write, and a truncated infos.json
        # would turn the NEXT resume into a json.load crash — the recovery
        # mechanism bricking the run it exists to save.
        tmp = self._infos_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(self.infos, f, indent=2, default=str)
                # fsync before rename: a host crash can journal the rename
                # without the data, leaving an EMPTY infos.json — worse
                # than the stale one the rename replaced.
                f.flush()
                os.fsync(f.fileno())
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.replace(tmp, self._infos_path)

    def save_recovery(self, step: int, state) -> None:
        """Periodic crash-recovery save (``--save_every_steps``): keeps only
        the most recent one, never affects best-score bookkeeping."""
        mgr = self._recovery_mgr()
        mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                params=ocp.args.StandardSave(state.params),
            ),
        )
        mgr.wait_until_finished()

    # -- restore -----------------------------------------------------------

    def _recovery_latest(self) -> Optional[int]:
        rec_dir = os.path.join(self.directory, "recovery")
        if self._recovery is None and not os.path.isdir(rec_dir):
            return None
        return self._recovery_mgr().latest_step()

    @property
    def latest_step(self) -> Optional[int]:
        cands = [s for s in (self._mgr.latest_step(), self._recovery_latest())
                 if s is not None]
        return max(cands, default=None)

    @property
    def best_step(self) -> Optional[int]:
        s = self.infos.get("best_step")
        return int(s) if s is not None else None

    def _available_steps(self) -> set:
        steps = set(self._mgr.all_steps())
        rec_dir = os.path.join(self.directory, "recovery")
        if self._recovery is not None or os.path.isdir(rec_dir):
            steps |= set(self._recovery_mgr().all_steps())
        return steps

    def _resolve_step(self, step: Optional[int], best: bool) -> int:
        if step is None:
            # A stage trained without a val split never records scores, so
            # best_step stays None — fall back to the latest checkpoint
            # rather than failing stage chaining / eval.
            step = (self.best_step if best and self.best_step is not None
                    else self.latest_step)
            avail = (self._available_steps()
                     if best and step is not None else ())
            if best and step is not None and step not in avail:
                # The recorded best step's DATA was trimmed: orbax keeps
                # the top-k by score with ties broken arbitrarily, while
                # best_step records the FIRST of tied scores (strict >).
                # Equal score == equal quality — restore the best step
                # that was retained (smallest step among the top scores).
                scores = {int(s): v for s, v in
                          self.infos.get("step_scores", {}).items()
                          if int(s) in avail}
                if scores:
                    trimmed = step
                    step = min(scores, key=lambda s: (-scores[s], s))
                    log.warning(
                        "best step %d was trimmed by checkpoint retention; "
                        "restoring best retained step %d (score %s)",
                        trimmed, step, scores[step])
                else:
                    step = self.latest_step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        return step

    def _mgr_for(self, step: int) -> ocp.CheckpointManager:
        if step in self._mgr.all_steps():
            return self._mgr
        return self._recovery_mgr()

    def restore(self, abstract_state, step: Optional[int] = None,
                best: bool = False):
        """Restore a full train state into the structure of
        ``abstract_state``.  ``best=True`` loads the best-score step."""
        step = self._resolve_step(step, best)
        target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                        abstract_state)
        out = self._mgr_for(step).restore(
            step,
            args=ocp.args.Composite(state=ocp.args.StandardRestore(target)),
        )
        return out["state"]

    def restore_params(self, abstract_params, step: Optional[int] = None,
                       best: bool = True):
        """Restore parameters only (stage warm-start path)."""
        step = self._resolve_step(step, best)
        target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                        abstract_params)
        out = self._mgr_for(step).restore(
            step,
            args=ocp.args.Composite(params=ocp.args.StandardRestore(target)),
        )
        return out["params"]

    def close(self) -> None:
        self._mgr.close()
        if self._recovery is not None:
            self._recovery.close()
