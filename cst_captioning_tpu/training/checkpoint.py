"""Checkpoint/resume with orbax — best-metric selection + stage chaining.

Reference semantics to preserve (SURVEY.md §5 "Checkpoint / resume"): save
model (+ optimizer) state each validation, track the best val score
(CIDEr by default), keep "best" retrievable so the next stage can
warm-start from it (WXE loads XE's best, CST loads WXE's best), and store
an "infos" side record (opts, step, scores) that eval re-reads so test-time
model hyperparams come from the checkpoint, not the CLI.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Any, Dict, Optional, Set, Tuple

import jax
import orbax.checkpoint as ocp

from ..resilience import integrity
from ..resilience.faults import FaultPlan
from ..telemetry.spans import NULL_SPAN

log = logging.getLogger(__name__)

#: Integrity-layer audit counters, declared at 0 when telemetry attaches
#: so snapshots distinguish "armed, nothing happened" from "absent"
#: (cstlint:declared-counters).
COUNTERS = ("checkpoints_saved", "checkpoints_quarantined",
            "checkpoint_walkbacks")


class CheckpointManager:
    """Orbax-backed manager writing ``step``-numbered checkpoints.

    Layout: ``<dir>/<step>/state`` (orbax standard pytree) plus
    ``<dir>/infos.json`` holding {"best_step", "best_score", "opts", ...}.
    The infos file is tiny and host-written — the reference's infos.pkl
    equivalent, readable without orbax.

    Integrity layer (resilience/integrity.py): every committed step gets a
    ``manifest.json`` of content checksums written AFTER the orbax commit;
    restore verifies the manifest and walks back to the newest non-corrupt
    step when the latest one is torn, so auto-resume never loads a
    half-written state.  ``fault_plan`` arms the ``ckpt_torn`` chaos hook
    (tear a payload file right after the manifest lands) — None in
    production, zero overhead.
    """

    #: Torn step dirs are renamed aside with this suffix at startup.
    QUARANTINE_SUFFIX = ".corrupt-quarantine"

    def __init__(self, directory: str, max_to_keep: int = 2,
                 keep_best: bool = True,
                 fault_plan: Optional[FaultPlan] = None,
                 readonly: bool = False,
                 telemetry=None):
        """``readonly=True`` is for consumers that only restore (eval,
        stage warm-start): it skips the destructive quarantine scan and
        infos scrub, so a reader can never rename a step out from under
        the trainer that owns the directory (e.g. during the owner's
        post-commit manifest-hash window, when marker-without-manifest
        legitimately exists for a moment).  Readers stay safe via
        restore's full verification + walk-back.

        ``telemetry`` (a ``telemetry.Telemetry``, optional): commit/
        verify/restore get host spans in the trace, and the integrity
        layer's outcomes count into the registry
        (``checkpoints_saved``/``checkpoints_quarantined``/
        ``checkpoint_walkbacks``) so a recovery's story is auditable in
        the exit telemetry.json.  None = one is-None check per event."""
        self.directory = os.path.abspath(directory)
        self._faults = fault_plan
        self._telemetry = telemetry
        if telemetry is not None:
            telemetry.declare(*COUNTERS)
        self._verify_cache: Dict[tuple, Tuple[str, str]] = {}
        os.makedirs(self.directory, exist_ok=True)
        # BEFORE orbax indexes anything: a step torn by a crash mid-save
        # must be moved out of orbax's sight entirely.  Letting native
        # code (tensorstore) parse a truncated ocdbt database is how a
        # recovery run dies of heap corruption instead of resuming —
        # observed in this environment as malloc "largebin corrupted"
        # aborts on the resume-after-torn path.
        self._quarantined: list = []
        if not readonly:
            self._quarantine_torn_steps()
        self._infos_path = os.path.join(self.directory, "infos.json")
        self.infos: Dict[str, Any] = {"best_step": None, "best_score": None}
        if os.path.exists(self._infos_path):
            with open(self._infos_path) as f:
                self.infos = json.load(f)
        if self._quarantined:
            self._scrub_infos_after_quarantine()

        def best_fn(metrics: Dict[str, float]) -> float:
            return metrics.get("score", float("-inf"))

        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=best_fn if keep_best else None,
                best_mode="max",
                create=True,
            ),
        )
        # Periodic failure-recovery saves live in their own manager: with
        # best_fn set, orbax exempts metric-less checkpoints from trimming,
        # so mixing them into the main manager would grow disk unboundedly.
        self._recovery: Optional[ocp.CheckpointManager] = None

    def _recovery_mgr(self) -> ocp.CheckpointManager:
        if self._recovery is None:
            self._recovery = ocp.CheckpointManager(
                os.path.join(self.directory, "recovery"),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=1, create=True,
                ),
            )
        return self._recovery

    # -- telemetry hooks (one is-None check each when disarmed) ------------

    def _span(self, name: str, **args):
        tel = self._telemetry
        if tel is None or tel.tracer is None:
            return NULL_SPAN
        return tel.tracer.span(name, **args)

    def _inc(self, name: str) -> None:
        if self._telemetry is not None:
            self._telemetry.inc(name)

    # -- save --------------------------------------------------------------

    def save(self, step: int, state, score: Optional[float] = None,
             extra: Optional[Dict[str, Any]] = None) -> None:
        """Save state; update best bookkeeping when ``score`` improves.

        Scored saves go to the best_fn-managed main manager.  Score-less
        saves (stage without a val split) go to the recovery manager —
        orbax exempts metric-less checkpoints from best_fn trimming, so
        keeping them in the main manager would grow disk one full
        TrainState per epoch regardless of max_to_keep.
        """
        if score is None:
            mgr, metrics = self._recovery_mgr(), None
        else:
            mgr, metrics = self._mgr, {"score": float(score)}
        self._clear_existing(mgr, step)
        # ``params`` is saved as its own entry so the next stage can
        # warm-start weights without matching this stage's optimizer
        # structure (XE -> WXE -> CST chaining, SURVEY.md §5).
        with self._span("ckpt_commit", step=int(step)):
            mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    params=ocp.args.StandardSave(state.params),
                ),
                metrics=metrics,
            )
            mgr.wait_until_finished()
            self._seal_step(step, recovery=score is None)
        self._inc("checkpoints_saved")
        if score is not None and (
            self.infos["best_score"] is None or score > self.infos["best_score"]
        ):
            self.infos["best_score"] = float(score)
            self.infos["best_step"] = int(step)
        if score is not None:
            # Per-step score record, pruned to the steps orbax actually
            # retained: best_fn trimming keeps the top-k by SCORE with ties
            # broken arbitrarily, so the strict-> best_step above can be
            # trimmed when scores tie (plateau) — restore(best=True) then
            # falls back to the best RETAINED step via this record.
            kept = set(self._mgr.all_steps())
            scores = {s: v for s, v in
                      self.infos.get("step_scores", {}).items()
                      if int(s) in kept}
            scores[str(int(step))] = float(score)
            self.infos["step_scores"] = scores
        if extra:
            self.infos.update(extra)
        self.infos["last_step"] = int(step)
        self._write_infos()

    def _write_infos(self) -> None:
        # Atomic replace: the wedge-recovery paths (watchdog os._exit,
        # harness SIGKILL) can land mid-write, and a truncated infos.json
        # would turn the NEXT resume into a json.load crash — the recovery
        # mechanism bricking the run it exists to save.
        integrity.atomic_json_write(self._infos_path, self.infos,
                                    indent=2, default=str)

    def _scrub_infos_after_quarantine(self) -> None:
        """A quarantined step's bookkeeping must go with it: leaving its
        best_step/step_scores entries behind would let a REPLAYED (new,
        different) state at the same step number inherit the torn
        checkpoint's recorded score — e.g. restore(best=True) serving a
        worse state under the old best's score.  Only MAIN-dir
        quarantines count: scores belong to scored (main-manager) saves,
        and a torn recovery twin of the same step number must not demote
        an intact scored best."""
        gone = {step for step, is_recovery in self._quarantined
                if not is_recovery}
        if not gone:
            return
        scores = {int(s): float(v)
                  for s, v in self.infos.get("step_scores", {}).items()
                  if int(s) not in gone}
        if "step_scores" in self.infos:
            self.infos["step_scores"] = {str(s): v
                                         for s, v in scores.items()}
        best = self.infos.get("best_step")
        if best is not None and int(best) in gone:
            new_best = self._best_retained(scores)
            self.infos["best_step"] = new_best
            self.infos["best_score"] = (None if new_best is None
                                        else scores[new_best])
            log.warning(
                "best checkpoint (step %d) was quarantined as torn; best "
                "bookkeeping now %s", int(best), new_best)
        self._write_infos()

    @staticmethod
    def _best_retained(scores: Dict[int, float]) -> Optional[int]:
        """Best step among retained scored steps: highest score, ties to
        the smallest step — the ONE definition shared by restore's
        trimmed-best fallback and the quarantine scrub."""
        if not scores:
            return None
        return min(scores, key=lambda s: (-scores[s], s))

    @staticmethod
    def _clear_existing(mgr: ocp.CheckpointManager, step: int) -> None:
        """A step being re-saved can already exist on disk: a divergence
        rollback (or a resume that walked back past a torn newest step)
        replays steps whose directories survive from the first pass.
        Orbax refuses to save over them — delete first, loudly."""
        if step in mgr.all_steps():
            log.warning("overwriting existing checkpoint step %d "
                        "(replay after rollback/walk-back)", step)
            try:
                mgr.delete(step)
            except Exception as e:  # directory may be half-torn
                log.warning("could not delete stale step %d cleanly: %s",
                            step, e)

    def save_recovery(self, step: int, state, verify: bool = False) -> None:
        """Periodic crash-recovery save (``--save_every_steps`` /
        ``--save_interval_secs``): keeps only the most recent one, never
        affects best-score bookkeeping.

        ``verify=True`` (the preemption boundary) re-reads the just-sealed
        step through the integrity layer and RAISES if it does not verify:
        a preempting trainer is about to exit with "resumable — checkpoint
        advanced" semantics, and that claim must be proven before the
        process stakes its exit code on it (an unverifiable save exits as
        a plain failure instead, and resume falls back to the previous
        verified step)."""
        mgr = self._recovery_mgr()
        self._clear_existing(mgr, step)
        with self._span("ckpt_commit", step=int(step), recovery=True):
            mgr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    params=ocp.args.StandardSave(state.params),
                ),
            )
            mgr.wait_until_finished()
            self._seal_step(step, recovery=True)
        self._inc("checkpoints_saved")
        if verify:
            status, detail = self._verify_dir(
                self._step_dir(step, recovery=True))
            if status != "verified":
                raise RuntimeError(
                    f"recovery checkpoint step {step} failed post-save "
                    f"integrity verification ({status}: {detail}); "
                    "refusing to exit as resumable on an unproven save")

    # -- integrity ---------------------------------------------------------

    def _quarantine_torn_steps(self) -> None:
        """Rename every integrity-corrupt step dir to ``<step>.corrupt-
        quarantine`` (kept for forensics, invisible to orbax's digit-dir
        scan).  The scan runs at STAT level (marker / existence / sizes,
        no hashing) so read-only consumers (eval, warm-start) don't pay a
        full re-read of healthy multi-GB checkpoints at startup; restore
        still full-verifies the step it actually loads.  Steps without a
        manifest pass as legacy; the walk-back in ``_resolve_step`` covers
        tears that happen AFTER this manager was constructed (e.g. the
        ckpt_torn chaos hook)."""
        for base in (self.directory, os.path.join(self.directory, "recovery")):
            if not os.path.isdir(base):
                continue
            for name in sorted(os.listdir(base)):
                if not name.isdigit():
                    continue
                step_dir = os.path.join(base, name)
                if not os.path.isdir(step_dir):
                    continue
                status, detail = integrity.verify_step_dir(step_dir,
                                                           level="stat")
                if status != "corrupt":
                    continue
                dst = step_dir + self.QUARANTINE_SUFFIX
                try:
                    shutil.rmtree(dst, ignore_errors=True)
                    # durable_rename, not bare os.rename: a crash right
                    # after quarantining could journal the rename away
                    # and resurrect the torn step on the next scan.
                    integrity.durable_rename(step_dir, dst)
                except OSError as e:
                    log.warning("could not quarantine torn step %s: %s",
                                step_dir, e)
                    continue
                self._quarantined.append(
                    (int(name), base != self.directory))
                self._inc("checkpoints_quarantined")
                log.warning(
                    "quarantined torn checkpoint step %s (%s) -> %s; "
                    "resume will use the newest verified step", name,
                    detail, os.path.basename(dst))

    def _step_dir(self, step: int, recovery: Optional[bool] = None) -> str:
        """On-disk directory of a committed step.  ``recovery`` pins the
        manager (save paths KNOW which one they wrote — the same step
        number can exist in both); None resolves main-first, mirroring
        ``_mgr_for``'s restore preference."""
        rec = os.path.join(self.directory, "recovery", str(step))
        if recovery is True:
            return rec
        main = os.path.join(self.directory, str(step))
        if recovery is False or os.path.isdir(main):
            return main
        return rec

    def _seal_step(self, step: int, recovery: bool) -> None:
        """Post-commit manifest write + the ``ckpt_torn`` chaos hook, on
        the directory the saving manager actually wrote.  A manifest
        failure is logged, not raised — the checkpoint itself is committed
        and an unverified step still restores (legacy rule)."""
        step_dir = self._step_dir(step, recovery=recovery)
        try:
            integrity.write_manifest(step_dir)
        except OSError as e:
            log.warning("could not write integrity manifest for step %d: %s",
                        step, e)
            return
        if self._faults is not None and self._faults.fire("ckpt_torn", step):
            self._tear_step(step_dir)

    @staticmethod
    def _tear_step(step_dir: str) -> None:
        """Chaos: truncate the largest payload file to half its size —
        the torn-write shape a power cut produces, which the manifest
        (already written, listing the full size) must catch on restore."""
        files = [(os.path.getsize(p), p)
                 for _rel, p in integrity._iter_payload_files(step_dir)]
        if not files:
            return
        size, victim = max(files)
        with open(victim, "r+b") as f:
            f.truncate(max(0, size // 2))
        log.warning("FAULT: tore checkpoint file %s (%d -> %d bytes)",
                    victim, size, max(0, size // 2))

    def _verify_dir(self, step_dir: str) -> Tuple[str, str]:
        """``integrity.verify_step_dir`` behind a cache: resume touches
        the same steps through quarantine, latest_verified_step, and
        restore's resolution, and re-hashing a multi-GB checkpoint three
        times would triple recovery latency.  The key is the manifest
        mtime PLUS a stat signature (relpath, size, mtime) of every
        payload file — a stat walk costs microseconds against the hash's
        full read, and any truncation/rewrite (including the chaos tear
        hook, which edits payload bytes without touching the manifest)
        changes the key and forces a fresh hash.  Manifest-less dirs are
        not cached (cheap to recompute, nothing stable to key on)."""
        try:
            mkey = os.stat(integrity.manifest_path(step_dir)).st_mtime_ns
            sig = tuple(
                (rel, os.stat(path).st_size, os.stat(path).st_mtime_ns)
                for rel, path in integrity._iter_payload_files(step_dir))
        except OSError:
            return integrity.verify_step_dir(step_dir)
        key = (step_dir, mkey, sig)
        hit = self._verify_cache.get(key)
        if hit is None:
            with self._span("ckpt_verify", dir=os.path.basename(step_dir)):
                hit = integrity.verify_step_dir(step_dir)
            self._verify_cache[key] = hit
        return hit

    def verify_step(self, step: int) -> Tuple[str, str]:
        """-> (status, detail): 'verified' / 'unverified' (pre-manifest
        legacy) / 'corrupt'."""
        return self._verify_dir(self._step_dir(step))

    @property
    def latest_verified_step(self) -> Optional[int]:
        """Newest step that passes integrity verification (legacy
        manifest-less steps count as passing) — what auto-resume should
        restore.  None when no intact checkpoint exists."""
        for step in sorted(self._available_steps(), reverse=True):
            if self.verify_step(step)[0] != "corrupt":
                return step
        return None

    # -- restore -----------------------------------------------------------

    def _recovery_latest(self) -> Optional[int]:
        rec_dir = os.path.join(self.directory, "recovery")
        if self._recovery is None and not os.path.isdir(rec_dir):
            return None
        return self._recovery_mgr().latest_step()

    @property
    def latest_step(self) -> Optional[int]:
        cands = [s for s in (self._mgr.latest_step(), self._recovery_latest())
                 if s is not None]
        return max(cands, default=None)

    @property
    def best_step(self) -> Optional[int]:
        s = self.infos.get("best_step")
        return int(s) if s is not None else None

    def _available_steps(self) -> set:
        steps = set(self._mgr.all_steps())
        rec_dir = os.path.join(self.directory, "recovery")
        if self._recovery is not None or os.path.isdir(rec_dir):
            steps |= set(self._recovery_mgr().all_steps())
        return steps

    def _pick_step(self, best: bool, excluded: Set[int]) -> Optional[int]:
        """One resolution pass over the steps not yet ruled out."""
        avail = self._available_steps() - excluded
        if not avail:
            return None
        if best and self.best_step is not None:
            if self.best_step in avail:
                return self.best_step
            # The recorded best step's DATA was trimmed (orbax keeps the
            # top-k by score with ties broken arbitrarily, while best_step
            # records the FIRST of tied scores, strict >) — or it failed
            # verification.  Equal score == equal quality — restore the
            # best step that was retained (smallest step among the top
            # scores).
            scores = {int(s): float(v) for s, v in
                      self.infos.get("step_scores", {}).items()
                      if int(s) in avail}
            step = self._best_retained(scores)
            if step is not None:
                log.warning(
                    "best step %d is unavailable (trimmed by retention or "
                    "failed verification); restoring best retained step %d "
                    "(score %s)", self.best_step, step, scores[step])
                return step
        return max(avail)

    def _resolve_step(self, step: Optional[int], best: bool) -> int:
        if step is not None:
            # An EXPLICITLY requested step never silently substitutes: a
            # torn step the caller named is an error, not a walk-back.
            status, detail = self.verify_step(step)
            if status == "corrupt":
                raise ValueError(
                    f"checkpoint step {step} in {self.directory} failed "
                    f"integrity verification ({detail}); refusing to "
                    "restore a torn state")
            return step
        # Auto-resolution (latest / best): verify the candidate and walk
        # back past torn steps so the newest INTACT state is restored —
        # a stage trained without a val split never records scores, so
        # best_step stays None and we fall back to the latest checkpoint
        # rather than failing stage chaining / eval.
        excluded: Set[int] = set()
        while True:
            cand = self._pick_step(best, excluded)
            if cand is None:
                if excluded:
                    raise FileNotFoundError(
                        f"every checkpoint in {self.directory} failed "
                        f"integrity verification ({sorted(excluded)}); "
                        "no intact state to restore")
                raise FileNotFoundError(f"no checkpoint in {self.directory}")
            status, detail = self.verify_step(cand)
            if status != "corrupt":
                if status == "unverified":
                    log.info("restoring step %d without a manifest "
                             "(pre-integrity-layer checkpoint)", cand)
                if excluded:
                    log.warning(
                        "walked back past torn checkpoint step(s) %s to "
                        "verified step %d", sorted(excluded), cand)
                return cand
            log.warning("checkpoint step %d failed integrity verification "
                        "(%s); walking back", cand, detail)
            excluded.add(cand)
            self._inc("checkpoint_walkbacks")

    def _mgr_for(self, step: int) -> ocp.CheckpointManager:
        if step in self._mgr.all_steps():
            return self._mgr
        return self._recovery_mgr()

    def restore(self, abstract_state, step: Optional[int] = None,
                best: bool = False):
        """Restore a full train state into the structure of
        ``abstract_state``.  ``best=True`` loads the best-score step."""
        step = self._resolve_step(step, best)
        target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                        abstract_state)
        with self._span("ckpt_restore", step=int(step)):
            out = self._mgr_for(step).restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(target)),
            )
        return out["state"]

    def restore_params(self, abstract_params, step: Optional[int] = None,
                       best: bool = True):
        """Restore parameters only (stage warm-start path)."""
        step = self._resolve_step(step, best)
        target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                        abstract_params)
        with self._span("ckpt_restore", step=int(step), params_only=True):
            out = self._mgr_for(step).restore(
                step,
                args=ocp.args.Composite(
                    params=ocp.args.StandardRestore(target)),
            )
        return out["params"]

    def close(self) -> None:
        self._mgr.close()
        if self._recovery is not None:
            self._recovery.close()
