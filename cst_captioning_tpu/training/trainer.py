"""Stage trainer: XE / WXE / CST epochs, validation, best-CIDEr checkpoints.

TPU restatement of the reference's ``train.py`` main/train/validate
(SURVEY.md §3.1–§3.2).  One Trainer instance runs one stage; the 3-stage
recipe (XE pretrain -> WXE warm-start -> CST fine-tune) chains stages via
``--start_from`` pointing at the previous stage's checkpoint dir, exactly
like the reference Makefile does with best checkpoints.

CST iteration, two shapes (flag-selected):
  host path (default): rollout (jit, sharded) -> reward (host, C++/Py
    CIDEr-D) -> grad step (jit, sharded), with up to --overlap_rewards
    rollouts in flight while the host scores (training/pipeline.py);
  fused path (--device_rewards 1): ONE device program — rollout +
    on-device CIDEr-D (ops/jax_ciderd.py) + REINFORCE grad — no host
    boundary, strict on-policy.
Either way the next batch's h5 reads + HBM transfer are overlapped by the
loader's prefetch thread.
"""

from __future__ import annotations

import atexit
import logging
import os
import signal
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..data.dataset import CaptionDataset, SplitPaths
from ..data.loader import CaptionLoader, prefetch_to_device
from ..data.sharding import resolve_shard_spec
from ..metrics.ciderd import (
    CiderD,
    build_corpus_df,
    load_corpus_df,
    save_corpus_df,
)
from ..metrics.coco_eval import score_key
from ..metrics.consensus import load_consensus, normalize_weights
from ..metrics.tokenizer import tokenize_corpus
from ..models.captioner import CaptionModel
from ..opts import (
    DEFAULT_OVERLAP_REWARDS,
    DEFAULT_REMAT_CELL,
    DEFAULT_SCAN_UNROLL,
)
from ..parallel.dp import data_parallel_jit
from ..parallel.mesh import batch_sharding, make_mesh
from ..resilience.faults import FaultPlan
from ..resilience.guard import DivergenceGuard
from ..resilience.preemption import PreemptedExit, PreemptionHandler
from ..telemetry import (
    JsonlSink,
    ScalarWriterSink,
    Telemetry,
    caption_step_flops,
    mfu_fields,
)
from ..utils.watchdog import ProgressWatchdog
from .checkpoint import CheckpointManager
from .evaluation import eval_split
from .pipeline import RewardPipeline
from .rewards import RewardComputer
from .state import create_train_state, make_optimizer, param_count
from .steps import make_rl_grad_step, make_rollout_fused, make_xe_step

log = logging.getLogger("cst_captioning_tpu.train")


class NegativeAdvantageAbort(RuntimeError):
    """Raised (opt-in: --abort_on_negative_advantage_window) when every
    logged advantage in the detector's rolling window is negative — the
    baseline dominates the samples, REINFORCE is only suppressing typical
    sequences, and an unattended chain should stop instead of burning its
    chip window on a collapsing stage.  train.py maps it to exit 4."""


def build_model(opt, vocab_size: int, seq_length: int) -> CaptionModel:
    """CaptionModel from the opts namespace (reference --model_type etc.)."""
    import jax.numpy as jnp

    return CaptionModel(
        vocab_size=vocab_size,
        embed_size=opt.input_encoding_size,
        hidden_size=opt.rnn_size,
        num_layers=opt.num_layers,
        attn_size=opt.att_size,
        use_attention=bool(opt.use_attention),
        dropout_rate=opt.drop_prob,
        decoder_type=opt.model_type,
        num_heads=opt.num_heads,
        num_tx_layers=opt.num_tx_layers,
        tx_max_len=max(seq_length + 1, opt.max_length + 1),
        dtype=jnp.bfloat16 if opt.use_bfloat16 else jnp.float32,
        use_pallas_attention=bool(getattr(opt, "pallas_attention", 0)),
        decode_kernel=getattr(opt, "decode_kernel", "reference"),
        fusion_type={"manet": "modality"}.get(
            getattr(opt, "fusion_type", "temporal"), "temporal"),
        scan_unroll=getattr(opt, "scan_unroll", DEFAULT_SCAN_UNROLL),
        remat_cell=bool(getattr(opt, "remat_cell", DEFAULT_REMAT_CELL)),
    )


def upload_table_chunked(read_fn, n: int, shapes, dtype, sharding,
                         upload_mb: float = 64.0, beat=None):
    """Build per-modality device-resident tables ``[(n, t, d), ...]`` by
    reading and uploading bounded row chunks.

    ``read_fn(ix)`` returns one host array per modality for the given row
    indices (``CaptionDataset.features``).  Each chunk is ``device_put``
    separately and written into a donated device buffer with
    ``lax.dynamic_update_slice`` — peak HBM is table + one chunk (never
    2x table, as a device-side concatenate would transiently cost), peak
    host memory is one chunk per modality, and no single transfer exceeds
    ``upload_mb`` (huge monolithic transfers wedged a remote TPU tunnel
    whose streaming path is reliable).  Per-chunk completion barriers keep
    at most one chunk in flight so progress is observable and bounded.
    """
    import functools

    from jax import lax

    jdtype = (jax.numpy.float32 if dtype is None
              else jax.numpy.dtype(dtype))
    row_bytes = [t * d * np.dtype(dtype or np.float32).itemsize
                 for t, d in shapes]
    chunk_rows = max(1, int(upload_mb * 1e6 // max(row_bytes)))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _write(buf, chunk, start):
        return lax.dynamic_update_slice(
            buf, chunk, (start,) + (0,) * (buf.ndim - 1))

    def _zeros(t, d):
        return jax.jit(
            lambda: jax.numpy.zeros((n, t, d), jdtype),
            out_shardings=sharding)()

    tables = [_zeros(t, d) for t, d in shapes]
    n_chunks = -(-n // chunk_rows)
    for i, start in enumerate(range(0, n, chunk_rows)):
        ix = np.arange(start, min(start + chunk_rows, n))
        for m, arr in enumerate(read_fn(ix)):
            if dtype is not None:
                # cstlint: disable=device-scalar-fetch -- read_fn returns host h5/numpy rows; this is a host-side dtype cast BEFORE device_put, not a device fetch.
                arr = np.asarray(arr, dtype=dtype)
            chunk = jax.device_put(arr, sharding)
            tables[m] = _write(tables[m], chunk, np.int32(start))
        # cstlint: disable=device-scalar-fetch -- deliberate per-chunk barrier: bounds upload to ONE chunk in flight (docstring contract) so a wedged tunnel is watchdog-visible; startup path, not the step loop.
        jax.block_until_ready(tables)
        if beat is not None:
            beat()  # each completed chunk is watchdog-visible progress
        if n_chunks > 1 and ((i + 1) % 8 == 0 or i + 1 == n_chunks):
            log.info("device_feats upload: %d/%d chunks", i + 1, n_chunks)
    return tables


def _split_paths(opt, split: str) -> Optional[SplitPaths]:
    feat = getattr(opt, f"{split}_feat_h5", None)
    label = getattr(opt, f"{split}_label_h5", None)
    info = getattr(opt, f"{split}_info_json", None)
    if not feat or not label or not info:
        return None
    return SplitPaths(
        feat_h5=list(feat),
        label_h5=label,
        info_json=info,
        cocofmt_json=getattr(opt, f"{split}_cocofmt_file", None),
    )


class Trainer:
    """One training stage (XE, WXE, or CST) over a device mesh."""

    # "METEOR" stays accepted for reference CLI compatibility but selects
    # (and is emitted as) METEOR_approx — see metrics/coco_eval.score_key.
    KNOWN_EVAL_METRICS = ("CIDEr", "CIDEr-plain", "METEOR", "METEOR_approx",
                          "ROUGE_L", "Bleu_1", "Bleu_2", "Bleu_3", "Bleu_4")

    def __init__(self, opt, preemption: Optional[PreemptionHandler] = None):
        self.opt = opt
        # Preemption layer (resilience/preemption.py): train.py installs
        # the handler BEFORE this slow constructor and passes it in, so a
        # SIGTERM landing during device bring-up / table upload is already
        # caught; an embedded caller that passes None gets a Trainer-owned
        # handler installed here (and uninstalled by close()).
        self._preempt = preemption
        self._preempt_owned = preemption is None
        if self._preempt_owned:
            self._preempt = PreemptionHandler().install()
        # Armed before ANY backend-touching op (even PRNGKey initializes
        # the device client, and a wedged transport blocks there): a train
        # stage launched into an already-dead tunnel must still die with
        # 124 for the harness to resume, not hang unprotected.
        # describe() must only read HOST state — fetching e.g.
        # self.state.step would block on the very transport whose death it
        # is reporting, and the exit would never happen.
        self._progress_step = -1  # host-side mirror, updated by the loop
        self._watchdog = ProgressWatchdog(
            getattr(opt, "wedge_timeout", 0.0) or 0.0,
            describe=lambda: ("last loop step %d; checkpoints in %s"
                              % (self._progress_step, opt.checkpoint_path)),
            # Liveness file an external harness can read without attaching:
            # last beat gap + the telemetry registry's last-step record and
            # resilience counters.  payload reads HOST state only (same
            # contract as describe — see ProgressWatchdog docstring).
            heartbeat_path=os.path.join(
                os.path.abspath(opt.checkpoint_path), "heartbeat.json"),
            payload=self._heartbeat_payload,
        ).start()
        try:
            self._init(opt)
        except BaseException:
            # A failed constructor must not leave the armed watchdog
            # ticking toward os._exit in a process that chose to continue
            # (e.g. a REPL catching the ValueError below) — nor an owned
            # signal handler pointing at a dead Trainer.
            if self._preempt_owned:
                self._preempt.uninstall()
            self._watchdog.stop()
            raise

    def _heartbeat_payload(self) -> Dict[str, Any]:
        """Watchdog-thread heartbeat enrichment — host memory only."""
        payload: Dict[str, Any] = {"loop_step": self._progress_step}
        tel = getattr(self, "_telemetry", None)  # watchdog arms before _init
        if tel is not None:
            payload.update(tel.registry.heartbeat_payload())
        return payload

    def _init(self, opt):
        # Telemetry bundle (telemetry/__init__.py): the metrics registry
        # always exists (counters are how rare resilience events become
        # auditable); the span tracer / step-phase timer stay None unless
        # --trace_dir / --step_timing arm them, and every hot-path hook
        # then costs one is-None check — the --fault_plan pattern.  Sinks
        # (metrics.jsonl, TB) attach at the end of _init, once the process
        # knows it is the pod's metrics writer.
        self._telemetry = Telemetry.from_opts(opt)
        # Preemption counters are declared at 0 up front so every
        # heartbeat/exit snapshot carries them: a reader can tell "armed,
        # nothing happened" from "feature absent" (registry.declare).
        self._telemetry.registry.declare("preempt_signals", "preempt_saves",
                                         "negative_advantage_aborts")
        # Tuned-config provenance (opts.apply_tuned_defaults) rides into
        # the telemetry.json exit snapshot: every run answers "which axes
        # came from which tuning record" without consulting the CLI line
        # that launched it (PARITY.md "Tuned configs").
        self._telemetry.registry.set_meta(
            "tuned_config",
            getattr(opt, "tuned_provenance", None) or {"tuned": False})
        if opt.eval_metric not in self.KNOWN_EVAL_METRICS:
            # Fail at startup, not after the first epoch's validation
            # silently scores 0.0 forever.
            raise ValueError(
                f"--eval_metric {opt.eval_metric!r} is not one of "
                f"{self.KNOWN_EVAL_METRICS}"
            )
        # Chaos fault plan (resilience/faults.py): parsed ONCE here and
        # threaded explicitly into every component that hosts an injection
        # point (loader, checkpoint manager, this loop) — no module-global
        # arming, so parallel Trainers can never leak faults into each
        # other.  None (the production case) costs one is-None check per
        # hook, all host-side, nothing inside jit.
        self._faults = FaultPlan.parse(
            getattr(opt, "fault_plan", None)
            or os.environ.get("CST_FAULT_PLAN"))
        if self._faults is not None:
            # Firings count into the registry so the drill's telemetry.json
            # carries fault_firings / fault_<kind> for the audit.
            self._faults.bind_metrics(self._telemetry.registry)
            # Persist firings next to the checkpoints: process-killing
            # faults (wedge) stay single-shot across the resume attempts a
            # recovery harness (scale_chain) spawns for this stage dir.
            os.makedirs(opt.checkpoint_path, exist_ok=True)
            self._faults.bind_state(os.path.join(
                os.path.abspath(opt.checkpoint_path),
                "fault_plan_state.jsonl"))
            log.warning("FAULT INJECTION ARMED: %s — this run will break "
                        "itself on purpose (chaos testing)", self._faults)
        # Divergence guard: device-side finite-check + skip is folded into
        # the compiled steps (steps._apply_gradients_guarded); this is the
        # host half that counts consecutive bad steps and rolls back.
        # Mutually exclusive with --debug_nans, which CRASHES on the first
        # NaN and therefore preempts skip-and-rollback entirely.
        guard_on = bool(getattr(opt, "divergence_guard", 1))
        if getattr(opt, "debug_nans", 0):
            jax.config.update("jax_debug_nans", True)
            if guard_on:
                log.warning(
                    "--debug_nans and --divergence_guard are mutually "
                    "exclusive: jax_debug_nans raises on the FIRST "
                    "non-finite value, so the guard's skip-and-rollback "
                    "can never run.  Disabling the divergence guard for "
                    "this run (pass --divergence_guard 0 to silence).")
                guard_on = False
        self._guard = DivergenceGuard(
            max_bad=getattr(opt, "divergence_max_bad", 3),
            max_rollbacks=getattr(opt, "divergence_max_rollbacks", 2),
            metrics=self._telemetry.registry,
        ) if guard_on else None
        self._rng_salt = 0  # bumped per rollback: re-seeds the rollout keys
        self.rng = jax.random.PRNGKey(opt.seed)

        # -- data ----------------------------------------------------------
        train_paths = _split_paths(opt, "train")
        if train_paths is None:
            raise ValueError("train split paths are required")
        preload = bool(getattr(opt, "preload_feats", 0))
        self.train_ds = CaptionDataset(train_paths, preload=preload)
        val_paths = _split_paths(opt, "val")
        self.val_ds = (CaptionDataset(val_paths, preload=preload)
                       if val_paths else None)
        self.vocab = self.train_ds.vocab

        consensus_weights = None
        self.consensus_scores = None
        if getattr(opt, "train_bcmrscores_pkl", None):
            self.consensus_scores = load_consensus(opt.train_bcmrscores_pkl)
            if opt.use_consensus_weights:
                consensus_weights = normalize_weights(
                    self.consensus_scores, temperature=opt.consensus_temperature
                )
                log.info("WXE: loaded consensus weights for %d videos",
                         len(consensus_weights))

        # Explicit shard assignment (--data_shards/--data_shard_id,
        # data/sharding.py) replaces the implicit process-strided split
        # for the TRAINING stream: the shard's identity comes from
        # config, and N shards partition each epoch's global shuffle
        # exactly.  None (the default) keeps the legacy per-process
        # split.  Val/eval loaders keep process striding either way —
        # gather_strided_predictions reconstructs shards from process
        # topology, a PUBLIC contract this plane does not touch.
        shard_spec = resolve_shard_spec(
            int(getattr(opt, "data_shards", 0) or 0),
            int(getattr(opt, "data_shard_id", 0) or 0))
        if shard_spec is not None and jax.process_count() > 1:
            # Identical argv on every host would make ALL processes
            # consume the same shard — shard 0 trained process_count
            # times, the rest never.  Refuse loudly; the multi-host
            # launch recipe is one --data_shard_id (or CST_DATA_SHARD_ID)
            # per host.
            raise ValueError(
                "--data_shards with multiple JAX processes needs a "
                "DISTINCT --data_shard_id (or CST_DATA_SHARD_ID) per "
                f"host — this launch gave every one of the "
                f"{jax.process_count()} processes shard "
                f"{shard_spec.shard_id}, which would duplicate it and "
                "drop the rest; either assign per-host shard ids or "
                "drop --data_shards for the process-strided split")
        self._telemetry.registry.set_meta("data_plane", {
            "loader_workers": int(getattr(opt, "loader_workers", 1) or 1),
            "data_shards": int(getattr(opt, "data_shards", 0) or 0),
            "data_shard_id": int(getattr(opt, "data_shard_id", 0) or 0),
        })
        self.loader = CaptionLoader(
            self.train_ds,
            batch_size=opt.batch_size,
            seq_per_img=opt.seq_per_img,
            shuffle=True,
            seed=opt.seed,
            consensus_weights=consensus_weights,
            shard_spec=shard_spec,
            process_index=0 if shard_spec is not None else jax.process_index(),
            process_count=1 if shard_spec is not None else jax.process_count(),
            # RewardComputer keeps its own tokenized reference corpus, so
            # per-batch gts assembly would be dead work even in RL.
            include_gts=False,
            # --device_feats: features live in HBM for the whole run and the
            # train steps gather them by video_ix INSIDE jit, so per-batch
            # h5 feature reads and host->device feature transfers disappear.
            include_feats=not bool(getattr(opt, "device_feats", 0)),
            fault_plan=self._faults,
        )
        self.val_loader = (
            CaptionLoader(
                self.val_ds,
                batch_size=opt.eval_batch_size or opt.batch_size,
                seq_per_img=1,
                shuffle=False,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
            if self.val_ds
            else None
        )

        # -- mesh ----------------------------------------------------------
        devices = jax.devices()
        n = opt.num_devices or len(devices)
        if opt.batch_size % n != 0:
            fit = max(d for d in range(1, n + 1) if opt.batch_size % d == 0)
            log.warning("batch_size %d not divisible by %d devices; using %d",
                        opt.batch_size, n, fit)
            n = fit
        self.mesh = make_mesh(devices[:n])
        log.info("mesh: %d device(s) on %s", n, devices[0].platform)

        # -- model / state -------------------------------------------------
        self.model = build_model(opt, self.vocab.size_with_pad,
                                 self.train_ds.seq_length)
        bpe = self.loader.batches_per_epoch
        tx, self.lr_sched = make_optimizer(
            optim=opt.optim,
            learning_rate=opt.learning_rate,
            grad_clip=opt.grad_clip,
            decay_rate=opt.learning_rate_decay_rate,
            decay_every_steps=opt.learning_rate_decay_every * bpe,
        )
        feat_shapes = list(zip(self.train_ds.feat_times, self.train_ds.feat_dims))
        init_rng, self.rng = jax.random.split(self.rng)
        self.state = create_train_state(
            self.model, init_rng, feat_shapes, self.train_ds.seq_length,
            opt.seq_per_img, tx, batch_size=max(2, n),
        )
        log.info("model: %s decoder, %.2fM params", opt.model_type,
                 param_count(self.state.params) / 1e6)

        # Stage chaining: warm-start params from the previous stage's best
        # checkpoint (fresh optimizer state), like the reference's
        # --start_from (SURVEY.md §5 checkpoint/resume).
        if getattr(opt, "start_from", None):
            # readonly: a reader must never quarantine/scrub a directory
            # another stage owns (checkpoint.py __init__ docstring).
            prev = CheckpointManager(opt.start_from, readonly=True)
            params = prev.restore_params(self.state.params, best=True)
            self.state = self.state.replace(params=params)
            prev.close()
            log.info("warm-started params from %s (best step %s)",
                     opt.start_from, prev.best_step)

        self.ckpt = CheckpointManager(opt.checkpoint_path,
                                      max_to_keep=opt.max_checkpoints,
                                      fault_plan=self._faults,
                                      telemetry=self._telemetry)
        resume_step = self.ckpt.latest_verified_step
        if resume_step is not None:
            latest = self.ckpt.latest_step
            if latest is not None and latest != resume_step:
                log.warning(
                    "newest checkpoint (step %d) failed integrity "
                    "verification — torn write; resuming from the last "
                    "verified step %d instead", latest, resume_step)
            self.state = self.ckpt.restore(self.state, step=resume_step)
            log.info("resumed from step %d in %s", int(resume_step),
                     opt.checkpoint_path)
        elif self.ckpt.latest_step is not None:
            log.warning(
                "every checkpoint in %s failed integrity verification; "
                "starting this stage from scratch", opt.checkpoint_path)
        # HOST-side step truth for the trainer's control plane (loop
        # position, rollout key stream, summaries): same value as the
        # device state.step on a healthy stack, but sourced from the
        # checkpoint directory's host-verified step number instead of a
        # device scalar fetch — the same no-device-scalar rule the
        # rollback path follows (this session's native stack occasionally
        # garbles scalar fetches; RESILIENCE.md caveat).
        self._host_step = int(resume_step) if resume_step is not None else 0
        # Step number of the newest durable checkpoint (host int): the
        # preemption boundary skips its forced save when the state on disk
        # is already current (e.g. the signal landed during the validate
        # that followed an epoch-boundary save).  -1 = nothing saved yet.
        self._last_saved_step = (int(resume_step) if resume_step is not None
                                 else -1)
        self._last_save_monotonic = time.monotonic()
        # Divergence-rollback target: a HOST-memory snapshot of the last
        # known-good state, refreshed at every checkpoint save (and here,
        # right after a resume — a fresh run deliberately has NO snapshot
        # until its first save, so an early divergence continues forward
        # from the skip-protected current state instead of replaying from
        # step 0).  Rolling back from memory instead of re-reading the
        # checkpoint keeps the recovery path free of same-process
        # tensorstore reads — observed to corrupt the heap on this
        # session's CPU stack — and costs no tunnel round trip on a remote
        # device.  The disk checkpoint remains the cross-process resume
        # source.
        self._good_state = None
        if resume_step is not None:
            self._snapshot_good_state(resume_step)

        # -- device-resident features (--device_feats) ---------------------
        self._feat_tables = None
        if getattr(opt, "device_feats", 0):
            self._feat_tables = self._load_device_feats()

        # -- compiled steps ------------------------------------------------
        xe_raw = make_xe_step(self.model, opt.seq_per_img,
                              guard=self._guard is not None)
        if self._feat_tables is not None:
            tables = self._feat_tables

            def xe_raw(state, video_ix, labels, weights, rng, _inner=xe_raw):
                return _inner(state, [t[video_ix] for t in tables],
                              labels, weights, rng)

        # Donation policy (ISSUE 3 tentpole): the state — params + optimizer
        # moments, the largest live buffers — is donated into every update
        # step (donate_argnums=(0,)), so XLA updates them in place instead
        # of holding old+new copies across the step.  Batch args are NOT
        # donated: these programs have no batch-shaped outputs to alias
        # them onto, so XLA would skip the donation with a warning and
        # keep the buffer anyway (pinned by tests/test_decode_fastpath).
        self.xe_step = data_parallel_jit(
            xe_raw, self.mesh, batch_argnums=(1, 2, 3), donate_argnums=(0,),
        )
        self.reward_computer = None
        self._rl_pipeline = None
        self._fused_step = None
        if opt.use_rl:
            self._setup_rl()
        self._watchdog.beat()  # init milestones (uploads, RL tables) done

        self._batch_sharding = batch_sharding(self.mesh)
        self.history: Dict[str, Any] = {"val": []}

        # -- observability: metrics.jsonl always, TensorBoard opt-in -------
        # Step records fan out through the telemetry registry (ONE write
        # surface instead of ad-hoc dict writes): metrics.jsonl (schema 2)
        # + optional TB scalars, with a telemetry.json snapshot on exit.
        # Sinks attach on process 0 only — one metrics stream per pod;
        # counters still count on every process (host-local audit).
        self._metrics_path = os.path.join(
            os.path.abspath(opt.checkpoint_path), "metrics.jsonl"
        )
        self._tb = None
        if jax.process_index() == 0:
            self._telemetry.registry.add_sink(JsonlSink(self._metrics_path))
            self._telemetry.snapshot_path = os.path.join(
                os.path.abspath(opt.checkpoint_path), "telemetry.json")
            if getattr(opt, "tensorboard", 0):
                try:
                    from ..utils.tb import ScalarWriter

                    self._tb = ScalarWriter(
                        os.path.join(os.path.abspath(opt.checkpoint_path),
                                     "tb")
                    )
                    self._telemetry.registry.add_sink(
                        ScalarWriterSink(self._tb))
                except ImportError as e:  # tensorboard pkg not installed
                    log.warning("tensorboard writer unavailable: %s", e)
        # finally/atexit double cover: train.py's finally calls close(),
        # and the atexit hook flushes TB events + the telemetry snapshot
        # when a run dies mid-epoch down a path that never reaches close()
        # (telemetry.close and ScalarWriter.close are both idempotent).
        atexit.register(self._telemetry.close)

        # -- live MFU accounting (--step_timing / --trace_dir) -------------
        # Same arithmetic as bench.py (telemetry/flops.py — shared so the
        # in-trainer gauge and the offline benchmark cannot drift), at the
        # RUN's real shapes: this run's feature modalities, vocab, decode
        # length.  PER-CHIP like bench's captions/s/chip: the step is
        # batch-sharded over the mesh, so each chip computes a 1/mesh.size
        # share — dividing here keeps mfu_pct comparable against ONE
        # chip's peak instead of reading mesh.size-times too high on a
        # pod slice.  Estimate note: assumes embed = attn = hidden.
        self._flops_per_step = None
        if self._telemetry.phases is not None:
            stage = "cst" if opt.use_rl else "xe"
            flops = caption_step_flops(
                opt.batch_size, opt.seq_per_img,
                opt.max_length if opt.use_rl else self.train_ds.seq_length,
                self.vocab.size_with_pad, opt.rnn_size,
                feat_shapes=feat_shapes,
            )
            self._flops_per_step = flops[stage] / max(1, self.mesh.size)
            self._device_kind = getattr(jax.devices()[0], "device_kind", "")

    def _maybe_log_train(self, step1: int, metrics: Dict[str, float],
                         total_steps: int, bpe: int) -> None:
        """Console + metrics.jsonl/TB logging for one completed train step,
        honoring --log_every.  ``step1`` is the 1-based index of the step
        the metrics belong to (= the loop step for XE; the completing
        pipeline step under RL overlap)."""
        if step1 % self.opt.log_every != 0:
            return
        # ONE batched device fetch, not a float() per metric: each separate
        # scalar fetch costs a full host<->device round trip (painful on
        # remote-TPU tunnels: 6 CST metrics x ~100ms RTT per logged step).
        for v in metrics.values():
            if hasattr(v, "copy_to_host_async"):
                v.copy_to_host_async()
        m = {k: float(np.asarray(v)) for k, v in metrics.items()}
        lr = float(self.lr_sched(step1 - 1))
        extra = {"lr": lr}
        cps_txt = ""
        if self._captions_done:  # 0 for steps logged mid-drain-burst:
            # their captions were already counted by the first drained step,
            # so a cps there would be a spurious zero in the metrics stream.
            dt = time.monotonic() - self._log_t0
            cps = self._captions_done / max(dt, 1e-9)
            extra["captions_per_sec"] = cps
            cps_txt = f" | {cps:.0f} captions/s"
            # Step-phase + MFU gauges (--step_timing / --trace_dir): the
            # interval's wall-time partition (host-attributed; exclusive
            # — see telemetry/phases.py) and the live utilization the
            # analytic FLOPs model implies.  mfu_pct is null off-TPU.
            phases = self._telemetry.phases
            if phases is not None:
                ncaps = self.opt.batch_size * self.opt.seq_per_img
                extra.update(phases.drain_ms(
                    max(1, round(self._captions_done / ncaps))))
                extra.update(mfu_fields(self._flops_per_step, cps, ncaps,
                                        self._device_kind))
            self._log_t0, self._captions_done = time.monotonic(), 0
        log.info(
            "step %d/%d epoch %.2f %s lr %.2e%s",
            step1, total_steps, step1 / bpe,
            " ".join(f"{k} {v:.4f}" for k, v in m.items()), lr, cps_txt,
        )
        self._check_advantage_regime(m)
        self._log_metrics(step1, "train", {**m, **extra})

    # Negative-advantage regime detector: with a greedy baseline, if the
    # multinomial samples score systematically BELOW the greedy decode,
    # every advantage is negative and REINFORCE can only push probability
    # mass away from typical sequences — the policy degenerates (sample
    # length drifts, then val collapses; observed live at 512-video scale:
    # reward 0.12 vs baseline 0.26 at step 10 → collapse by epoch 12).
    # SCB baselines are centred by construction and don't enter this
    # regime.  One warning, early, with the numbers and the remedies.
    _ADV_WARN_STEPS = 5

    def _check_advantage_regime(self, m: Dict[str, float]) -> None:
        if "advantage" not in m or getattr(self, "_adv_warned", False):
            return
        # Rolling window of the last K logged steps: bounded memory, and
        # one noise-positive early advantage only delays detection by K
        # steps instead of disabling it for the whole run.
        hist = getattr(self, "_adv_history", [])
        hist.append((m["advantage"], m.get("reward", 0.0),
                     m.get("baseline", 0.0)))
        self._adv_history = hist = hist[-self._ADV_WARN_STEPS:]
        if len(hist) < self._ADV_WARN_STEPS:
            return
        adv = [a for a, _, _ in hist]
        if max(adv) < 0 and np.mean(adv) < -0.05:
            rew = np.mean([r for _, r, _ in hist])
            base = np.mean([b for _, _, b in hist])
            msg = (
                "advantage has been negative on every logged step so far "
                "(mean %.3f; sampled reward %.3f vs baseline %.3f): the "
                "baseline dominates the samples, so REINFORCE is only "
                "suppressing typical sequences and the policy is likely "
                "to degenerate.  Remedies: --rl_baseline scb-sample/"
                "scb-gt (centred by construction), lower --temperature, "
                "or a lower --learning_rate." % (np.mean(adv), rew, base))
            self._adv_warned = True
            # getattr chain, not self.opt: the detector is also driven as
            # a bound-free method over a bare namespace in unit tests.
            opt = getattr(self, "opt", None)
            if opt is not None and getattr(
                    opt, "abort_on_negative_advantage_window", 0):
                # Opt-in hard stop for unattended chains: surface the
                # collapsing stage now (exit 4 via train.py) rather than
                # training a degenerating policy for the rest of the
                # stage's epoch/chip budget.
                self._telemetry.inc("negative_advantage_aborts")
                raise NegativeAdvantageAbort(msg)
            log.warning(msg)

    def _log_metrics(self, step: int, scope: str,
                     metrics: Dict[str, float]) -> None:
        # One fan-out surface: metrics.jsonl (schema 2) + TB scalars via
        # the registry's sinks (attached on process 0 only — a non-zero
        # process's registry has no sinks, so this is a cheap no-op there)
        # plus the last-record bookkeeping the heartbeat/exit snapshot
        # read.
        self._telemetry.registry.log_step(step, scope, metrics)

    # -- device-resident features -----------------------------------------

    def _feat_dtype(self):
        """numpy dtype features travel/reside in: bfloat16 when --bf16_feats
        resolves true (default: follow --use_bfloat16), else None (keep
        f32).  ONE resolution shared by the streamed prefetch path and the
        device-resident tables so the two paths can never diverge."""
        bf16 = getattr(self.opt, "bf16_feats", None)
        if bf16 is None:
            bf16 = self.opt.use_bfloat16
        if not bf16:
            return None
        import ml_dtypes

        return ml_dtypes.bfloat16

    def _load_device_feats(self):
        """Read EVERY training video's features once and pin them in HBM
        (replicated over the mesh); train steps gather rows by video_ix
        inside jit.  Dtype follows ``_feat_dtype`` (bf16 halves residency).
        MSR-VTT scale is ~0.8 GB in bf16; for datasets that do not fit,
        leave --device_feats 0 and the prefetch thread streams per-batch
        features instead.

        Multi-host cost model (ADVICE r3): the table is REPLICATED — every
        process reads the full h5 set from its own filesystem and every
        device holds the full table, so adding hosts/chips does not raise
        the dataset-size ceiling; it is always full-table-per-device.  The
        guard below fails at startup with the table size instead of letting
        a pod run die in an opaque device OOM mid-epoch.

        Reads and uploads in bounded row chunks (``upload_table_chunked``)
        so (a) transient host memory stays ~one chunk per modality, never a
        full-dataset copy, and (b) no single host->device transfer exceeds
        ``--device_feats_upload_mb`` — one monolithic multi-hundred-MB
        ``device_put`` was observed to wedge a remote-tunnel transport that
        streams per-batch transfers indefinitely, and chunked uploads also
        give loggable progress instead of a silent multi-minute stall."""
        from ..parallel import replicated_sharding

        if getattr(self.opt, "preload_feats", 0):
            log.info("--preload_feats with --device_feats keeps an unused "
                     "full f32 feature copy in host RAM; prefer "
                     "--preload_feats 0 when features live on device")
        dtype = self._feat_dtype()
        n = self.train_ds.num_videos
        shapes = list(zip(self.train_ds.feat_times, self.train_ds.feat_dims))
        itemsize = np.dtype(dtype or np.float32).itemsize
        table_bytes = sum(n * t * d * itemsize for t, d in shapes)
        budget = float(getattr(self.opt, "device_feats_max_gb", 8.0)) * 1e9
        if table_bytes > budget:
            raise ValueError(
                f"--device_feats table is {table_bytes / 1e9:.1f} GB "
                f"PER DEVICE (replicated; {n} videos), over the "
                f"--device_feats_max_gb {budget / 1e9:.1f} GB budget — "
                "use --device_feats 0 (streamed prefetch) or raise the "
                "budget if the chip's HBM actually fits it")
        tables = upload_table_chunked(
            self.train_ds.features, n, shapes, dtype,
            replicated_sharding(self.mesh),
            upload_mb=float(getattr(self.opt, "device_feats_upload_mb", 64.0)),
            beat=self._watchdog.beat,
        )
        log.info("device_feats: %d videos x %d modalities pinned in HBM "
                 "(%.2f GB%s)", n, len(tables), table_bytes / 1e9,
                 ", bf16" if dtype is not None else "")
        return tables

    # -- RL plumbing -------------------------------------------------------

    def _setup_rl(self) -> None:
        opt = self.opt
        refs = tokenize_corpus(self.train_ds.references())
        self._fused_step = None
        # Resume-safe rollout key stream: continue from the restored step so
        # a resumed run never replays the draws of steps whose updates made
        # it into the checkpoint.  (Host path, depth k: rollouts in flight
        # at a crash never updated params, so their fold_in indices ARE
        # redrawn after resume — under the restored params, which is the
        # correct on-policy behavior; checkpoints written by save_recovery
        # drain the pipeline first, so this only applies to hard crashes.)
        # Host-side step mirror, not a device scalar fetch (see _host_step).
        self._rl_dispatch_step = self._host_step
        if getattr(opt, "device_rewards", 0):
            self._setup_fused_rl(refs)
            return
        scorer = None
        if getattr(opt, "native_cider", 1):
            # C++ scorer consumes token ids straight off the rollout.
            try:
                from ..native import NativeCiderD

                scorer = NativeCiderD(refs, self.vocab.word_to_ix)
            except Exception as e:  # toolchain missing etc. — fall back
                log.warning("native CIDEr-D unavailable (%s); using Python", e)
            else:
                if getattr(opt, "train_cached_tokens", None):
                    # Honor the user's precomputed corpus-df pickle exactly
                    # (same artifact the Python scorer loads); without it
                    # the df is derived from this run's training refs.  A
                    # bad pickle must FAIL, not silently train on the
                    # wrong df — so no except around this block.
                    try:
                        df, ref_len = load_corpus_df(opt.train_cached_tokens)
                        scorer.load_df(df, ref_len)
                    except Exception:
                        scorer.close()
                        raise
                    log.info("RL reward: native CIDEr-D with corpus df "
                             "from %s (%d n-grams, %d docs)",
                             opt.train_cached_tokens, len(df), int(ref_len))
                log.info("RL reward: native C++ CIDEr-D (%d videos)",
                         scorer.num_videos)
        if scorer is None:
            if getattr(opt, "train_cached_tokens", None):
                scorer = CiderD(df_mode="corpus",
                                df_path=opt.train_cached_tokens)
            else:
                log.info("no --train_cached_tokens; building corpus df in-process")
                df, ndocs = build_corpus_df(refs)
                scorer = CiderD(df_mode="corpus", df=df, ref_len=float(ndocs))
        self.reward_computer = RewardComputer(
            self.vocab, scorer, refs,
            seq_per_img=opt.seq_per_img,
            baseline=opt.rl_baseline,
            consensus_scores=self.consensus_scores,
            scb_captions=opt.scb_captions,
            telemetry=self._telemetry,
        )
        rollout_raw = make_rollout_fused(
            self.model, opt.max_length, opt.seq_per_img,
            temperature=opt.temperature,
            greedy_baseline=opt.rl_baseline == "greedy",
            decode_chunk=getattr(opt, "decode_chunk", 0))
        rl_raw = make_rl_grad_step(self.model, opt.seq_per_img,
                                   guard=self._guard is not None)
        if self._feat_tables is not None:
            tables = self._feat_tables

            def rollout_raw(params, video_ix, rng, _inner=rollout_raw):
                return _inner(params, [t[video_ix] for t in tables], rng)

            def rl_raw(state, video_ix, sampled, advantage, rng,
                       _inner=rl_raw):
                return _inner(state, [t[video_ix] for t in tables],
                              sampled, advantage, rng)

        self.rollout = data_parallel_jit(
            rollout_raw,
            self.mesh, batch_argnums=(1,), donate_argnums=(),
            # sampled flows straight back into rl_step on device, so it must
            # keep the batch sharding; fetch leaves for the host either way.
            out_batch_tree=(True, True),
        )
        # State donated (see xe_step donation-policy note); the rollout
        # above donates nothing — its params input is the same live params
        # the grad step still reads, and its feats stay in flight in the
        # pipeline until the grad step consumes them.
        self.rl_step = data_parallel_jit(
            rl_raw, self.mesh, batch_argnums=(1, 2, 3), donate_argnums=(0,),
        )
        # Overlapped CST pipeline (SURVEY §7 step 6): rollouts dispatched
        # ahead of their reward/grad step, so host CIDEr-D + the tunnel
        # round trips run while the device computes the next rollout.
        self._rl_pipeline = RewardPipeline(
            self.rollout, self.rl_step,
            # ctx = (absolute step index, video ids): the index keeps
            # metric attribution honest under the pipeline lag.
            lambda ctx, s, g: self.reward_computer(ctx[1], s, g),
            depth=getattr(opt, "overlap_rewards", DEFAULT_OVERLAP_REWARDS),
            telemetry=self._telemetry,
        )

    def _setup_fused_rl(self, refs) -> None:
        """--device_rewards: the whole CST iteration as ONE device program
        (rollout + on-device CIDEr-D + REINFORCE grad; steps.py
        make_fused_cst_step).  No host reward path, no pipeline, strict
        on-policy semantics."""
        from .device_rewards import build_device_tables
        from .rewards import scb_gt_value
        from .steps import make_fused_cst_step

        opt = self.opt
        external_df = external_ref_len = None
        if getattr(opt, "train_cached_tokens", None):
            external_df, external_ref_len = load_corpus_df(
                opt.train_cached_tokens)
        # Batch.video_ix indexes the dataset's video list, so table rows
        # must follow that exact order — re-key rather than trusting the
        # refs mapping's iteration order (a cocofmt file can list
        # annotations in any order).
        try:
            refs = {v: refs[v] for v in self.train_ds.video_ids}
        except KeyError as e:
            raise ValueError(
                f"video {e.args[0]!r} has no reference captions; "
                "--device_rewards needs references for every training video"
            ) from None
        corpus, tables, video_row = build_device_tables(
            refs, self.vocab.word_to_ix,
            external_df=external_df, external_ref_len=external_ref_len,
            telemetry=self._telemetry,
        )
        scb_gt = None
        if opt.rl_baseline == "scb-gt":
            if self.consensus_scores is None:
                raise ValueError("scb-gt baseline needs --train_bcmrscores_pkl")
            import jax.numpy as jnp

            missing = [v for v in self.train_ds.video_ids
                       if v not in self.consensus_scores]
            if missing:
                # Same visibility as the host path: a mismatched pickle
                # would otherwise degrade training invisibly (baseline 0).
                log.warning(
                    "scb-gt baseline: %d video(s) missing from the "
                    "consensus pickle (e.g. %s); their baseline falls back "
                    "to 0.0 — check --train_bcmrscores_pkl matches the "
                    "training split", len(missing), missing[:3],
                )
            scb_gt = jnp.asarray(np.asarray([
                scb_gt_value(self.consensus_scores.get(vid, [0.0]),
                             opt.scb_captions)
                for vid in self.train_ds.video_ids
            ], dtype=np.float32))
        # Reward-memory envelope: the hyp-ref match transient is the fused
        # step's dominant HBM term and grows as batch·refs·ref_len·hyp_len;
        # log it and chunk the contraction over the R axis past the budget
        # so batch/length growth degrades gracefully instead of OOMing
        # (VERDICT r3 #3).  Scores agree to f32 ULP level (test-pinned).
        from ..ops.jax_ciderd import auto_ref_chunk, match_tensor_bytes

        # The step runs batch-sharded over the data axis, so the transient
        # that actually lands in any one chip's HBM is the PER-DEVICE
        # shard of the hypothesis axis — budget against that, not the
        # global batch (global would over-chunk an 8-chip mesh 8x).
        data_size = int(self.mesh.shape.get("data", 1))
        n_hyps = -(-opt.batch_size * opt.seq_per_img // data_size)
        budget = int(float(getattr(opt, "device_cider_chunk_mb", 256)) * 2**20)
        envelope = match_tensor_bytes(n_hyps, opt.max_length, tables)
        ref_chunk = auto_ref_chunk(n_hyps, opt.max_length, tables,
                                   budget_bytes=budget)
        log.info(
            "device rewards: match transient %.1f MB/device (batch %d x %d "
            "caps/video over %d device(s), %d refs x %d grams, hyp "
            "positions for len %d)%s",
            envelope / 2**20, opt.batch_size, opt.seq_per_img, data_size,
            tables.slot.shape[1], tables.slot.shape[2], opt.max_length,
            (f"; chunking over refs at {ref_chunk} to stay under "
             f"{budget / 2**20:.0f} MB" if ref_chunk is not None
             else " (within budget, one-shot)"),
        )
        fused_raw = make_fused_cst_step(
            self.model, opt.max_length, opt.seq_per_img, corpus, tables,
            baseline=opt.rl_baseline, temperature=opt.temperature,
            scb_gt_baseline=scb_gt, ref_chunk=ref_chunk,
            guard=self._guard is not None,
            decode_chunk=getattr(opt, "decode_chunk", 0),
        )
        if self._feat_tables is not None:
            feat_tables = self._feat_tables

            def fused_vix(state, video_ix, rng, _inner=fused_raw):
                return _inner(state, [t[video_ix] for t in feat_tables],
                              video_ix, rng)

            self._fused_step = data_parallel_jit(
                fused_vix, self.mesh, batch_argnums=(1,), donate_argnums=(0,),
            )
        else:
            self._fused_step = data_parallel_jit(
                fused_raw, self.mesh, batch_argnums=(1, 2), donate_argnums=(0,),
            )
        self._rl_pipeline = None
        log.info("RL reward: fused on-device CIDEr-D (%d videos, "
                 "df table %d slots)", tables.ref_mask.shape[0],
                 corpus.key1.shape[0])

    # -- iteration bodies --------------------------------------------------

    def _batch_feats_arg(self, batch):
        """First batch argument of the compiled steps: the feature arrays
        (host-streamed path) or the (B,) video indices that gather from the
        HBM-resident tables inside jit (--device_feats)."""
        if self._feat_tables is not None:
            return np.asarray(batch.video_ix, dtype=np.int32)
        return batch.feats

    def _rollout_rng(self, step_ix: int):
        """Rollout key for one dispatch step.  ``_rng_salt`` is 0 until the
        first divergence rollback; each rollback bumps it so the replayed
        steps draw a FRESH key stream — replaying the exact multinomial
        draws that just diverged would re-walk the same trajectory."""
        base = self.rng
        if self._rng_salt:
            base = jax.random.fold_in(base, 1_000_003 + self._rng_salt)
        return jax.random.fold_in(base, step_ix)

    def _nan_fault_inputs(self, step_ix: int, arrays):
        """``nan_grad`` chaos hook: when the plan covers ``step_ix``,
        replace the step's host-side input arrays with all-NaN twins of
        the same shape/dtype so the device computes a non-finite
        loss/gradient — exercising the guard without touching the compiled
        program.  Returns the arrays unchanged (same objects) otherwise."""
        if self._faults is None or not self._faults.fire("nan_grad", step_ix):
            return arrays
        log.warning("FAULT: nan_grad at step %d — feeding NaN inputs",
                    step_ix + 1)
        return [np.full(np.shape(a), np.nan, dtype=np.asarray(a).dtype)
                for a in arrays]

    def _observe_guard(self, step_ix: int, metrics) -> None:
        if self._guard is not None:
            self._guard.observe(step_ix, metrics.get("bad_step"))

    def _xe_iteration(self, batch) -> Dict[str, float]:
        # XE's NaN injection point is the consensus-weight vector: always
        # host-resident, multiplies straight into the loss on any path.
        (weights,) = self._nan_fault_inputs(self._progress_step,
                                            [batch.weights])
        self.state, metrics = self.xe_step(
            self.state, self._batch_feats_arg(batch), batch.labels,
            weights, self.rng
        )
        return metrics

    def _rl_iteration(self, batch):
        """One pipelined CST step (``training.pipeline.RewardPipeline``).

        Depth 0 reproduces the reference's serial semantics exactly; depth
        k >= 1 grades each sample under params up to k updates newer than
        the ones that drew it (stale-sample REINFORCE; see PARITY.md).
        Returns the steps COMPLETED by this call as (step_index, metrics)
        pairs — empty while the pipeline fills.
        """
        step_ix = self._rl_dispatch_step
        roll_rng = self._rollout_rng(step_ix)
        self._rl_dispatch_step += 1
        # RL's NaN injection point is the streamed feature arrays (NaN
        # features -> NaN logits -> NaN loss/grads).  With --device_feats
        # the features never cross the host, so the hook cannot reach them:
        # fail the chaos drill loudly instead of silently not injecting.
        feats_arg = self._batch_feats_arg(batch)
        if self._faults is not None and self._faults.pending("nan_grad"):
            if self._feat_tables is not None:
                raise RuntimeError(
                    "nan_grad fault injection needs host-streamed features "
                    "on RL paths; rerun the chaos drill with "
                    "--device_feats 0")
            feats_arg = self._nan_fault_inputs(step_ix, feats_arg)
        if self._fused_step is not None:  # --device_rewards: no host gap
            if self._feat_tables is not None:
                self.state, metrics = self._fused_step(
                    self.state, feats_arg, roll_rng)
            else:
                self.state, metrics = self._fused_step(
                    self.state, feats_arg,
                    np.asarray(batch.video_ix, dtype=np.int32), roll_rng)
            return [(step_ix, metrics)]
        self.state, completed = self._rl_pipeline.push(
            self.state, feats_arg, roll_rng, self.rng,
            (step_ix, batch.video_ids),
        )
        return [(c[0], m) for c, m in completed]

    def _rl_drain(self):
        """Flush the pipeline (epoch boundary / checkpoint / end of run);
        returns the flushed steps' (step_index, metrics) for logging."""
        if self._rl_pipeline is None:  # fused path has nothing in flight
            return []
        self.state, completed = self._rl_pipeline.drain(self.state)
        return [(c[0], m) for c, m in completed]

    def _note_saved(self, step1: int) -> None:
        """Bookkeeping after ANY durable checkpoint save: the preemption
        boundary uses ``_last_saved_step`` to skip a redundant save, and
        the ``--save_interval_secs`` cadence restarts its wall clock."""
        self._last_saved_step = int(step1)
        self._last_save_monotonic = time.monotonic()

    def _honor_preemption(self, step: int, drain) -> None:
        """Step-boundary half of the preemption contract (module docstring
        of resilience/preemption.py): called when the handler's flag is
        set.  Drains in-flight rollouts, forces a VERIFIED checkpoint save
        through the normal manifest/integrity path (skipped when the
        newest checkpoint already holds this step), stamps the preemption
        counters, and raises :class:`PreemptedExit` — which train.py maps
        to the taxonomy's resumable exit code."""
        h = self._preempt
        reg = self._telemetry.registry
        reg.inc("preempt_signals", h.drain_signal_count())
        saved = step != self._last_saved_step
        if saved:
            if self.opt.use_rl:
                drain()  # the checkpoint must include every dispatched step
            with self._telemetry.phase("ckpt"):
                self.ckpt.save_recovery(step, self.state, verify=True)
            self._note_saved(step)
            reg.inc("preempt_saves")
        if h.signal_monotonic is not None:
            reg.set_gauge(
                "preempt_exit_ms",
                round((time.monotonic() - h.signal_monotonic) * 1e3, 3))
        # Durable with the state it describes, like every checkpoint
        # boundary — this is the last flush before the process exits.
        self._telemetry.flush(fsync=True)
        log.warning(
            "preemption (%s) honored at step boundary %d: %s; exiting with "
            "the resumable taxonomy code", h.signal_name, step,
            "verified checkpoint saved" if saved
            else "checkpoint already current")
        raise PreemptedExit(step, h.signal_name or "signal", saved)

    def _snapshot_good_state(self, step: int) -> None:
        """Host-memory copy of the current state — the divergence guard's
        rollback target.  Called right after every checkpoint save (the
        state just proven durable) and after a resume.  ``step`` is the
        HOST-side step counter the snapshot belongs to: the rollback's
        loop/key bookkeeping is rebuilt from it rather than from a device
        scalar fetch (which this environment's native stack occasionally
        garbles — RESILIENCE.md caveat).  No-op when the guard is off:
        the snapshot's device->host fetch would be pure overhead."""
        if self._guard is None:
            return
        self._good_state = (int(step),
                            jax.tree_util.tree_map(np.asarray, self.state))

    def _handle_divergence(self, failed_step: int) -> Optional[int]:
        """Rollback after ``--divergence_max_bad`` consecutive non-finite
        steps: reload the last known-good state (host snapshot taken at
        the last checkpoint save), discard in-flight rollouts, re-seed the
        rollout key stream, and return the loop step to replay from —
        or None when there is nothing to rewind to, meaning "finish the
        current iteration normally" (so an epoch-boundary validate/save is
        not silently skipped).  ``DivergenceUnrecoverable`` propagates
        once the ``--divergence_max_rollbacks`` budget is spent — a
        divergence that replaying cannot fix must abort, not loop
        forever."""
        self._guard.note_rollback()
        if self._rl_pipeline is not None:
            dropped = self._rl_pipeline.abort()
            if dropped:
                log.warning("divergence rollback: discarded %d in-flight "
                            "rollout(s) drawn from the diverged params",
                            dropped)
        self._rng_salt += 1
        if self._good_state is None:
            # No checkpoint this run — but the guard's on-device skips kept
            # params at their last finite values, so the CURRENT state is
            # the known-good state: continue forward on a fresh key stream
            # instead of dying before the first checkpoint.
            log.warning(
                "divergence guard: rollback requested at step %d but no "
                "checkpoint exists yet; continuing from the current "
                "(skip-protected) state with re-seeded rollout keys "
                "(salt %d)", failed_step + 1, self._rng_salt)
            self._rl_dispatch_step = failed_step + 1
            return None
        import jax.numpy as jnp

        good_step, snap = self._good_state
        state = jax.tree_util.tree_map(jnp.asarray, snap)
        # Pin the step from the host counter: the snapshot's own step leaf
        # is authoritative too, but rebuilding the loop position from a
        # plain python int keeps this recovery path free of device-scalar
        # round trips.
        self.state = state.replace(
            step=jnp.asarray(good_step, dtype=state.step.dtype))
        self._rl_dispatch_step = good_step
        log.warning(
            "divergence guard: rolled back from step %d to the known-good "
            "state of step %d (rollback %d/%d); replaying with a "
            "re-seeded rollout key stream (salt %d)",
            failed_step + 1, good_step, self._guard.rollbacks,
            self._guard.max_rollbacks, self._rng_salt)
        return good_step

    # -- main loop ---------------------------------------------------------

    def _profile_window(self) -> Optional[Tuple[int, int]]:
        """Loop-step window [start, stop) for the programmatic
        jax.profiler trace; None when --profile_dir is unset.
        ``--profile_steps`` is either a COUNT (window starts at
        --profile_start, the historical form) or an explicit ``A:B``."""
        opt = self.opt
        if not opt.profile_dir:
            return None
        spec = str(getattr(opt, "profile_steps", "10")).strip()
        if ":" in spec:
            a, b = spec.split(":", 1)
            start, stop = int(a), int(b)
        else:
            start = int(getattr(opt, "profile_start", 10))
            stop = start + int(spec)
        if stop <= start:
            raise ValueError(
                f"--profile_steps {spec!r} with --profile_start "
                f"{getattr(opt, 'profile_start', 10)} is an empty window")
        return start, stop

    def validate(self) -> Optional[Dict[str, float]]:
        if self.val_loader is None:
            return None
        refs = self.val_ds.references()
        scorers = None
        if self.opt.fast_val:
            # Always include the model-selection metric: scoring only CIDEr
            # while selecting on METEOR would zero every epoch's score and
            # blind the early stop (VERDICT.md round 2, weak #4).
            # language_eval accepts either METEOR spelling as a scorer
            # name, so no remap is needed here.
            sel = ("Bleu" if self.opt.eval_metric.startswith("Bleu")
                   else self.opt.eval_metric)
            scorers = tuple(dict.fromkeys(("CIDEr", sel)))
        _, scores = eval_split(
            self.model, self.state.params, self.val_loader, self.vocab,
            self.opt.max_length, refs,
            beam_size=self.opt.val_beam_size,
            length_norm=self.opt.length_norm,
            scorers=scorers,
            mesh=self.mesh,  # decode shards over data axis, no idle chips
            beat=self._watchdog.beat,  # long val decode is not a wedge
            decode_chunk=getattr(self.opt, "decode_chunk", 0),
        )
        self._watchdog.beat()  # host-side scoring done too
        return scores

    def train(self) -> Dict[str, Any]:
        opt = self.opt
        bpe = self.loader.batches_per_epoch
        # Host-side loop position, never a device scalar fetch (_host_step
        # note in _init): identical to state.step on a healthy stack.
        start_step = self._host_step
        # Data half of deterministic resume (loader.skip_batches): align
        # the batch stream with the position the restored params were
        # trained to, BEFORE the prefetch worker starts drawing — a
        # resumed run then consumes the same batch sequence from
        # start_step onward as an uninterrupted run of the same seed, so
        # a preempted-and-resumed stage ends bit-identical to its twin.
        if start_step > 0:
            self.loader.skip_batches(start_step)
        # The loader itself (not iter(loader)) so the prefetch worker can
        # re-issue a failed next_batch: transient feature-read errors are
        # retried with backoff instead of poisoning the run.
        it = iter(prefetch_to_device(
            self.loader, size=2,
            device_put=lambda x: jax.device_put(x, self._batch_sharding),
            feat_dtype=self._feat_dtype(),
            telemetry=self._telemetry,
            # --loader_workers N: assembler threads + ordered reassembly;
            # the consumed stream is bit-identical at any worker count.
            workers=int(getattr(opt, "loader_workers", 1) or 1),
        ))
        total_steps = opt.max_epochs * bpe
        best = self.ckpt.infos.get("best_score")
        best = float("-inf") if best is None else float(best)
        # epochs-since-best survives resume alongside best_score: a run
        # that crashes each epoch must early-stop at the same epoch as the
        # uninterrupted run (VERDICT r3 weak #4).
        patience = int(self.ckpt.infos.get("patience") or 0)
        if (opt.max_patience and patience >= opt.max_patience
                and start_step // bpe >= opt.min_epochs):
            # The stage ALREADY early-stopped in a previous run; re-running
            # it (e.g. the scale-chain recovery flow re-invoking every
            # stage) must be a no-op, not train bonus epochs whose noisy
            # val could resurrect a stopped run (round-4 review).
            log.info("early stop already reached (%d epochs without %s "
                     "improvement); nothing to train", patience,
                     opt.eval_metric)
            return {
                "best_score": None if best == float("-inf") else best,
                "best_step": self.ckpt.best_step,
                "last_step": start_step,
                "history": self.history,
            }
        self._log_t0 = time.monotonic()
        self._captions_done = 0
        # --save_interval_secs counts from the start of THIS process's
        # loop, not from Trainer construction: device bring-up must not
        # make the first wall-clock save fire on the first step.
        self._last_save_monotonic = time.monotonic()
        save_interval = float(getattr(opt, "save_interval_secs", 0.0) or 0.0)

        def drain_and_log():
            for k, m in self._rl_drain():
                self._observe_guard(k, m)
                self._maybe_log_train(k + 1, m, total_steps, bpe)

        profiling = False
        profile_window = self._profile_window()
        # Phase hooks below follow the --fault_plan pattern: ``ph`` is
        # None unless --trace_dir/--step_timing armed it, and the disabled
        # path of every hook is exactly one is-None check — no context
        # manager, no allocation, nothing near a jitted program.
        ph = self._telemetry.phases
        step = start_step
        # while (not for): a divergence rollback rewinds ``step`` to the
        # restored checkpoint and replays from there.
        while step < total_steps:
            # Each completed loop pass implies the previous dispatch, fetch,
            # val, and save all returned — one beat covers them all.
            self._watchdog.beat()
            self._progress_step = step  # host int, safe for describe()
            # Step boundary: a preemption signal that arrived during the
            # previous iteration (or during init) is honored HERE — save,
            # count, and exit resumable (raises PreemptedExit).
            if self._preempt is not None and self._preempt.requested:
                self._honor_preemption(step, drain_and_log)
            if self._faults is not None and self._faults.fire("preempt",
                                                              step):
                log.warning("FAULT: preempt at step %d — delivering a real "
                            "SIGTERM to pid %d (the boundary above must "
                            "checkpoint and exit next pass)", step + 1,
                            os.getpid())
                os.kill(os.getpid(), signal.SIGTERM)
            if self._faults is not None and self._faults.fire("wedge", step):
                log.critical("FAULT: wedge at step %d — blocking the train "
                             "loop (the watchdog must turn this into exit "
                             "%s)", step + 1, "124")
                time.sleep(2 ** 31)
            if profile_window is not None:
                if step == profile_window[0] and not profiling:
                    jax.profiler.start_trace(opt.profile_dir)
                    profiling = True
                elif profiling and step == profile_window[1]:
                    jax.profiler.stop_trace()
                    profiling = False
                    log.info("profiler trace written to %s", opt.profile_dir)
            if ph is None:
                batch = next(it)
            else:
                with ph.phase("data_wait"):
                    batch = next(it)
            self._captions_done += opt.batch_size * opt.seq_per_img
            if opt.use_rl:
                # Completed steps lag dispatch by the pipeline depth; each
                # is logged under ITS OWN step index, not the loop's.
                # "compute" covers dispatch + completion (the host-path
                # score nests inside and is attributed exclusively — see
                # telemetry/phases.py); logging's metric fetch stays
                # outside so a device sync at the log boundary shows up
                # as its own cost, not as compute.
                if ph is None:
                    completed = self._rl_iteration(batch)
                else:
                    with ph.phase("compute"):
                        completed = self._rl_iteration(batch)
                for k, m in completed:
                    self._observe_guard(k, m)
                    self._maybe_log_train(k + 1, m, total_steps, bpe)
            else:
                if ph is None:
                    metrics = self._xe_iteration(batch)
                else:
                    with ph.phase("compute"):
                        metrics = self._xe_iteration(batch)
                self._observe_guard(step, metrics)
                self._maybe_log_train(step + 1, metrics, total_steps, bpe)
            if self._guard is not None and self._guard.poll():
                rewind = self._handle_divergence(step)
                if rewind is not None:
                    step = rewind
                    continue

            # Recovery-save cadence: step-based (--save_every_steps) OR
            # wall-clock (--save_interval_secs — long CST stages bound
            # preemption/crash loss by TIME even when step rate drifts).
            due_steps = (opt.save_every_steps
                         and (step + 1) % opt.save_every_steps == 0)
            due_time = (save_interval > 0
                        and time.monotonic() - self._last_save_monotonic
                        >= save_interval)
            if ((due_steps or due_time)
                    and (step + 1) % bpe != 0):  # epoch boundary saves below
                if opt.use_rl:
                    drain_and_log()  # checkpoint must include all updates
                # Cold sites (seconds of orbax work) use the facade — the
                # disarmed case returns the shared no-op; only the
                # per-step data_wait/compute hooks above keep the explicit
                # is-None branch.
                with self._telemetry.phase("ckpt"):
                    self.ckpt.save_recovery(step + 1, self.state)
                self._note_saved(step + 1)
                self._snapshot_good_state(step + 1)
                # Checkpoint boundary: make the metrics stream durable with
                # the state it describes (schema-2 contract, ISSUE 2).
                self._telemetry.flush(fsync=True)

            if (step + 1) % bpe == 0:  # epoch boundary
                if opt.use_rl:
                    drain_and_log()  # validate/ckpt on fully-updated params
                # Reap every queued bad-step flag before validating/saving:
                # a divergence in the epoch's tail must roll back, not ride
                # into the best-score bookkeeping.
                if self._guard is not None and self._guard.flush():
                    rewind = self._handle_divergence(step)
                    if rewind is not None:
                        step = rewind
                        continue
                scores = self.validate()
                if scores is not None:
                    metric = scores.get(score_key(opt.eval_metric), 0.0)
                    self.history["val"].append(
                        {"step": step + 1, **scores}
                    )
                    self._log_metrics(step + 1, "val", scores)
                    log.info("val @ step %d: %s", step + 1,
                             {k: round(v, 4) for k, v in scores.items()})
                    if metric > best:
                        best, patience = metric, 0
                    else:
                        patience += 1
                    # patience rides in infos so the save reflects THIS
                    # epoch's outcome and a resume restores it exactly.
                    with self._telemetry.phase("ckpt"):
                        self.ckpt.save(step + 1, self.state, score=metric,
                                       extra={"opt": vars(opt),
                                              "val_scores": scores,
                                              "patience": patience})
                    self._note_saved(step + 1)
                    self._snapshot_good_state(step + 1)
                    self._telemetry.flush(fsync=True)  # durable with state
                    self._watchdog.beat()  # orbax fetch+write completed
                    # min_epochs floors the STOP, not the patience count:
                    # epochs without improvement keep accumulating, but
                    # the run cannot end while val scores may still be in
                    # the early all-tie regime.
                    if (opt.max_patience and patience >= opt.max_patience
                            and (step + 1) // bpe >= opt.min_epochs):
                        log.info("early stop: no %s improvement in %d epochs",
                                 opt.eval_metric, patience)
                        step += 1  # count the completed step (the loop's
                        break      # own += 1 is skipped by the break)
                else:
                    with self._telemetry.phase("ckpt"):
                        self.ckpt.save(step + 1, self.state)
                    self._note_saved(step + 1)
                    self._snapshot_good_state(step + 1)
                    self._telemetry.flush(fsync=True)
            step += 1

        if opt.use_rl:
            drain_and_log()  # no-op unless the run ended mid-pipeline
        if self._guard is not None:
            self._guard.flush()  # surface any trailing skipped steps
            if self._guard.total_skipped:
                log.warning(
                    "divergence guard summary: %d step(s) skipped as "
                    "non-finite, %d rollback(s)",
                    self._guard.total_skipped, self._guard.rollbacks)
        if profiling:  # run ended inside the trace window
            jax.profiler.stop_trace()
        # The loop's own host counter is the step truth (== state.step on
        # a healthy stack) — summaries must not depend on a device scalar
        # fetch this environment can garble (RESILIENCE.md caveat).
        self._host_step = step
        return {
            "best_score": None if best == float("-inf") else best,
            "best_step": self.ckpt.best_step,
            "last_step": step,
            "history": self.history,
        }

    def close(self) -> None:
        try:
            # Telemetry first: the exit telemetry.json snapshot + sink
            # close (which closes the TB writer) must not be hostage to a
            # device-touching close below hanging on a dead transport.
            # Idempotent, so the still-registered atexit hook is a no-op.
            self._telemetry.close()
            try:
                atexit.unregister(self._telemetry.close)
            except Exception:
                pass
            if self._tb is not None:
                self._tb.close()  # already closed via the sink; tolerated
            # ckpt.close() joins orbax's async writer — a device fetch
            # that can block on a dead transport, so the watchdog must
            # outlive it (a false 124 here costs one cheap resume; a hang
            # costs the chain).
            self.ckpt.close()
            self.train_ds.close()
            if self.val_ds:
                self.val_ds.close()
        finally:
            # Always disarm, even if a close above raised — an embedded
            # caller that catches the error must not be os._exit'd by a
            # still-armed watchdog minutes later.  Same rule for a
            # Trainer-OWNED preemption handler: restore the process's
            # previous signal dispositions (train.py keeps its own handler
            # armed through its exit path).
            if self._preempt_owned and self._preempt is not None:
                self._preempt.uninstall()
            self._watchdog.stop()
