"""Jitted train-step factories: XE/WXE, RL rollout, RL gradient.

Each factory returns a *pure* function suitable for ``jax.jit`` or
``parallel.data_parallel_jit``.  The CST stage is deliberately two device
programs with a host gap between them (SURVEY.md §3.2, §7 hard part (a)):

    rollout (device) -> reward/advantage (host, strings) -> grad step (device)

The gradient step recomputes log p(sampled tokens) with the teacher-forced
``model.__call__`` instead of keeping the rollout graph alive — the
XLA-native SCST formulation (rollout runs as a fused no-grad scan).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from ..ops.losses import cross_entropy_loss, reward_loss, sequence_mask, token_logprobs
from ..ops.sampling import sample_captions, sample_with_baseline
from .state import TrainState


def _grad_norm(grads) -> jnp.ndarray:
    return optax.global_norm(grads)


def _apply_gradients_guarded(state: TrainState, grads, loss,
                             guard: bool):
    """Optimizer update with the divergence guard's device half folded in.

    ``guard=False`` is today's exact behavior.  With ``guard=True`` the
    step checks ``isfinite(loss) & isfinite(global_grad_norm)`` ON DEVICE
    and, when the check fails, masks the parameter AND optimizer-state
    update back to their pre-step values — the step becomes a counted
    no-op (``state.step`` still advances, keeping resume/log accounting
    monotonic) and ``metrics['bad_step']`` reports 1.0.  No host sync is
    added: the flag travels with the other metrics and the host guard
    (resilience/guard.py) fetches it with a lag.  On a good step the
    ``where`` selects the new leaves exactly, so guarded and unguarded
    trajectories are bit-identical.
    """
    gnorm = _grad_norm(grads)
    new_state = state.apply_gradients(grads=grads)
    metrics = {"loss": loss, "grad_norm": gnorm}
    if guard:
        ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)

        def sel(new, old):
            return jnp.where(ok, new, old)

        new_state = new_state.replace(
            params=jax.tree_util.tree_map(sel, new_state.params,
                                          state.params),
            opt_state=jax.tree_util.tree_map(sel, new_state.opt_state,
                                             state.opt_state),
        )
        metrics["bad_step"] = 1.0 - ok.astype(jnp.float32)
    return new_state, metrics


def make_xe_step(model, seq_per_img: int, guard: bool = False) -> Callable:
    """(state, feats, labels, weights, rng) -> (state, metrics).

    ``weights`` = per-caption consensus weights: all-ones reproduces plain
    XE; consensus softmax weights give the WXE stage.  One compiled step
    serves both stages (weights are data, not structure).  ``guard=True``
    folds the divergence guard's finite-check/skip into the program
    (``_apply_gradients_guarded``).
    """

    def step(state: TrainState, feats, labels, weights, rng):
        dropout_rng = jax.random.fold_in(rng, state.step)

        def loss_fn(params):
            logits = state.apply_fn(
                {"params": params}, feats, labels, seq_per_img,
                train=True, rngs={"dropout": dropout_rng},
            )
            return cross_entropy_loss(logits, labels, weights)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return _apply_gradients_guarded(state, grads, loss, guard)

    return step


def make_rollout(model, max_len: int, seq_per_img: int,
                 temperature: float = 1.0, greedy_baseline: bool = True,
                 decode_chunk: int = 0) -> Callable:
    """(params, feats, rng) -> (sampled (B*S, L), greedy (B, L)).

    One device program, ONE scan: the greedy baseline rows ride the same
    scan as the multinomial rollout rows (``sample_with_baseline``) — the
    per-step matmuls are too small to hide a second scan's sequential
    latency on TPU.  Pass ``greedy_baseline=False`` for pure-SCB runs to
    drop the baseline rows entirely (greedy output is then all-zeros).
    ``decode_chunk`` > 0 = early-exit chunked rollout (ops.sampling).
    """

    def rollout(params, feats, rng):
        variables = {"params": params}
        if greedy_baseline:
            sampled, _, greedy_toks = sample_with_baseline(
                model, variables, feats, rng, max_len,
                seq_per_img=seq_per_img, temperature=temperature,
                decode_chunk=decode_chunk,
            )
        else:
            sampled, _ = sample_captions(
                model, variables, feats, rng, max_len,
                seq_per_img=seq_per_img, greedy=False, temperature=temperature,
                decode_chunk=decode_chunk,
            )
            greedy_toks = jnp.zeros(
                (feats[0].shape[0], max_len), dtype=jnp.int32
            )
        return sampled, greedy_toks

    return rollout


def make_rollout_fused(model, max_len: int, seq_per_img: int,
                       temperature: float = 1.0,
                       greedy_baseline: bool = True,
                       decode_chunk: int = 0) -> Callable:
    """(params, feats, rng) -> (sampled (B*S, L), fetch).

    The overlapped CST pipeline's rollout: ``sampled`` stays on device for
    the later grad step; ``fetch`` is the ONE array the host pulls for
    reward scoring — ``concat([sampled, greedy])`` rows under the greedy
    baseline, just the sampled rows for SCB baselines.  A single fetch
    array means a single device->host transfer per step, which matters
    when the host link is high-latency (remote TPU tunnels pay a full
    round trip per transfer).
    """

    def rollout(params, feats, rng):
        variables = {"params": params}
        if greedy_baseline:
            sampled, _, greedy = sample_with_baseline(
                model, variables, feats, rng, max_len,
                seq_per_img=seq_per_img, temperature=temperature,
                decode_chunk=decode_chunk,
            )
            fetch = jnp.concatenate([sampled, greedy], axis=0)
        else:
            sampled, _ = sample_captions(
                model, variables, feats, rng, max_len,
                seq_per_img=seq_per_img, greedy=False, temperature=temperature,
                decode_chunk=decode_chunk,
            )
            fetch = sampled
        return sampled, fetch

    return rollout


def make_fused_cst_step(
    model,
    max_len: int,
    seq_per_img: int,
    corpus,                    # ops.jax_ciderd.CorpusTable (device)
    tables,                    # ops.jax_ciderd.RefTables (device)
    baseline: str = "greedy",
    temperature: float = 1.0,
    scb_gt_baseline=None,      # (V,) f32 per-video baseline for scb-gt
    ref_chunk: int | None = None,
    guard: bool = False,
    decode_chunk: int = 0,
) -> Callable:
    """(state, feats, video_ix, rng) -> (state, metrics): the ENTIRE CST
    iteration as ONE device program — rollout, on-device CIDEr-D rewards
    (ops/jax_ciderd.py), advantage, REINFORCE gradient, optimizer update.

    No host boundary, no device->host transfer, no pipeline staleness:
    this is the fully TPU-native form of the reference's
    rollout -> get_self_critical_reward -> RewardCriterion loop
    (SURVEY.md §3.2), enabled with --device_rewards.  ``video_ix`` is the
    batch's dataset video indices (Batch.video_ix), which index the
    reference tables directly.

    ``ref_chunk`` bounds the reward's transient HBM (see
    ops.jax_ciderd.auto_ref_chunk); scores agree to float32 ULP level
    either way (test-pinned).

    ``decode_chunk`` > 0 runs the rollout with early-exit chunking
    (ops.sampling) — bit-identical samples, fewer executed decode steps
    once the whole batch has terminated; the executed count is reported
    as ``metrics['rollout_steps']`` so the saving is visible per step in
    metrics.jsonl and the bench.
    """
    from ..ops.jax_ciderd import ciderd_scores

    if baseline == "scb-gt" and scb_gt_baseline is None:
        raise ValueError("scb-gt fused step needs the per-video baseline table")
    if baseline == "scb-sample" and seq_per_img < 2:
        # same guard as RewardComputer: /(S-1) would be a silent NaN on device
        raise ValueError("scb-sample baseline needs seq_per_img >= 2")

    def step(state: TrainState, feats, video_ix, rng):
        variables = {"params": state.params}
        if baseline == "greedy":
            sampled, _, greedy, rollout_steps = sample_with_baseline(
                model, variables, feats, rng, max_len,
                seq_per_img=seq_per_img, temperature=temperature,
                decode_chunk=decode_chunk, return_steps=True,
            )
        else:
            sampled, _, rollout_steps = sample_captions(
                model, variables, feats, rng, max_len,
                seq_per_img=seq_per_img, greedy=False, temperature=temperature,
                decode_chunk=decode_chunk, return_steps=True,
            )
            greedy = None
        sampled = jax.lax.stop_gradient(sampled)
        hyp_vix = jnp.repeat(video_ix, seq_per_img)
        r_sample = ciderd_scores(sampled, hyp_vix, corpus, tables,
                                 ref_chunk=ref_chunk)
        if baseline == "greedy":
            r_base = jnp.repeat(
                ciderd_scores(jax.lax.stop_gradient(greedy), video_ix,
                              corpus, tables, ref_chunk=ref_chunk),
                seq_per_img,
            )
        elif baseline == "scb-sample":
            per_vid = r_sample.reshape(-1, seq_per_img)
            loo = (per_vid.sum(axis=1, keepdims=True) - per_vid) \
                / (seq_per_img - 1)
            r_base = loo.reshape(-1)
        else:  # scb-gt
            r_base = jnp.repeat(scb_gt_baseline[video_ix], seq_per_img)
        advantage = (r_sample - r_base).astype(jnp.float32)

        def loss_fn(params):
            logits = state.apply_fn(
                {"params": params}, feats, sampled, seq_per_img,
                train=False,  # same no-dropout decision as make_rl_grad_step
            )
            logp = token_logprobs(logits, sampled)
            return reward_loss(logp, sampled, advantage)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        new_state, metrics = _apply_gradients_guarded(state, grads, loss,
                                                      guard)
        metrics.update({
            "sample_len": sequence_mask(sampled).sum(axis=1).mean(),
            "reward": r_sample.mean(),
            "baseline": r_base.mean(),
            "advantage": advantage.mean(),
            # decode steps the rollout actually executed (== max_len on
            # the legacy path; a chunk multiple under --decode_chunk once
            # the whole batch terminates early)
            "rollout_steps": rollout_steps.astype(jnp.float32),
        })
        return new_state, metrics

    return step


def make_rl_grad_step(model, seq_per_img: int, guard: bool = False) -> Callable:
    """(state, feats, sampled, advantage, rng) -> (state, metrics).

    REINFORCE gradient: recompute log-probs of the sampled sequences under
    the current params (teacher-forcing the samples), then
    ``reward_loss`` = -E[advantage * log p].  ``advantage`` (B*S,) comes
    from the host reward computation and is stop-gradiented inside the loss.

    The recompute runs ``train=False`` — NO dropout — so the policy whose
    log-probs are reinforced is exactly the policy that drew the samples
    (the rollout scan is deterministic-parameter sampling).  Recomputing
    under dropout would reinforce a different, randomly-thinned policy each
    step; decision + parity test in PARITY.md / tests/test_training.py
    (``rng`` stays in the signature for interface stability).
    """

    def step(state: TrainState, feats, sampled, advantage, rng):
        del rng  # see docstring: grad recompute is deterministic

        def loss_fn(params):
            logits = state.apply_fn(
                {"params": params}, feats, sampled, seq_per_img,
                train=False,
            )
            logp = token_logprobs(logits, sampled)
            return reward_loss(logp, sampled, advantage)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        new_state, metrics = _apply_gradients_guarded(state, grads, loss,
                                                      guard)
        metrics["sample_len"] = sequence_mask(sampled).sum(axis=1).mean()
        return new_state, metrics

    return step
