"""Host-side RL rewards: CIDEr-D advantage with greedy / SCB baselines.

This is the device->host->device boundary of the CST stage (SURVEY.md §3.2
and §7 hard part (a)): sampled token ids come off the device, are decoded to
strings, scored with corpus-df CIDEr-D, and return as a per-caption
advantage array.  Kept outside jit on purpose — deterministic, profilable,
and overlappable with the next rollout.

Baseline variants (the exact reference SCB formula is unverified —
SURVEY.md §7 hard part (d) — so all defensible readings are implemented and
flag-selectable):

- ``greedy``  — SCST: advantage = r(sample) - r(greedy decode of the same
  video), the north-star formulation [V in BASELINE.json].
- ``scb-sample`` — self-consensus over the rollout: baseline for sample i of
  a video is the leave-one-out mean reward of that video's other samples.
- ``scb-gt`` — consensus of the ground truth: baseline is the mean of the
  video's top-``scb_captions`` precomputed consensus scores (the
  ``--train_bcmrscores_pkl`` artifact powering WXE reused as a baseline).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("cst_captioning_tpu.rewards")

from ..data.vocab import Vocab
from ..metrics.ciderd import CiderD

BASELINES = ("greedy", "scb-sample", "scb-gt")


def decode_sequences(vocab: Vocab, tokens: np.ndarray) -> List[str]:
    """(N, L) 0-terminated id rows -> caption strings."""
    return vocab.decode_batch(np.asarray(tokens))


def scb_gt_value(scores, scb_captions: int) -> float:
    """Top-k mean of a video's precomputed consensus scores — the scb-gt
    baseline value (k = all when scb_captions <= 0).  Shared by the host
    RewardComputer and the fused device step's baseline table."""
    s = np.sort(np.asarray(scores, dtype=np.float64))[::-1]
    k = len(s) if scb_captions <= 0 else min(scb_captions, len(s))
    return float(s[:k].mean()) if k else 0.0


class RewardComputer:
    """Per-batch CIDEr-D rewards + advantage for the CST/REINFORCE stage."""

    def __init__(
        self,
        vocab: Vocab,
        scorer: CiderD,
        tokenized_refs: Mapping[str, Sequence[str]],
        seq_per_img: int,
        baseline: str = "greedy",
        consensus_scores: Optional[Mapping[str, np.ndarray]] = None,
        scb_captions: int = 0,
        telemetry=None,
    ):
        if baseline not in BASELINES:
            raise ValueError(f"baseline {baseline!r} not in {BASELINES}")
        if baseline == "scb-sample" and seq_per_img < 2:
            raise ValueError("scb-sample baseline needs seq_per_img >= 2")
        if baseline == "scb-gt" and consensus_scores is None:
            raise ValueError("scb-gt baseline needs precomputed consensus scores")
        self.vocab = vocab
        self.scorer = scorer
        # Optional telemetry.Telemetry: scoring is the CST stage's host
        # gap, so it gets the "score" step phase (and trace span) when
        # instrumentation is armed — None costs one is-None check/call.
        self._telemetry = telemetry
        # Native scorer (cst_captioning_tpu.native.NativeCiderD) consumes
        # token-id arrays directly — no id->string->split round trip.
        self._native = hasattr(scorer, "score_ids")
        self.refs = tokenized_refs
        self.seq_per_img = seq_per_img
        self.baseline = baseline
        self.scb_captions = scb_captions
        self._warned_missing_consensus = False
        self._scb_gt_cache: Dict[str, float] = {}
        if consensus_scores is not None:
            for vid, s in consensus_scores.items():
                self._scb_gt_cache[vid] = scb_gt_value(s, scb_captions)

    def _reward(self, video_ids: Sequence[str],
                token_rows: np.ndarray) -> np.ndarray:
        """(N, L) 0-terminated id rows -> per-row CIDEr-D, scorer-agnostic."""
        if self._native:
            return self.scorer.score_ids(video_ids, np.asarray(token_rows))
        return self._score(video_ids,
                           decode_sequences(self.vocab, token_rows))

    def _score(self, video_ids: Sequence[str], captions: List[str]) -> np.ndarray:
        """Score each caption row against its video's reference set."""
        per_vid = len(captions) // len(video_ids)
        gts = {}
        res = []
        for i, cap in enumerate(captions):
            vid = video_ids[i // per_vid]
            key = f"{i}"
            gts[key] = list(self.refs[vid])
            res.append({"image_id": key, "caption": [cap]})
        _, scores = self.scorer.compute_score(gts, res)
        return scores

    def __call__(
        self,
        video_ids: Sequence[str],
        sampled: np.ndarray,                 # (B*S, L) device->host token ids
        greedy: Optional[np.ndarray] = None, # (B, L), greedy baseline only
    ) -> Tuple[np.ndarray, Dict[str, float]]:
        """-> (advantage (B*S,) float32, stats for logging)."""
        tel = self._telemetry
        if tel is None:
            return self._compute(video_ids, sampled, greedy)
        with tel.phase("score"):
            return self._compute(video_ids, sampled, greedy)

    def _compute(
        self,
        video_ids: Sequence[str],
        sampled: np.ndarray,
        greedy: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, Dict[str, float]]:
        S = self.seq_per_img
        r_sample = self._reward(video_ids, sampled)

        if self.baseline == "greedy":
            if greedy is None:
                raise ValueError("greedy baseline requires greedy rollouts")
            baseline = np.repeat(self._reward(video_ids, greedy), S)
        elif self.baseline == "scb-sample":
            per_vid = r_sample.reshape(-1, S)
            loo = (per_vid.sum(axis=1, keepdims=True) - per_vid) / (S - 1)
            baseline = loo.reshape(-1)
        else:  # scb-gt
            missing = [v for v in video_ids if v not in self._scb_gt_cache]
            if missing and not self._warned_missing_consensus:
                # A mismatched consensus pickle would otherwise degrade
                # training invisibly (baseline 0 => inflated advantage).
                log.warning(
                    "scb-gt baseline: %d video(s) missing from the "
                    "consensus pickle (e.g. %s); their baseline falls back "
                    "to 0.0 — check --train_bcmrscores_pkl matches the "
                    "training split (warned once)",
                    len(missing), missing[:3],
                )
                self._warned_missing_consensus = True
            baseline = np.repeat(
                [self._scb_gt_cache.get(v, 0.0) for v in video_ids], S
            )

        advantage = (r_sample - baseline).astype(np.float32)
        stats = {
            "reward": float(r_sample.mean()),
            "baseline": float(np.mean(baseline)),
            "advantage": float(advantage.mean()),
        }
        return advantage, stats
