"""cstlint rule engine: sources, suppressions, registry, runner, output.

The engine is deliberately small and dependency-free (stdlib ``ast`` +
``tokenize``); jax is imported only by the donation-audit rule, and only
when tracing is enabled for the run.  Rules are registered by name and
checked against a :class:`Project` (every source file parsed once); each
raw finding then passes through the suppression layer:

- ``# cstlint: disable=<rule>[,<rule>...] -- <justification>`` suppresses
  the named rule(s) on the comment's own line (trailing comment) or on
  the next non-blank, non-comment line (standalone comment).
- The justification text after ``--`` is REQUIRED: a suppression without
  one is itself a violation (``suppression-format``) and does not apply.
- A suppression that no longer matches any raw finding of its rule is
  reported as ``stale-suppression`` (only for rules that actually ran,
  so a ``--rules`` subset can never mass-expire the others' receipts) —
  justified exceptions cannot rot silently.

Meta rules (``parse-error``, ``suppression-format``,
``stale-suppression``) are engine-owned and cannot be suppressed.
"""

from __future__ import annotations

import ast
import glob
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

#: Engine/format version stamped into the JSON report.
LINT_SCHEMA = 1

#: Rule registry: name -> Rule.  Populated by the @rule decorator at
#: import time (analysis.rules / analysis.donation).
RULES: Dict[str, "Rule"] = {}

#: Engine-owned finding kinds; never suppressible, always reported.
META_RULES = ("parse-error", "suppression-format", "stale-suppression")

_SUPPRESS_RE = re.compile(
    r"#\s*cstlint:\s*disable=([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)"
    r"\s*(?:--\s*(.*\S))?\s*$")


@dataclass(frozen=True)
class Violation:
    """One finding, anchored to a source line."""

    rule: str
    path: str          # repo-relative path (or the virtual path in tests)
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


@dataclass
class Suppression:
    """One parsed ``cstlint: disable`` comment."""

    rules: Tuple[str, ...]
    path: str
    comment_line: int      # line the comment sits on
    target_line: int       # line the suppression applies to
    justification: str     # "" when missing (-> suppression-format)
    used_rules: set = field(default_factory=set)


class Rule:
    """A registered check.  ``check(project)`` yields raw Violations;
    the engine applies suppressions afterwards."""

    def __init__(self, name: str, doc: str,
                 check: Callable[["Project"], Iterable[Violation]],
                 needs_trace: bool = False, category: str = "core"):
        self.name = name
        self.doc = doc
        self._check = check
        #: True for rules that trace/lower jax programs (donation-audit);
        #: skipped when the run disables tracing.
        self.needs_trace = needs_trace
        #: Reporting group ("core" | "concurrency"); `--list-rules` and
        #: the human summary line group by it.
        self.category = category

    def check(self, project: "Project") -> Iterable[Violation]:
        return self._check(project)


def rule(name: str, doc: str, needs_trace: bool = False,
         category: str = "core"):
    """Decorator registering a check function under ``name``."""
    if name in META_RULES:
        raise ValueError(f"{name!r} is reserved for the engine")

    def deco(fn):
        RULES[name] = Rule(name, doc, fn, needs_trace=needs_trace,
                           category=category)
        return fn

    return deco


class SourceFile:
    """One parsed source: AST + comments + suppression table."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: Optional[str] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"{e.msg} (line {e.lineno})"
        self.suppressions: List[Suppression] = self._scan_suppressions()

    @classmethod
    def from_path(cls, path: str, relpath: str) -> "SourceFile":
        with open(path, encoding="utf-8") as f:
            return cls(relpath, f.read())

    # -- suppression comments ---------------------------------------------

    def _scan_suppressions(self) -> List[Suppression]:
        out: List[Suppression] = []
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return out
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            names = tuple(r.strip() for r in m.group(1).split(","))
            line = tok.start[0]
            standalone = not self.lines[line - 1][:tok.start[1]].strip()
            target = self._next_code_line(line) if standalone else line
            out.append(Suppression(
                rules=names, path=self.relpath, comment_line=line,
                target_line=target, justification=m.group(2) or ""))
        return out

    def _next_code_line(self, after: int) -> int:
        """First non-blank, non-comment line after ``after`` (1-based);
        the line a standalone suppression comment governs."""
        for i in range(after, len(self.lines)):
            stripped = self.lines[i].strip()
            if stripped and not stripped.startswith("#"):
                return i + 1
        return after  # trailing comment at EOF: govern itself (no-op)

    def statement_span(self, line: int) -> Tuple[int, int]:
        """(first, last) physical line of the statement STARTING at
        ``line`` — a suppression governs the whole statement, so a
        multi-line call chain needs one comment, not one per line."""
        if self.tree is None:
            return line, line
        end = line
        for node in ast.walk(self.tree):
            if isinstance(node, ast.stmt) and node.lineno == line:
                # The outermost statement starting here wins (`if` arms
                # start at their test line, not here).
                body_start = min(
                    (s.lineno for s in ast.iter_child_nodes(node)
                     if isinstance(s, ast.stmt)), default=None)
                stop = node.end_lineno or line
                if body_start is not None and body_start > line:
                    stop = min(stop, body_start - 1)
                end = max(end, stop)
        return line, end


class Project:
    """Every source file of one lint run, plus run configuration."""

    def __init__(self, files: Sequence[SourceFile], root: str = "",
                 trace: bool = True):
        self.files = list(files)
        self.root = root
        self.trace = trace
        self.by_path = {f.relpath: f for f in self.files}

    def file(self, relpath: str) -> Optional[SourceFile]:
        return self.by_path.get(relpath)


@dataclass
class LintResult:
    """Outcome of one run.  ``violations`` includes the meta findings
    (stale/format/parse); ``clean`` is the ``make lint`` gate."""

    violations: List[Violation]
    suppressed: List[Tuple[Violation, Suppression]]
    rules_ran: List[str]
    files_scanned: int

    @property
    def clean(self) -> bool:
        return not self.violations

    def summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.violations:
            out[v.rule] = out.get(v.rule, 0) + 1
        return out


# -- tree discovery ----------------------------------------------------------

#: Directories walked relative to the repo root, plus top-level ``*.py``
#: entry points.  tests/ is deliberately out of scope (the seeded
#: violation corpus lives there), matching ISSUE 10's enforcement
#: surface: the package, the scripts, and the CLIs.
TREE_ROOTS = ("cst_captioning_tpu", "scripts")
_EXCLUDE_DIRS = ("__pycache__",)


def tree_files(root: str) -> List[str]:
    """Repo-relative paths of every linted source file under ``root``."""
    out: List[str] = []
    for sub in TREE_ROOTS:
        base = os.path.join(root, sub)
        for path in sorted(glob.glob(os.path.join(base, "**", "*.py"),
                                     recursive=True)):
            rel = os.path.relpath(path, root)
            if any(part in _EXCLUDE_DIRS for part in rel.split(os.sep)):
                continue
            out.append(rel)
    out.extend(sorted(
        os.path.relpath(p, root)
        for p in glob.glob(os.path.join(root, "*.py"))))
    return out


# -- the runner --------------------------------------------------------------

def _resolve_rules(names: Optional[Sequence[str]]) -> List[Rule]:
    if names is None:
        return [RULES[k] for k in sorted(RULES)]
    missing = [n for n in names if n not in RULES]
    if missing:
        raise KeyError(f"unknown rule(s): {', '.join(missing)}; "
                       f"known: {', '.join(sorted(RULES))}")
    return [RULES[n] for n in names]


def run_rules(project: Project,
              rules: Optional[Sequence[str]] = None) -> LintResult:
    """Check ``project``, apply suppressions, report stale ones."""
    selected = [r for r in _resolve_rules(rules)
                if project.trace or not r.needs_trace]
    ran = [r.name for r in selected]

    raw: List[Violation] = []
    for f in project.files:
        if f.parse_error is not None:
            raw.append(Violation("parse-error", f.relpath, 1, 0,
                                 f"cannot parse: {f.parse_error}"))
    for r in selected:
        raw.extend(r.check(project))

    # Index suppressions by (path, rule) -> [(span, suppression)]; a
    # suppression governs the whole statement at its target line, and is
    # pre-flagged when the grammar lacks the required justification.
    visible: List[Violation] = []
    suppressed: List[Tuple[Violation, Suppression]] = []
    sup_index: Dict[Tuple[str, str],
                    List[Tuple[Tuple[int, int], Suppression]]] = {}
    for f in project.files:
        for s in f.suppressions:
            if not s.justification:
                visible.append(Violation(
                    "suppression-format", s.path, s.comment_line, 0,
                    "suppression lacks a justification — write "
                    "'# cstlint: disable=<rule> -- <why this is safe>'"))
                continue  # an unjustified suppression does not apply
            span = f.statement_span(s.target_line)
            for name in s.rules:
                sup_index.setdefault((s.path, name), []).append((span, s))

    for v in raw:
        match = None
        if v.rule not in META_RULES:
            for (lo, hi), s in sup_index.get((v.path, v.rule), ()):
                if lo <= v.line <= hi:
                    match = s
                    break
        if match is not None:
            match.used_rules.add(v.rule)
            suppressed.append((v, match))
        else:
            visible.append(v)

    ran_set = set(ran)
    for f in project.files:
        for s in f.suppressions:
            if not s.justification:
                continue
            for name in s.rules:
                if name in ran_set and name not in s.used_rules:
                    visible.append(Violation(
                        "stale-suppression", s.path, s.comment_line, 0,
                        f"'{name}' no longer fires on line "
                        f"{s.target_line} — remove the suppression "
                        f"(justification was: {s.justification})"))

    visible.sort(key=lambda v: (v.path, v.line, v.rule))
    return LintResult(violations=visible, suppressed=suppressed,
                      rules_ran=ran, files_scanned=len(project.files))


def lint_sources(sources: Sequence[Tuple[str, str]],
                 rules: Optional[Sequence[str]] = None,
                 trace: bool = False) -> LintResult:
    """Lint in-memory (relpath, text) pairs — the corpus-test surface."""
    project = Project([SourceFile(rel, text) for rel, text in sources],
                      trace=trace)
    return run_rules(project, rules=rules)


def lint_tree(root: str, rules: Optional[Sequence[str]] = None,
              trace: bool = True,
              paths: Optional[Sequence[str]] = None) -> LintResult:
    """Lint the repo tree (or an explicit repo-relative ``paths`` list)."""
    rels = list(paths) if paths else tree_files(root)
    files = [SourceFile.from_path(os.path.join(root, rel), rel)
             for rel in rels]
    return run_rules(Project(files, root=root, trace=trace), rules=rules)


# -- output ------------------------------------------------------------------

def render_human(result: LintResult) -> str:
    lines = [v.render() for v in result.violations]
    counts = result.summary()
    if counts:
        # Group the per-rule summary by rule category (core vs the
        # concurrency contracts) so `make lint` reads as two audits.
        groups: Dict[str, List[str]] = {}
        for name, n in sorted(counts.items()):
            cat = RULES[name].category if name in RULES else "meta"
            groups.setdefault(cat, []).append(f"{name}={n}")
        per_rule = " | ".join(
            f"{cat}: " + ", ".join(parts)
            for cat, parts in sorted(groups.items()))
        lines.append(f"cstlint: {len(result.violations)} violation(s) "
                     f"[{per_rule}] in {result.files_scanned} file(s)")
    else:
        lines.append(
            f"cstlint: clean — {result.files_scanned} file(s), "
            f"{len(result.rules_ran)} rule(s), "
            f"{len(result.suppressed)} justified suppression(s)")
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    doc = {
        "schema": LINT_SCHEMA,
        "clean": result.clean,
        "files_scanned": result.files_scanned,
        "rules_ran": result.rules_ran,
        "summary": result.summary(),
        "violations": [vars(v) for v in result.violations],
        "suppressed": [
            {**vars(v), "justification": s.justification,
             "comment_line": s.comment_line}
            for v, s in result.suppressed
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True)
