"""Concurrency-contract rules: the threading model as analysis-time law.

The serving plane is a real multi-threaded system (reader threads ->
inbox -> single-owner scheduler, loader prefetch workers, the watchdog
thread, telemetry locks), and every thread-safety invariant PRs 1-9
earned the hard way lived only in prose: the PR 4 "plain bool, not
``threading.Event``, in a signal handler" rule, the inbox-owns-intake
discipline, monotonic-clock deadlines.  This module declares that model
in source annotations and enforces it with six AST rules
(catalogue + grammar: ANALYSIS.md "Concurrency contracts"):

Annotation grammar (trailing comments on attribute-declaration sites):

- ``# cstlint: guarded_by=<lock expr>`` — the attribute is shared state;
  every read/write outside its declaring function must sit lexically
  inside ``with <lock expr>:``.  Functions named ``*_locked`` are exempt
  by convention (their contract is "caller holds the lock").
- ``# cstlint: owned_by=<owner>`` — the attribute belongs to one thread
  (the scheduler loop, the controlling thread); functions spawned as
  ``threading.Thread(target=...)`` in the same file must not touch it.
- ``LOCK_ORDER = ("<name>", ...)`` — a module-level table of canonical
  lock names in allowed acquisition order (hold earlier while acquiring
  later).  Lock expressions resolve to canonical names through
  assignments from ``locksan.named_lock("<name>")``; the same table is
  registered at runtime via ``locksan.declare_order(*LOCK_ORDER)``, so
  the static and dynamic checks read ONE declaration.

The rules only consult same-file facts (plus the project-wide union of
LOCK_ORDER tables): Python gives the AST no types, so cross-file alias
analysis would be guesswork.  Where the heuristic over-fires, the call
site carries a justified suppression — the suppression text is the
documentation, exactly like the PR 10 rules.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import Project, SourceFile, Violation, rule

_ANNOT_RE = re.compile(
    r"#\s*cstlint:\s*(guarded_by|owned_by)=([A-Za-z_][\w.]*)")


def _dotted(node: ast.AST) -> str:
    """'self._lock' / 'threading.Thread' for Attribute/Name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# -- annotation parsing ------------------------------------------------------


class _Annotation:
    """One ``guarded_by``/``owned_by`` declaration, bound to the
    attribute (``self.X`` -> ``X`` with ``is_self``) or module global
    assigned on the annotated line."""

    __slots__ = ("kind", "arg", "attr", "is_self", "line", "func")

    def __init__(self, kind: str, arg: str, attr: str, is_self: bool,
                 line: int, func: Optional[ast.AST]):
        self.kind = kind
        self.arg = arg
        self.attr = attr
        self.is_self = is_self
        self.line = line
        #: The function owning the declaration site (usually __init__);
        #: accesses inside it are construction, exempt by definition.
        self.func = func


def _assign_target(stmt: ast.stmt) -> Optional[Tuple[str, bool]]:
    """(attr name, is_self) of a single-target Assign/AnnAssign."""
    if isinstance(stmt, ast.AnnAssign):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        targets = stmt.targets
    else:
        return None
    t = targets[0]
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return t.attr, True
    if isinstance(t, ast.Name):
        return t.id, False
    return None


def _enclosing_functions(tree: ast.AST) -> Dict[int, ast.AST]:
    """lineno -> innermost enclosing FunctionDef (None at module level),
    via a parent-aware walk."""
    owner: Dict[int, ast.AST] = {}

    def walk(node: ast.AST, fn: Optional[ast.AST]):
        for child in ast.iter_child_nodes(node):
            here = fn
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                here = child
            if hasattr(child, "lineno"):
                owner.setdefault(child.lineno, here)
            walk(child, here)

    walk(tree, None)
    return owner


def _annotation_state(f: SourceFile) -> Tuple[List[_Annotation],
                                              Dict[int, ast.AST]]:
    """(annotations, lineno -> enclosing-function map) for one file,
    memoized on the SourceFile — several rules consult it and the walks
    are whole-tree, so computing once per file per run matters."""
    cached = getattr(f, "_concurrency_state", None)
    if cached is not None:
        return cached
    if f.tree is None:
        f._concurrency_state = ([], {})
        return f._concurrency_state
    stmts_by_line: Dict[int, ast.stmt] = {}
    for node in ast.walk(f.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            stmts_by_line.setdefault(node.lineno, node)
    owner = _enclosing_functions(f.tree)
    out: List[_Annotation] = []
    for i, text in enumerate(f.lines, start=1):
        m = _ANNOT_RE.search(text)
        if m is None:
            continue
        stmt = stmts_by_line.get(i)
        tgt = _assign_target(stmt) if stmt is not None else None
        if tgt is None:
            continue  # annotation on a non-declaration line: inert
        out.append(_Annotation(m.group(1), m.group(2), tgt[0], tgt[1],
                               i, owner.get(i)))
    f._concurrency_state = (out, owner)
    return f._concurrency_state


def parse_annotations(f: SourceFile) -> List[_Annotation]:
    return _annotation_state(f)[0]


# -- named-lock resolution + LOCK_ORDER tables -------------------------------


def _named_lock_assignments(f: SourceFile) -> Dict[str, str]:
    """Map of lock-holding expression text ('self._lock' / '_LOCK') ->
    canonical sanitizer name, from ``X = [locksan.]named_lock("name")``
    assignments anywhere in the file."""
    out: Dict[str, str] = {}
    if f.tree is None:
        return out
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        v = node.value
        if not (isinstance(v, ast.Call) and
                _dotted(v.func).split(".")[-1] == "named_lock" and
                v.args and isinstance(v.args[0], ast.Constant) and
                isinstance(v.args[0].value, str)):
            continue
        expr = _dotted(node.targets[0])
        if expr:
            out[expr] = v.args[0].value
    return out


def _lock_order_table(f: SourceFile) -> Optional[Tuple[ast.Assign,
                                                       List[str]]]:
    """The module-level ``LOCK_ORDER = ("a", "b", ...)`` table, if any."""
    if f.tree is None:
        return None
    for node in f.tree.body if isinstance(f.tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "LOCK_ORDER" and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            names = [e.value for e in node.value.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)]
            return node, names
    return None


def _is_lock_expr(expr: str, named: Dict[str, str]) -> bool:
    """Is a ``with`` context expression a lock acquisition?  Canonical
    (assigned from named_lock) or name-hinted ('lock' in the last path
    component — matches this tree's _lock/_LOCK/_write_lock spellings)."""
    if expr in named:
        return True
    return "lock" in expr.split(".")[-1].lower()


# -- guarded-by --------------------------------------------------------------


class _GuardedVisitor(ast.NodeVisitor):
    """Track the lexical with-lock stack and flag annotated-attribute
    accesses outside their declared lock."""

    def __init__(self, f: SourceFile, annots: Sequence[_Annotation],
                 owner: Dict[int, ast.AST]):
        self.f = f
        self.owner = owner
        self.by_self = {a.attr: a for a in annots
                        if a.kind == "guarded_by" and a.is_self}
        self.by_global = {a.attr: a for a in annots
                          if a.kind == "guarded_by" and not a.is_self}
        self.with_stack: List[List[str]] = [[]]
        self.func_stack: List[ast.AST] = []
        self.hits: List[Violation] = []

    # Each function body starts with an EMPTY lock stack: a nested def
    # inside a `with` block runs later, on whatever thread calls it.
    def _func(self, node):
        self.func_stack.append(node)
        self.with_stack.append([])
        self.generic_visit(node)
        self.with_stack.pop()
        self.func_stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _func

    def visit_With(self, node: ast.With):
        held = [_dotted(item.context_expr) for item in node.items]
        self.with_stack[-1].extend(h for h in held if h)
        self.generic_visit(node)
        for h in held:
            if h:
                self.with_stack[-1].remove(h)

    visit_AsyncWith = visit_With

    def _check(self, annot: _Annotation, node: ast.AST, shown: str):
        if self.func_stack and annot.func is self.func_stack[-1]:
            return  # construction inside the declaring function
        if annot.func is None and not self.func_stack:
            return  # module-level construction (the declaration itself)
        if any(getattr(fn, "name", "").endswith("_locked")
               for fn in self.func_stack):
            return  # *_locked convention: caller holds the lock
        if annot.arg in self.with_stack[-1]:
            return
        self.hits.append(Violation(
            "guarded-by", self.f.relpath, node.lineno, node.col_offset,
            f"'{shown}' is declared guarded_by={annot.arg} "
            f"(line {annot.line}) but is touched outside a "
            f"'with {annot.arg}:' block — shared state races the "
            "moment one access skips the lock"))

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            annot = self.by_self.get(node.attr)
            if annot is not None:
                self._check(annot, node, f"self.{node.attr}")
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        annot = self.by_global.get(node.id)
        if annot is not None:
            self._check(annot, node, node.id)


@rule("guarded-by",
      "a '# cstlint: guarded_by=<lock>' attribute is only read/written "
      "inside 'with <lock>:' (functions named *_locked are exempt)",
      category="concurrency")
def check_guarded_by(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None:
            continue
        all_annots, owner = _annotation_state(f)
        annots = [a for a in all_annots if a.kind == "guarded_by"]
        if not annots:
            continue
        v = _GuardedVisitor(f, annots, owner)
        v.visit(f.tree)
        yield from v.hits


# -- thread-ownership --------------------------------------------------------


def _functions_by_name(tree: ast.AST) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _thread_calls(tree: ast.AST) -> List[ast.Call]:
    return [node for node in ast.walk(tree)
            if isinstance(node, ast.Call)
            and _dotted(node.func) in ("threading.Thread", "Thread")]


def _thread_target_functions(f: SourceFile) -> List[ast.AST]:
    """FunctionDefs passed as ``target=`` to same-file Thread() calls."""
    if f.tree is None:
        return []
    funcs = _functions_by_name(f.tree)
    out: List[ast.AST] = []
    for call in _thread_calls(f.tree):
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            name = _dotted(kw.value).split(".")[-1]
            fn = funcs.get(name)
            if fn is not None and fn not in out:
                out.append(fn)
    return out


def _own_body_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body EXCLUDING nested function bodies: a closure
    defined inside a thread target may legally run on another thread
    (the server's per-connection ``respond`` executes on the scheduler)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


@rule("thread-ownership",
      "a '# cstlint: owned_by=<owner>' attribute is never touched from "
      "functions spawned as threading.Thread(target=...) in the file",
      category="concurrency")
def check_thread_ownership(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None:
            continue
        owned = [a for a in parse_annotations(f) if a.kind == "owned_by"]
        if not owned:
            continue
        targets = _thread_target_functions(f)
        for fn in targets:
            for node in _own_body_nodes(fn):
                for a in owned:
                    if a.is_self:
                        hit = (isinstance(node, ast.Attribute)
                               and node.attr == a.attr
                               and isinstance(node.value, ast.Name)
                               and node.value.id == "self")
                        shown = f"self.{a.attr}"
                    else:
                        hit = (isinstance(node, ast.Name)
                               and node.id == a.attr)
                        shown = a.attr
                    if hit:
                        yield Violation(
                            "thread-ownership", f.relpath, node.lineno,
                            node.col_offset,
                            f"'{shown}' is declared owned_by={a.arg} "
                            f"(line {a.line}) but thread target "
                            f"'{getattr(fn, 'name', '?')}' touches it — "
                            "reader threads hand work to the owner "
                            "(inbox discipline), they never reach into "
                            "its state")


# -- lock-order --------------------------------------------------------------


class _WithEdgeVisitor(ast.NodeVisitor):
    """Lexically nested lock acquisitions -> (outer, inner, node) edges,
    with expressions resolved to canonical names where possible."""

    def __init__(self, f: SourceFile, named: Dict[str, str]):
        self.f = f
        self.named = named
        self.stack: List[List[Tuple[str, bool]]] = [[]]
        self.edges: List[Tuple[str, bool, str, bool, ast.AST]] = []

    def _func(self, node):
        self.stack.append([])
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _func

    def _resolve(self, expr: str) -> Tuple[str, bool]:
        if expr in self.named:
            return self.named[expr], True
        return expr, False

    def visit_With(self, node: ast.With):
        acquired: List[Tuple[str, bool]] = []
        for item in node.items:
            expr = _dotted(item.context_expr)
            if expr and _is_lock_expr(expr, self.named):
                resolved = self._resolve(expr)
                for outer, outer_canon in self.stack[-1]:
                    self.edges.append((outer, outer_canon,
                                       resolved[0], resolved[1], node))
                acquired.append(resolved)
                self.stack[-1].append(resolved)
        self.generic_visit(node)
        for r in acquired:
            self.stack[-1].remove(r)

    visit_AsyncWith = visit_With


def _declared_graph(project: Project) -> Tuple[Set[Tuple[str, str]],
                                               Dict[str, int]]:
    """Union of every module's LOCK_ORDER table -> declared edge set +
    a name -> declaring-line map for diagnostics."""
    edges: Set[Tuple[str, str]] = set()
    where: Dict[str, int] = {}
    for f in project.files:
        table = _lock_order_table(f)
        if table is None:
            continue
        node, names = table
        for name in names:
            where.setdefault(name, node.lineno)
        for i in range(len(names)):
            for j in range(i + 1, len(names)):
                edges.add((names[i], names[j]))
    return edges, where


# One reachability definition for both analyses: the runtime sanitizer
# and this rule must agree on what "declared before" means.
from ..utils.locksan import path_exists as _path_exists  # noqa: E402


def _has_path(edges: Set[Tuple[str, str]], src: str, dst: str) -> bool:
    if src == dst:
        return False  # a lock nested under itself is not "declared"
    return _path_exists(edges, src, dst)


@rule("lock-order",
      "lexically nested lock acquisitions embed into the declared "
      "LOCK_ORDER partial order (canonical names via locksan.named_lock); "
      "inversions, undeclared pairs, and cycles are violations",
      category="concurrency")
def check_lock_order(project: Project) -> Iterator[Violation]:
    declared, _ = _declared_graph(project)
    observed: List[Tuple[str, str, str, ast.AST]] = []  # (path, a, b, node)
    for f in project.files:
        if f.tree is None:
            continue
        v = _WithEdgeVisitor(f, _named_lock_assignments(f))
        v.visit(f.tree)
        for outer, outer_canon, inner, inner_canon, node in v.edges:
            if not (outer_canon and inner_canon):
                yield Violation(
                    "lock-order", f.relpath, node.lineno, node.col_offset,
                    f"nested acquisition '{outer}' -> '{inner}' uses "
                    "unnamed locks — create them via "
                    "locksan.named_lock(...) and declare the pair in a "
                    "LOCK_ORDER table so both analyses can check it")
                continue
            if _has_path(declared, inner, outer):
                yield Violation(
                    "lock-order", f.relpath, node.lineno, node.col_offset,
                    f"acquiring '{inner}' while holding '{outer}' "
                    "INVERTS the declared LOCK_ORDER "
                    f"('{inner}' is declared before '{outer}')")
            elif not _has_path(declared, outer, inner):
                yield Violation(
                    "lock-order", f.relpath, node.lineno, node.col_offset,
                    f"nested acquisition '{outer}' -> '{inner}' is not "
                    "covered by any LOCK_ORDER table — declare it or "
                    "break the nesting")
            else:
                observed.append((f.relpath, outer, inner, node))
    # Cycle check over declared + observed edges: a mis-declared table
    # (or two tables that disagree) must fail even with no inversion at
    # a single site.
    graph = set(declared)
    graph.update((a, b) for _, a, b, _ in observed)
    for path, a, b, node in observed:
        if _has_path(graph - {(a, b)}, b, a):
            yield Violation(
                "lock-order", path, node.lineno, node.col_offset,
                f"acquisition edge '{a}' -> '{b}' closes a cycle in the "
                "combined declared+observed lock graph — the declared "
                "order and the code disagree somewhere on this loop")


# -- signal-safe-handler -----------------------------------------------------

#: Calls that are not async-signal-safe(-ish): anything taking a lock the
#: interrupted thread may hold (logging, print's stdout lock, Event/Lock
#: ops, queues) or allocating heavily.  The shipped handler
#: (resilience/preemption.py) uses a plain-bool flag + os.write instead.
_UNSAFE_METHODS = frozenset(
    {"acquire", "wait", "notify", "notify_all", "set", "clear", "put",
     "debug", "info", "warning", "error", "critical", "exception", "log"})
_UNSAFE_PREFIXES = ("logging.", "threading.", "queue.")
_UNSAFE_NAMES = frozenset({"print"})


def _called_names(fn: ast.AST) -> Iterator[str]:
    """Same-file callables a function invokes: bare names and self.X."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d.startswith("self."):
                yield d[len("self."):]
            elif d and "." not in d:
                yield d


def _signal_handlers(f: SourceFile) -> List[Tuple[ast.AST, ast.Call]]:
    """(handler function/lambda, registering call) for every same-file
    ``signal.signal(sig, handler)`` site."""
    if f.tree is None:
        return []
    funcs = _functions_by_name(f.tree)
    out: List[Tuple[ast.AST, ast.Call]] = []
    for node in ast.walk(f.tree):
        if not (isinstance(node, ast.Call)
                and _dotted(node.func) == "signal.signal"
                and len(node.args) >= 2):
            continue
        h = node.args[1]
        if isinstance(h, ast.Lambda):
            out.append((h, node))
            continue
        name = _dotted(h).split(".")[-1]
        fn = funcs.get(name)
        if fn is not None:
            out.append((fn, node))
    return out


@rule("signal-safe-handler",
      "functions reachable from a signal.signal handler stay "
      "async-signal-safe: no Event/Lock ops, no logging/print/queue "
      "calls (flag + os.write only — the PR 4 preemption invariant)",
      category="concurrency")
def check_signal_safe_handler(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None:
            continue
        handlers = _signal_handlers(f)
        if not handlers:
            continue
        funcs = _functions_by_name(f.tree)
        for handler, _reg in handlers:
            # Reachability closure over same-file calls (bare names and
            # self.<method>), handler included.
            reach: List[ast.AST] = [handler]
            seen: Set[int] = {id(handler)}
            frontier = [handler]
            while frontier:
                fn = frontier.pop()
                for name in _called_names(fn):
                    callee = funcs.get(name)
                    if callee is not None and id(callee) not in seen:
                        seen.add(id(callee))
                        reach.append(callee)
                        frontier.append(callee)
            hname = getattr(handler, "name", "<lambda>")
            for fn in reach:
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    d = _dotted(node.func)
                    bad = None
                    if d in _UNSAFE_NAMES:
                        bad = f"{d}() takes the interpreter's I/O lock"
                    elif any(d.startswith(p) for p in _UNSAFE_PREFIXES):
                        bad = f"{d}() allocates/locks"
                    elif isinstance(node.func, ast.Attribute) and \
                            node.func.attr in _UNSAFE_METHODS:
                        bad = (f".{node.func.attr}() may take a "
                               "non-reentrant lock the interrupted "
                               "thread already holds")
                    if bad is not None:
                        yield Violation(
                            "signal-safe-handler", f.relpath,
                            node.lineno, node.col_offset,
                            f"{bad} — reachable from signal handler "
                            f"'{hname}'; a nested signal at the next "
                            "bytecode boundary deadlocks the process "
                            "(resilience/preemption.py:67 rationale: "
                            "plain-bool flag + os.write only)")


# -- thread-discipline -------------------------------------------------------


def _is_thread_join(n: ast.AST) -> bool:
    """A THREAD join, not str.join: Thread.join takes no args, a bare
    numeric timeout, or timeout= — str.join always passes an iterable,
    so requiring numeric/absent arguments keeps 'there is a reap site'
    from being satisfied by a ', '.join(...) somewhere in the file."""
    if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "join"):
        return False
    if len(n.args) > 1:
        return False
    if n.args and not (isinstance(n.args[0], ast.Constant)
                       and isinstance(n.args[0].value, (int, float))
                       and not isinstance(n.args[0].value, bool)):
        return False
    return all(kw.arg == "timeout" for kw in n.keywords)


@rule("thread-discipline",
      "every threading.Thread(...) states name= and daemon=; a "
      "daemon=False thread needs a reachable .join() in the file",
      category="concurrency")
def check_thread_discipline(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None:
            continue
        has_join = any(_is_thread_join(n) for n in ast.walk(f.tree))
        for call in _thread_calls(f.tree):
            kwargs = {kw.arg: kw.value for kw in call.keywords
                      if kw.arg is not None}
            if "name" not in kwargs:
                yield Violation(
                    "thread-discipline", f.relpath, call.lineno,
                    call.col_offset,
                    "threading.Thread(...) without name= — anonymous "
                    "threads are unattributable in trace viewers, "
                    "heartbeats, and sanitizer receipts")
            if "daemon" not in kwargs:
                yield Violation(
                    "thread-discipline", f.relpath, call.lineno,
                    call.col_offset,
                    "threading.Thread(...) without an explicit daemon= — "
                    "state whether process exit may abandon this thread")
            else:
                d = kwargs["daemon"]
                if isinstance(d, ast.Constant) and d.value is False \
                        and not has_join:
                    yield Violation(
                        "thread-discipline", f.relpath, call.lineno,
                        call.col_offset,
                        "daemon=False thread with no .join() anywhere in "
                        "the file — a non-daemon thread that is never "
                        "reaped blocks interpreter shutdown")


# -- monotonic-deadline ------------------------------------------------------


def _walltime_calls(node: ast.AST) -> List[ast.Call]:
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Call) and _dotted(n.func) == "time.time"]


@rule("monotonic-deadline",
      "deadline/timeout arithmetic and comparisons use time.monotonic(), "
      "never time.time() (wall clock steps under NTP; bare timestamp "
      "reads are fine)",
      category="concurrency")
def check_monotonic_deadline(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None:
            continue
        flagged: Set[Tuple[int, int]] = set()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, (ast.Add, ast.Sub)):
                hits = _walltime_calls(node.left) + \
                    _walltime_calls(node.right)
            elif isinstance(node, ast.Compare):
                hits = _walltime_calls(node.left)
                for cmp in node.comparators:
                    hits.extend(_walltime_calls(cmp))
            else:
                continue
            for call in hits:
                key = (call.lineno, call.col_offset)
                if key in flagged:
                    continue
                flagged.add(key)
                yield Violation(
                    "monotonic-deadline", f.relpath, call.lineno,
                    call.col_offset,
                    "time.time() in deadline/duration arithmetic — an "
                    "NTP step or operator clock change corrupts the "
                    "wait; use time.monotonic() (serving/engine.py's "
                    "clock).  Wall-clock TIMESTAMPS (log records, "
                    "snapshots) are exempt because they do no "
                    "arithmetic")
