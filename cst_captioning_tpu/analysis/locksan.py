"""Runtime lock sanitizer — analysis-side façade.

The implementation lives in ``cst_captioning_tpu/utils/locksan.py`` so
that runtime modules creating locks (telemetry, serving, native) depend
only on a stdlib-only leaf module and never pull the lint engine into a
serving process's import graph.  This module re-exports the full surface
under the analysis package, where the concurrency rules (ANALYSIS.md
"Concurrency contracts") document it: the ``lock-order`` rule resolves
lock expressions through ``named_lock`` assignments and reads the same
``LOCK_ORDER`` tables that ``declare_order`` registers at runtime.
"""

from ..utils.locksan import (  # noqa: F401
    DEFAULT_RECEIPT,
    ENV_FLAG,
    ENV_RECEIPT,
    LOCKSAN_SCHEMA,
    LockOrderViolation,
    declare_order,
    enabled,
    named_lock,
    reset_observed,
    violations,
)
