"""The shipped AST rules — each one a RESILIENCE.md/SERVING.md invariant
distilled from PRs 1-9 (catalogue + rationale: ANALYSIS.md).

All five are static heuristics, tuned against this tree: where the AST
cannot prove a value is host-side (Python has no types here), the rule
errs toward flagging inside the configured hot paths and the call site
carries a justified suppression instead — the suppression text IS the
documentation the old hand-audits never left behind.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Set, Tuple

from .engine import Project, SourceFile, Violation, rule

# ---------------------------------------------------------------------------
# device-scalar-fetch
# ---------------------------------------------------------------------------

#: Hot-path files/dirs where a per-iteration device scalar fetch is the
#: exact pattern this environment's native stack nondeterministically
#: garbles to 0.0 (RESILIENCE.md caveat; PR 3 moved the trainer's control
#: plane to host-side integers, PR 8 batched dryrun's fetches).
HOT_PATHS = (
    "cst_captioning_tpu/training/trainer.py",
    "cst_captioning_tpu/training/pipeline.py",
    "cst_captioning_tpu/training/rewards.py",
    "cst_captioning_tpu/serving/engine.py",
    "cst_captioning_tpu/serving/server.py",
    "cst_captioning_tpu/serving/fleet.py",
    # The process-fleet supervisor (ISSUE 16): its tick loop pumps every
    # child socket and its reader/requeue/health threads must declare
    # their locks — a missed guard here corrupts requeue bookkeeping.
    "cst_captioning_tpu/serving/supervisor.py",
    "cst_captioning_tpu/telemetry/lifecycle.py",
    # The fleet observability plane (ISSUE 17): its scraper runs on the
    # supervisor's tick thread while reports read the sample ring from
    # outside — the ring lock and the tick-thread ownership of the
    # scrape/file state must stay declared.
    "cst_captioning_tpu/telemetry/fleetobs.py",
    "cst_captioning_tpu/parallel/",
    # The sharded multi-worker data plane (ISSUE 15): the prefetch loop
    # is a per-batch hot path, and its worker threads must obey the
    # concurrency contracts from day one.
    "cst_captioning_tpu/data/loader.py",
    "cst_captioning_tpu/data/sharding.py",
    # The autoscaler (ISSUE 19): rides the supervisor's tick thread and
    # shares its decision state with brownout checks on the submit
    # path — its state lock must stay declared, and it must never grow
    # a per-tick device fetch.
    "cst_captioning_tpu/serving/autoscale.py",
    # The intake journal (ISSUE 20): its append sits on the accept path
    # of every request (fsync-before-placement), and its high-water /
    # counter state is read off-thread by the exit snapshot — the state
    # lock and the scheduler's ownership of the maps must stay declared.
    "cst_captioning_tpu/serving/journal.py",
)

#: Conversions that force a device->host sync when applied to a jax
#: array.  ``.item()`` and ``jax.device_get`` are always fetches;
#: float/int/np.asarray only when their argument isn't provably host.
_FETCH_NAMES = {"float", "int"}


def _is_hot(relpath: str) -> bool:
    return any(relpath == p or (p.endswith("/") and relpath.startswith(p))
               for p in HOT_PATHS)


def _dotted(node: ast.AST) -> str:
    """'np.asarray' for Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _host_safe(node: ast.AST) -> bool:
    """Conservatively true when the expression cannot be a jax array:
    literals, len()/range()/time.* results, ``.shape`` lookups, and
    arithmetic/comparisons built from those."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name in ("len", "range", "ord", "str", "repr", "id") or \
                name.startswith("time.") or name.startswith("os."):
            return True
        return False
    if isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim",
                                                         "size", "dtype"):
        return True
    if isinstance(node, ast.Subscript):
        return _host_safe(node.value)
    if isinstance(node, ast.BinOp):
        return _host_safe(node.left) and _host_safe(node.right)
    if isinstance(node, ast.UnaryOp):
        return _host_safe(node.operand)
    if isinstance(node, ast.BoolOp):
        return all(_host_safe(v) for v in node.values)
    return False


class _LoopFetchVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.depth = 0
        self.hits: List[Violation] = []

    def _loop(self, node):
        self.depth += 1
        self.generic_visit(node)
        self.depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop

    def visit_Call(self, node: ast.Call):
        if self.depth > 0:
            name = _dotted(node.func)
            msg = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                msg = ".item() fetches a device scalar"
            elif name in _FETCH_NAMES and len(node.args) == 1 and \
                    not _host_safe(node.args[0]):
                msg = f"{name}() on a possibly-device value forces a sync"
            elif name in ("np.asarray", "numpy.asarray", "onp.asarray") \
                    and node.args and not _host_safe(node.args[0]):
                msg = f"{name}() on a possibly-device value forces a copy"
            elif name in ("jax.device_get", "jax.block_until_ready"):
                msg = f"{name}() inside a loop body"
            if msg is not None:
                self.hits.append(Violation(
                    "device-scalar-fetch", self.relpath, node.lineno,
                    node.col_offset,
                    msg + " inside a hot-path loop — keep values on "
                    "device and batch one fetch after the loop (the "
                    "native stack garbles per-step scalar fetches; "
                    "RESILIENCE.md caveat)"))
        self.generic_visit(node)


@rule("device-scalar-fetch",
      "no per-iteration device scalar fetches (float/int/.item()/"
      "np.asarray/device_get) in trainer/engine/parallel hot-path loops")
def check_device_scalar_fetch(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None or not _is_hot(f.relpath):
            continue
        v = _LoopFetchVisitor(f.relpath)
        v.visit(f.tree)
        yield from v.hits


# ---------------------------------------------------------------------------
# atomic-write
# ---------------------------------------------------------------------------

#: The one module allowed to spell the raw write (it IS the discipline).
_ATOMIC_HOME = "cst_captioning_tpu/resilience/integrity.py"


def _json_path_expr(node: ast.AST) -> bool:
    """Does this expression syntactically look like a *.json/*.jsonl
    path?  Literal suffixes, f-string tails, os.path.join tails, and
    name hints ('...json...') — heuristic by design; a false negative
    is caught when the write grows a literal suffix."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.endswith((".json", ".jsonl"))
    if isinstance(node, ast.JoinedStr) and node.values:
        last = node.values[-1]
        return isinstance(last, ast.Constant) and \
            isinstance(last.value, str) and \
            last.value.endswith((".json", ".jsonl"))
    if isinstance(node, ast.Call) and \
            _dotted(node.func) in ("os.path.join", "posixpath.join") and \
            node.args:
        return _json_path_expr(node.args[-1])
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _json_path_expr(node.right)
    if isinstance(node, (ast.Name, ast.Attribute)):
        tail = node.id if isinstance(node, ast.Name) else node.attr
        return "json" in tail.lower()
    return False


def _open_mode(node: ast.Call) -> Optional[ast.AST]:
    """The mode expression of an ``open()`` call — positional arg 1 or
    the ``mode=`` keyword (both spellings must be caught)."""
    if len(node.args) >= 2:
        return node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            return kw.value
    return None


def _is_text_write_mode(mode: Optional[ast.AST]) -> bool:
    return (isinstance(mode, ast.Constant) and
            isinstance(mode.value, str) and
            "w" in mode.value and "b" not in mode.value)


@rule("atomic-write",
      "durable *.json/*.jsonl writes must go through "
      "integrity.atomic_json_write (fsync'd tmp + rename + dir fsync)")
def check_atomic_write(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None or f.relpath == _ATOMIC_HOME:
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name == "json.dump":
                yield Violation(
                    "atomic-write", f.relpath, node.lineno,
                    node.col_offset,
                    "json.dump to a raw handle can be torn by a crash — "
                    "route durable JSON through "
                    "resilience.integrity.atomic_json_write")
            elif name == "open" and node.args and \
                    _is_text_write_mode(_open_mode(node)) and \
                    _json_path_expr(node.args[0]):
                yield Violation(
                    "atomic-write", f.relpath, node.lineno,
                    node.col_offset,
                    "open(<*.json path>, 'w') bypasses the atomic-write "
                    "discipline — use "
                    "resilience.integrity.atomic_json_write")


# ---------------------------------------------------------------------------
# journal-append
# ---------------------------------------------------------------------------

#: The one module allowed to open a write-ahead segment for writing —
#: its ``_append`` is the single fsync'd frame-stamp-crc path every
#: journal record must take (SERVING.md "Durable intake journal").
_JOURNAL_HOME = "cst_captioning_tpu/serving/journal.py"


def _wal_path_expr(node: ast.AST) -> bool:
    """Does this expression syntactically look like a journal segment
    path?  Literal ``*.wal`` suffixes, f-string tails, os.path.join
    tails, and name hints ('...wal...'/'...journal...') — the same
    heuristic shape as :func:`_json_path_expr`."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.endswith(".wal")
    if isinstance(node, ast.JoinedStr) and node.values:
        last = node.values[-1]
        return isinstance(last, ast.Constant) and \
            isinstance(last.value, str) and last.value.endswith(".wal")
    if isinstance(node, ast.Call) and \
            _dotted(node.func) in ("os.path.join", "posixpath.join") and \
            node.args:
        return _wal_path_expr(node.args[-1])
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _wal_path_expr(node.right)
    if isinstance(node, (ast.Name, ast.Attribute)):
        tail = node.id if isinstance(node, ast.Name) else node.attr
        tail = tail.lower()
        return "wal" in tail or "journal" in tail
    return False


def _is_mutating_mode(mode: Optional[ast.AST]) -> bool:
    return (isinstance(mode, ast.Constant) and
            isinstance(mode.value, str) and
            ("w" in mode.value or "a" in mode.value or
             "+" in mode.value))


@rule("journal-append",
      "write-ahead segments (*.wal) are written ONLY by serving/"
      "journal.py's fsync'd append helper — a raw open elsewhere can "
      "tear the exactly-once record")
def check_journal_append(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None or f.relpath == _JOURNAL_HOME:
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call) and \
                    _dotted(node.func) == "open" and node.args and \
                    _is_mutating_mode(_open_mode(node)) and \
                    _wal_path_expr(node.args[0]):
                yield Violation(
                    "journal-append", f.relpath, node.lineno,
                    node.col_offset,
                    "open(<*.wal path>) for writing outside the journal "
                    "module — every journal record must take "
                    "IntakeJournal's one fsync'd append path (frame + "
                    "schema stamp + crc), or replay after a crash will "
                    "see bytes the supervisor never acknowledged")


# ---------------------------------------------------------------------------
# declared-counters
# ---------------------------------------------------------------------------

def _counter_sites(f: SourceFile):
    """-> (declared names, [(inc name, lineno, col)]) for one file."""
    declared: Set[str] = set()
    incs: List[Tuple[str, int, int]] = []
    if f.tree is None:
        return declared, incs
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Assign):
            # A *COUNTERS*-named table of string literals IS a declare
            # site (engine.COUNTERS is splat into registry.declare at
            # attach time; the SERVING.md doc table is pinned to it), so
            # `declare(*COUNTERS)` needs no separate starred resolution.
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and "COUNTERS" in tgt.id and \
                        isinstance(node.value, (ast.Tuple, ast.List)):
                    declared.update(e.value for e in node.value.elts
                                    if isinstance(e, ast.Constant)
                                    and isinstance(e.value, str))
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if attr == "declare":
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    declared.add(a.value)
        elif attr in ("inc", "_inc") and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            incs.append((node.args[0].value, node.lineno, node.col_offset))
    return declared, incs


@rule("declared-counters",
      "every literal counter increment has a declare-at-0 site "
      "(registry.declare / a COUNTERS table) somewhere in the tree")
def check_declared_counters(project: Project) -> Iterator[Violation]:
    declared: Set[str] = set()
    per_file = []
    for f in project.files:
        d, incs = _counter_sites(f)
        declared |= d
        per_file.append((f, incs))
    for f, incs in per_file:
        for name, line, col in incs:
            if name not in declared:
                yield Violation(
                    "declared-counters", f.relpath, line, col,
                    f"counter '{name}' is incremented but never declared "
                    "at 0 — add it to the owner's registry.declare()/"
                    "COUNTERS table so snapshots distinguish 'armed, "
                    "nothing happened' from 'feature absent'")


# ---------------------------------------------------------------------------
# exit-taxonomy
# ---------------------------------------------------------------------------

_EXIT_HOME = "cst_captioning_tpu/resilience/exitcodes.py"


def _int_literal(node: Optional[ast.AST]) -> bool:
    """True for int literals including the negative spelling
    ``sys.exit(-1)`` (ast.UnaryOp(USub) around the Constant)."""
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.USub, ast.UAdd)):
        node = node.operand
    return (isinstance(node, ast.Constant) and
            isinstance(node.value, int) and
            not isinstance(node.value, bool))


@rule("exit-taxonomy",
      "process exits spell a resilience.exitcodes constant, never a "
      "bare int literal (and never a string: that exits 1 untyped)")
def check_exit_taxonomy(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None or f.relpath == _EXIT_HOME:
            continue
        for node in ast.walk(f.tree):
            arg = None
            if isinstance(node, ast.Call) and \
                    _dotted(node.func) in ("sys.exit", "exit", "os._exit"):
                arg = node.args[0] if node.args else None
            elif isinstance(node, ast.Raise) and \
                    isinstance(node.exc, ast.Call) and \
                    _dotted(node.exc.func) == "SystemExit":
                arg = node.exc.args[0] if node.exc.args else None
            else:
                continue
            if _int_literal(arg):
                yield Violation(
                    "exit-taxonomy", f.relpath, node.lineno,
                    node.col_offset,
                    "exit with a bare int literal — name it via "
                    "resilience.exitcodes (EXIT_*) so "
                    "scale_chain.classify() can route the death")
            elif isinstance(arg, ast.JoinedStr) or (
                    isinstance(arg, ast.Constant) and
                    isinstance(arg.value, str)):
                yield Violation(
                    "exit-taxonomy", f.relpath, node.lineno,
                    node.col_offset,
                    "sys.exit(<string>) exits 1 with the message on "
                    "stderr, bypassing the taxonomy — use parser.error() "
                    "(usage, EXIT_USAGE) or print + an EXIT_* constant")
            elif isinstance(arg, ast.IfExp) and any(
                    _int_literal(b) for b in (arg.body, arg.orelse)):
                yield Violation(
                    "exit-taxonomy", f.relpath, node.lineno,
                    node.col_offset,
                    "exit with conditional int literals — name both "
                    "branches via resilience.exitcodes (EXIT_*)")


# ---------------------------------------------------------------------------
# bare-except-swallow
# ---------------------------------------------------------------------------

#: Failure-domain code where a silently swallowed exception is itself a
#: fault: one bad line/chunk must be COUNTED (PR 9's serving contract).
_SWALLOW_SCOPE = ("cst_captioning_tpu/serving/",
                  "cst_captioning_tpu/resilience/")


def _broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    return any(isinstance(n, ast.Name) and
               n.id in ("Exception", "BaseException") for n in names)


def _body_accounts(handler: ast.ExceptHandler) -> bool:
    """True when the handler does ANYTHING observable — a log call, a
    counter increment, a re-raise, an assignment.  Only a body that is
    entirely pass/docstring swallows silently."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue
        return True
    return False


@rule("bare-except-swallow",
      "serving/resilience code may not swallow Exception silently — "
      "count it or log it (one bad line must be visible, PR 9)")
def check_bare_except_swallow(project: Project) -> Iterator[Violation]:
    for f in project.files:
        if f.tree is None or \
                not any(f.relpath.startswith(p) for p in _SWALLOW_SCOPE):
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ExceptHandler) and \
                    _broad_handler(node) and not _body_accounts(node):
                yield Violation(
                    "bare-except-swallow", f.relpath, node.lineno,
                    node.col_offset,
                    "broad except swallows silently in failure-domain "
                    "code — increment a counter or log before "
                    "continuing (a fault nobody counted never happened)")
