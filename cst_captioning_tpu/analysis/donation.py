"""donation-audit: prove every donated buffer is actually aliased.

``donate_argnums`` is a *request*: XLA only frees the input buffer when
it can alias it onto an output with a matching shape/dtype.  A donation
with no matching output is silently skipped (one warning, easy to lose),
so "state is updated in place" claims rot the moment a step stops
returning the state — exactly what PR 3 and PR 6 audited BY HAND across
trainer/bench/serving.  This module mechanizes that audit at lowering
time, no device execution:

- every donated-entry-point family registers a builder here (trainer XE
  step, fused device-reward CST step, serving greedy/beam chunk + admit
  programs) that constructs the REAL jitted program at tiny shapes and
  returns its ``jax.stages.Lowered`` plus the donated-leaf count;
- :func:`audit_lowered` parses the lowered StableHLO entry signature:
  jax marks each donated-and-aliased input with ``tf.aliasing_output``
  (a donated-but-unusable input gets no marker), so
  ``aliased == donated leaves`` is the machine-checkable form of the
  hand audit.

The rule is registered with ``needs_trace=True``: AST-only runs
(``cstlint --no-trace``) skip it; ``make lint`` and the tier-1 test run
it against every registered entry point.
"""

from __future__ import annotations

import inspect
import re
from typing import Callable, Dict, Iterator, List, Tuple

from .engine import Project, Violation, rule

#: name -> builder() -> (jax.stages.Lowered, donated_leaf_count).
ENTRY_POINTS: Dict[str, Callable] = {}


def register_entry_point(name: str):
    """Decorator adding a donated jit program to the audited registry."""

    def deco(fn):
        ENTRY_POINTS[name] = fn
        return fn

    return deco


_ALIAS_RE = re.compile(r"tf\.aliasing_output")


def _main_signature(text: str) -> str:
    """The @main argument list of a lowered StableHLO module — from
    'func.func public @main(' to the '->' result arrow (arg attribute
    blocks like '{tf.aliasing_output = 0 : i32}' live in between; result
    attributes come after the arrow and must not be counted)."""
    start = text.find("@main(")
    if start < 0:
        return ""
    end = text.find("->", start)
    if end < 0:
        end = text.find("\n", start)
    return text[start:end if end > 0 else len(text)]


def audit_lowered(lowered, donated_leaves: int) -> List[str]:
    """-> problems (empty = every donated leaf aliased to an output)."""
    sig = _main_signature(lowered.as_text())
    if not sig:
        return ["could not locate @main in the lowered module "
                "(jax lowering format changed?)"]
    aliased = len(_ALIAS_RE.findall(sig))
    if aliased < donated_leaves:
        return [f"only {aliased} of {donated_leaves} donated leaves are "
                "aliased to outputs — the rest are silently NOT freed "
                "(XLA skips unusable donations with a warning)"]
    if donated_leaves == 0:
        return ["entry point declares zero donated leaves — register it "
                "without donation auditing or fix the builder"]
    return []


def audit_entry_points(entry_points: Dict[str, Callable] = None
                       ) -> Dict[str, List[str]]:
    """Run every registered builder; -> {name: [problems]} (empty lists
    for clean entries).  Builder exceptions are reported as problems,
    not raised — one broken entry must not mask the others' results."""
    out: Dict[str, List[str]] = {}
    for name, builder in sorted((entry_points or ENTRY_POINTS).items()):
        try:
            lowered, donated = builder()
            out[name] = audit_lowered(lowered, donated)
        except Exception as e:  # surfaced as a violation, not a crash
            out[name] = [f"entry-point builder failed: {e!r}"]
    return out


# -- registered entry points -------------------------------------------------
# Tiny-shape twins of the real programs, built through the SAME factories
# the trainer/serving engine use (make_xe_step / make_fused_cst_step /
# data_parallel_jit / ServingEngine._programs) so a donation regression in
# any factory fails the audit before a chip ever runs it.

_V, _H, _B, _S, _L = 20, 8, 2, 2, 5
_FEAT_SHAPES = [(3, 4)]


def _tiny_model_state():
    import jax
    import numpy as np

    from ..models import CaptionModel
    from ..training.state import create_train_state, make_optimizer

    model = CaptionModel(vocab_size=_V, embed_size=_H, hidden_size=_H,
                         attn_size=_H, dropout_rate=0.0)
    tx, _ = make_optimizer(learning_rate=1e-3, grad_clip=5.0)
    state = create_train_state(model, jax.random.PRNGKey(0), _FEAT_SHAPES,
                               _L, _S, tx, batch_size=_B)
    rng = np.random.default_rng(0)
    feats = [rng.standard_normal((_B,) + s).astype(np.float32)
             for s in _FEAT_SHAPES]
    return model, state, feats


@register_entry_point("trainer_xe_dp_step")
def _xe_dp_step():
    """The trainer's XE train step through data_parallel_jit, state
    donated (trainer.py --> parallel/dp.py)."""
    import jax
    import jax.numpy as jnp

    from ..parallel import data_parallel_jit, make_mesh
    from ..training.steps import make_xe_step
    import numpy as np

    model, state, feats = _tiny_model_state()
    mesh = make_mesh(jax.devices()[:1])
    step = data_parallel_jit(make_xe_step(model, _S), mesh,
                             batch_argnums=(1, 2, 3), donate_argnums=(0,))
    rng = np.random.default_rng(1)
    labels = jnp.asarray(rng.integers(1, _V, (_B * _S, _L)), jnp.int32)
    weights = jnp.ones((_B * _S,), jnp.float32)
    args = (state, [jnp.asarray(f) for f in feats], labels, weights,
            jax.random.PRNGKey(1))
    lowered = step.jit_for(len(args)).lower(*args)
    return lowered, len(jax.tree_util.tree_leaves(state))


@register_entry_point("trainer_fused_cst_dp_step")
def _fused_cst_dp_step():
    """The fused device-reward CST step (--device_rewards 1, the shipped
    RL path), state donated."""
    import jax
    import jax.numpy as jnp

    from ..parallel import data_parallel_jit, make_mesh
    from ..training.device_rewards import build_device_tables
    from ..training.steps import make_fused_cst_step

    model, state, feats = _tiny_model_state()
    vocab_words = {i: f"w{i}" for i in range(1, _V)}
    w2i = {w: i for i, w in vocab_words.items()}
    refs = {f"v{i}": [" ".join(f"w{1 + ((i + j + k) % (_V - 1))}"
                              for k in range(4)) for j in range(2)]
            for i in range(3)}
    corpus, tables, video_row = build_device_tables(refs, w2i)
    fused = make_fused_cst_step(model, _L, _S, corpus, tables)
    mesh = make_mesh(jax.devices()[:1])
    step = data_parallel_jit(fused, mesh, batch_argnums=(1, 2),
                             donate_argnums=(0,))
    vix = jnp.asarray([video_row[f"v{i % 3}"] for i in range(_B)],
                      jnp.int32)
    args = (state, [jnp.asarray(f) for f in feats], vix,
            jax.random.PRNGKey(1))
    lowered = step.jit_for(len(args)).lower(*args)
    return lowered, len(jax.tree_util.tree_leaves(state))


def _serving_programs(beam_size: int):
    import jax
    import numpy as np

    from ..models import CaptionModel
    from ..serving.engine import ServingEngine

    model = CaptionModel(vocab_size=_V, embed_size=_H, hidden_size=_H,
                         attn_size=_H, dropout_rate=0.0)
    t, d = _FEAT_SHAPES[0]
    feats = [np.zeros((1, t, d), np.float32)]
    variables = model.init(jax.random.PRNGKey(0),
                           [jax.numpy.asarray(feats[0])],
                           np.zeros((1, _L), np.int32))
    engine = ServingEngine(model, variables, [(t, d)], max_len=_L,
                           beam_size=beam_size, decode_chunk=2,
                           bucket_sizes=(2,))
    slots = 2
    programs = engine._programs(slots)
    state = engine._init_state(slots)
    return engine, variables, programs, state, feats


def _serving_entry(beam_size: int, which: str):
    import jax
    import jax.numpy as jnp

    engine, variables, programs, state, feats = \
        _serving_programs(beam_size)
    donated = len(jax.tree_util.tree_leaves(state))
    if which == "chunk":
        lowered = programs["chunk"].lower(variables, state)
    else:
        lowered = programs["admit"].lower(
            variables, state, [jnp.asarray(feats[0])], jnp.int32(0))
    return lowered, donated


@register_entry_point("serving_greedy_chunk")
def _serve_greedy_chunk():
    """ServingEngine's compiled greedy decode chunk, slot state donated."""
    return _serving_entry(1, "chunk")


@register_entry_point("serving_greedy_admit")
def _serve_greedy_admit():
    """ServingEngine's one-encoder-pass admission program (greedy)."""
    return _serving_entry(1, "admit")


@register_entry_point("serving_beam_chunk")
def _serve_beam_chunk():
    """ServingEngine's compiled beam decode chunk, slot state donated."""
    return _serving_entry(3, "chunk")


@register_entry_point("serving_beam_admit")
def _serve_beam_admit():
    """ServingEngine's admission program under beam decoding."""
    return _serving_entry(3, "admit")


# -- the rule ----------------------------------------------------------------

@rule("donation-audit",
      "every donate_argnames/donate_argnums leaf of the registered jit "
      "entry points is aliased to an output at lowering time",
      needs_trace=True)
def check_donation(project: Project) -> Iterator[Violation]:
    for name, problems in audit_entry_points().items():
        builder = ENTRY_POINTS[name]
        try:
            src = inspect.getsourcefile(builder) or ""
            line = inspect.getsourcelines(builder)[1]
        except (OSError, TypeError):
            src, line = "", 1
        rel = "cst_captioning_tpu/analysis/donation.py" \
            if src.endswith("donation.py") else (src or "<donation>")
        for p in problems:
            yield Violation("donation-audit", rel, line, 0,
                            f"entry point '{name}': {p}")
