"""cstlint: project-native static analysis (ANALYSIS.md).

Nine PRs of training/serving hardening produced invariants that lived
only as prose in RESILIENCE.md/SERVING.md and reviewer memory — never
fetch device scalars in hot loops, every durable JSON write goes through
``integrity.atomic_json_write``, every counter is declared-at-0, every
process exit routes through ``resilience/exitcodes.py``.  Each was
violated at least once before being fixed by hand.  This package moves
that enforcement to analysis time: an AST-based rule engine with a rule
registry, per-rule suppression comments carrying a required written
justification, JSON + human output, and a jaxpr-level donation audit —
run over the whole tree as a tier-1 test (tests/test_cstlint.py) so the
caveats are law, not tribal knowledge.

ISSUE 11 extends the same engine to the THREADING model: a declared
concurrency grammar (``guarded_by``/``owned_by`` annotations, per-module
``LOCK_ORDER`` tables) enforced by six rules in ``concurrency.py``, plus
``locksan.py`` — the opt-in runtime lock sanitizer that re-validates the
declared order under the serving chaos drills (``CST_LOCK_SANITIZER=1``).

Entry points: ``scripts/cstlint.py`` / ``make lint`` / ``make lint-json``;
the rule catalogue and suppression grammar are documented in ANALYSIS.md.
"""

from .engine import (  # noqa: F401
    LintResult,
    Project,
    RULES,
    SourceFile,
    Suppression,
    Violation,
    lint_sources,
    lint_tree,
    render_human,
    render_json,
    tree_files,
)

# Importing the rule modules registers every shipped rule.
from . import rules  # noqa: F401,E402
from . import donation  # noqa: F401,E402
from . import concurrency  # noqa: F401,E402
