"""Transformer caption decoder — driver config 5's decoder swap.

For ActivityNet-length feature streams the LSTM's sequential carry wastes
the MXU; a causal Transformer decoder computes the whole teacher-forced
sequence as batched matmuls (SURVEY.md §6 config ladder: "Transformer-
decoder swap at pod scale").  Pre-LN blocks: causal self-attention over
the word prefix, cross-attention over the encoder memory, MLP.

Autoregressive decoding reuses the same parallel forward over a static
token buffer (carry = (buffer, position)): step t writes the token at
position t and reads logits at t.  That is O(L^2) per caption — for
caption lengths (<=30 tokens) this costs less than maintaining a KV cache
and keeps ONE forward implementation for train and decode.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

TxCarry = Tuple[jnp.ndarray, jnp.ndarray]  # (token buffer (B, Lmax), position ())


class TransformerBlock(nn.Module):
    hidden_size: int
    num_heads: int
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, memory, causal_mask, train: bool = False):
        deterministic = not train
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype,
            dropout_rate=self.dropout_rate, name="self_attn",
        )(y, y, mask=causal_mask, deterministic=deterministic)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype,
            dropout_rate=self.dropout_rate, name="cross_attn",
        )(y, memory, deterministic=deterministic)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(4 * self.hidden_size, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.hidden_size, dtype=self.dtype)(y)
        if self.dropout_rate > 0:
            y = nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        return x + y


class TransformerDecoder(nn.Module):
    vocab_size: int
    embed_size: int = 512
    hidden_size: int = 512
    num_layers: int = 2
    num_heads: int = 8
    dropout_rate: float = 0.0
    max_len: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jnp.ndarray, memory: jnp.ndarray,
                 pooled: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        """Teacher-forced parallel decode: (B, L) tokens -> (B, L, V) logits."""
        b, length = inputs.shape
        if length > self.max_len:
            raise ValueError(f"sequence {length} exceeds max_len {self.max_len}")
        x = nn.Embed(self.vocab_size, self.hidden_size, dtype=self.dtype,
                     name="embed")(inputs)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (self.max_len, self.hidden_size), self.dtype)
        # The fused video feature seeds every position (the transformer
        # analogue of the LSTM's feature-initialized state).
        x = x + pos[None, :length, :] + pooled[:, None, :].astype(self.dtype)
        causal = nn.make_causal_mask(inputs)
        for layer in range(self.num_layers):
            x = TransformerBlock(self.hidden_size, self.num_heads,
                                 self.dropout_rate, self.dtype,
                                 name=f"block_{layer}")(x, memory, causal, train)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab_size, dtype=self.dtype, name="logit")(x)

    def decode(self, carry: TxCarry, tokens: jnp.ndarray, memory: jnp.ndarray,
               pooled: jnp.ndarray, train: bool = False):
        """Autoregressive step(s) over a static buffer.

        tokens (B, L): written into the buffer at [pos, pos+L); returns
        logits for those positions.  With L==1 this is the sampler step.
        """
        buf, pos = carry
        b, l = tokens.shape
        buf = jax.lax.dynamic_update_slice(buf, tokens, (0, pos))
        logits_all = self(buf, memory, pooled, train=train)
        logits = jax.lax.dynamic_slice(
            logits_all, (0, pos, 0), (b, l, logits_all.shape[-1])
        )
        return (buf, pos + l), logits
