"""Attention-LSTM caption decoder — the flagship decode path.

The reference decoder (SURVEY.md §2 "Captioning model") is a 1–2 layer LSTM
over word embeddings with the fused video feature initializing the state.
TPU-first rebuild:

- the per-step computation lives in one ``DecoderCell`` module; teacher
  forcing, sampling and beam search all drive the SAME cell (same param
  tree), either under ``nn.scan`` (training: whole sequence in one compiled
  scan, weights broadcast — no Python-per-timestep) or as a length-1 scan
  (autoregressive decoding), so there is exactly one set of semantics;
- attention context (AdditiveAttention over the encoder memory) replaces
  the reference's constant mean-pooled feature; ``use_attention=False``
  recovers the reference's pooled behavior exactly (context = pooled
  feature each step);
- carries are (c, h) tuples per layer — a pytree that shards trivially
  over the data mesh axis.
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import AdditiveAttention

Carry = Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]  # ((c, h) per layer)


class DecoderCell(nn.Module):
    """One decode step: embed token, attend, run LSTM stack -> hidden.

    The vocab projection deliberately lives OUTSIDE the cell (in
    ``CaptionModel``): under ``nn.scan`` an in-cell projection would run L
    sequential (B, H) x (H, V) GEMMs, while the hoisted head projects the
    whole (B, L, H) sequence in one batched MXU-friendly GEMM for teacher
    forcing — and the samplers apply the same shared Dense per step, so
    training and decoding still share one set of weights/semantics."""

    vocab_size: int          # with PAD/EOS row: len(vocab) + 1
    embed_size: int
    hidden_size: int
    num_layers: int = 1
    attn_size: int = 512
    use_attention: bool = True
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    use_pallas_attention: bool = False

    @nn.compact
    def __call__(
        self,
        carry: Carry,
        token: jnp.ndarray,        # (B,) int32
        memory: jnp.ndarray,       # (B, T, H)
        proj_mem: jnp.ndarray,     # (B, T, A)
        pooled: jnp.ndarray,       # (B, H)
        train: bool = False,
    ):
        x = nn.Embed(self.vocab_size, self.embed_size, dtype=self.dtype,
                     name="embed")(token)
        h_top = carry[-1][1]
        if self.use_attention:
            context, _ = AdditiveAttention(
                self.attn_size, dtype=self.dtype,
                use_pallas=self.use_pallas_attention, name="attn",
            )(h_top, memory, proj_mem)
        else:
            context = pooled
        inp = jnp.concatenate([x, context.astype(self.dtype)], axis=-1)
        new_carry = []
        for layer in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype,
                                        name=f"lstm{layer}")
            layer_carry, inp = cell(carry[layer], inp)
            new_carry.append(layer_carry)
        h = inp
        if self.dropout_rate > 0:
            h = nn.Dropout(self.dropout_rate, deterministic=not train)(h)
        return tuple(new_carry), h


def scan_decoder(cell_cls=DecoderCell, unroll: int = 1):
    """nn.scan-transformed DecoderCell: tokens (B, L) -> hiddens (B, L, H).

    Params broadcast across time (one weight set), dropout rng split per
    step.  Single-step decoding is the L=1 case of the same transform, so
    training and sampling can never diverge.  The caller applies the
    shared vocab head to the stacked hiddens (see DecoderCell docstring).

    ``unroll`` is forwarded to ``lax.scan``: the recurrence stays
    sequential either way, but unrolling k steps per scan iteration lets
    XLA fuse/pipeline across step boundaries, amortizing per-iteration
    overhead when the per-step matmuls are small (measured on TPU in
    PARITY.md; identical numerics, compile time grows with k).
    """
    return nn.scan(
        cell_cls,
        variable_broadcast="params",
        split_rngs={"params": False, "dropout": True},
        in_axes=(1, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
        out_axes=1,
        unroll=unroll,
    )
