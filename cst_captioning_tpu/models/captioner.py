"""CaptionModel — encoder + decoder with the reference's three surfaces.

The reference ``CaptionModel`` exposes teacher-forced ``forward``, stochastic
``sample`` and ``sample_beam`` (SURVEY.md §2).  Here the model owns *state
and parameters only*; the decoding algorithms live in ``ops/sampling.py`` /
``ops/beam.py`` as pure functions over the model's ``decode`` step — so jit,
shard_map and the samplers compose without method-boundary tracing issues.

Surfaces:
- ``__call__(feats, labels, seq_per_img)`` — teacher-forced logits for
  XE/WXE/RL-gradient computation (one compiled ``nn.scan`` over time).
- ``encode(feats)`` — memory/pooled summaries, once per video batch.
- ``decode(carry, tokens, ...)`` — run the decoder over a token block;
  length-1 blocks are the autoregressive step for samplers and beam.
- ``init_carry(pooled)`` — decoder start state from the fused feature.

The pooled/no-attention configuration (``use_attention=False``) reproduces
the reference's mean-pool architecture; attention (default) is the
north-star attention-LSTM.  ``decoder_type="transformer"`` swaps in the
Transformer decoder (driver config 5) behind the same four surfaces.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..ops.sampling import repeat_for_captions  # noqa: F401  (re-export)
from .decoder_lstm import Carry, DecoderCell, scan_decoder
from .decoder_transformer import TransformerDecoder
from .encoder import FeatureEncoder


def shift_right(labels: jnp.ndarray) -> jnp.ndarray:
    """Teacher-forcing inputs: BOS (=0) then the target prefix."""
    return jnp.concatenate(
        [jnp.zeros_like(labels[:, :1]), labels[:, :-1]], axis=1
    )


class CaptionModel(nn.Module):
    vocab_size: int                 # embedding rows: len(vocab) + 1 (id 0 = PAD/EOS/BOS)
    embed_size: int = 512
    hidden_size: int = 512
    num_layers: int = 1
    attn_size: int = 512
    use_attention: bool = True
    dropout_rate: float = 0.5
    decoder_type: str = "lstm"      # "lstm" | "transformer"
    num_heads: int = 8              # transformer only
    num_tx_layers: int = 2          # transformer only
    tx_max_len: int = 64            # transformer only: positional-table size;
                                    # must cover the label seq_length
    dtype: jnp.dtype = jnp.float32
    use_pallas_attention: bool = False  # fused VMEM attention kernel (lstm)
    decode_kernel: str = "reference"    # "reference" | "pallas" | "bf16":
                                        # decode-step cell for samplers/
                                        # beam/eval — the flax cell, the
                                        # fused Pallas attention+LSTM kernel
                                        # (ops/pallas_decode_cell.py), or
                                        # the bfloat16 low-precision variant
                                        # (ops/bf16_decode.py, parity-gated).
                                        # Decode/rollout only; teacher
                                        # forcing is unaffected.  Swept by
                                        # the autotuner (tuning/)
    fusion_type: str = "temporal"   # "temporal" | "modality" (manet variant)
    scan_unroll: int = 1            # lax.scan unroll for decoder/sampling
                                    # scans (see decoder_lstm.scan_decoder)
    remat_cell: bool = False        # rematerialize the decoder cell in
                                    # backward: recompute the per-step
                                    # attention instead of storing (L,B,T,A)
                                    # f32 residuals (HBM-traffic trade;
                                    # measured on TPU in PARITY.md)
    encode_constraint: Callable | None = None
                                    # context parallelism: applied to the
                                    # encoder memory (B, T, H) right after
                                    # encode — parallel.cp.time_shard_memory
                                    # keeps T sharded over the model axis
                                    # through the decoder's cross-attention

    def setup(self):
        self.encoder = FeatureEncoder(self.hidden_size, self.dropout_rate,
                                      self.dtype, fusion=self.fusion_type,
                                      name="encoder")
        if self.decoder_type == "lstm":
            self.memory_proj = nn.Dense(self.attn_size, use_bias=False,
                                        dtype=self.dtype, name="memory_proj")
            # static_argnums counts the bound method's args including the
            # implicit module/scope slot, so ``train`` (the 6th user arg)
            # is index 6; it must be static because the cell branches on it
            cell_cls = (nn.remat(DecoderCell, prevent_cse=False,
                                 static_argnums=(6,))
                        if self.remat_cell else DecoderCell)
            self.cell = scan_decoder(cell_cls, unroll=self.scan_unroll)(
                vocab_size=self.vocab_size,
                embed_size=self.embed_size,
                hidden_size=self.hidden_size,
                num_layers=self.num_layers,
                attn_size=self.attn_size,
                use_attention=self.use_attention,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype,
                use_pallas_attention=self.use_pallas_attention,
                name="cell",
            )
            self.state_init = [
                nn.Dense(2 * self.hidden_size, dtype=self.dtype, name=f"state_init_{l}")
                for l in range(self.num_layers)
            ]
            # Shared vocab head, hoisted out of the scanned cell: teacher
            # forcing projects the whole (B, L, H) sequence in ONE batched
            # GEMM; the samplers apply the same weights per step.
            self.logit = nn.Dense(self.vocab_size, dtype=self.dtype,
                                  name="logit")
        elif self.decoder_type == "transformer":
            self.tx = TransformerDecoder(
                vocab_size=self.vocab_size,
                embed_size=self.embed_size,
                hidden_size=self.hidden_size,
                num_layers=self.num_tx_layers,
                num_heads=self.num_heads,
                dropout_rate=self.dropout_rate,
                max_len=self.tx_max_len,
                dtype=self.dtype,
                name="tx",
            )
        else:
            raise ValueError(f"unknown decoder_type {self.decoder_type!r}")

    # -- encoding ----------------------------------------------------------

    def encode(self, feats: Sequence[jnp.ndarray], train: bool = False):
        """-> (memory (B,T,H), proj_mem (B,T,A), pooled (B,H))."""
        memory, pooled = self.encoder(feats, train=train)
        if self.encode_constraint is not None:
            memory = self.encode_constraint(memory)
        if self.decoder_type == "lstm":
            proj_mem = self.memory_proj(memory)
        else:
            proj_mem = memory  # transformer cross-attn projects internally
        return memory, proj_mem, pooled

    # -- decoder state -----------------------------------------------------

    def init_carry(self, pooled: jnp.ndarray, max_len: int = 0) -> Carry:
        """Start state from the fused feature.

        LSTM: per-layer (c, h) via a learned projection (the reference
        initializes its LSTM from the embedded video feature).
        Transformer: a (token-buffer, position) pair of static size
        ``max_len`` (required > 0).
        """
        if self.decoder_type == "lstm":
            carry = []
            for layer in range(self.num_layers):
                ch = jnp.tanh(self.state_init[layer](pooled))
                c, h = jnp.split(ch, 2, axis=-1)
                carry.append((c, h))
            return tuple(carry)
        if max_len <= 0:
            raise ValueError("transformer carry needs max_len > 0")
        n = pooled.shape[0]
        buf = jnp.zeros((n, max_len), dtype=jnp.int32)
        return (buf, jnp.zeros((), dtype=jnp.int32))

    # -- decoding ----------------------------------------------------------

    def decode(
        self,
        carry,
        tokens: jnp.ndarray,        # (B, L) int32; L==1 for autoregressive step
        memory: jnp.ndarray,
        proj_mem: jnp.ndarray,
        pooled: jnp.ndarray,
        train: bool = False,
    ):
        """-> (carry, logits (B, L, V))."""
        if self.decoder_type == "lstm":
            carry, h = self.cell(carry, tokens, memory, proj_mem, pooled,
                                 train)
            return carry, self.logit(h)
        return self.tx.decode(carry, tokens, memory, pooled, train=train)

    # -- teacher-forced training surface -----------------------------------

    def __call__(
        self,
        feats: Sequence[jnp.ndarray],
        labels: jnp.ndarray,         # (B*seq_per_img, L)
        seq_per_img: int = 1,
        train: bool = False,
    ) -> jnp.ndarray:
        memory, proj_mem, pooled = self.encode(feats, train=train)
        memory = repeat_for_captions(memory, seq_per_img)
        proj_mem = repeat_for_captions(proj_mem, seq_per_img)
        pooled = repeat_for_captions(pooled, seq_per_img)
        inputs = shift_right(labels)
        if self.decoder_type == "lstm":
            carry = self.init_carry(pooled)
            _, logits = self.decode(carry, inputs, memory, proj_mem, pooled,
                                    train=train)
        else:
            logits = self.tx(inputs, memory, pooled, train=train)
        return logits
