"""Model zoo: feature encoder, attention-LSTM / Transformer caption decoders."""

from .captioner import CaptionModel, repeat_for_captions, shift_right
from .decoder_lstm import DecoderCell, scan_decoder
from .decoder_transformer import TransformerDecoder
from .encoder import FeatureEncoder

__all__ = [
    "CaptionModel",
    "DecoderCell",
    "FeatureEncoder",
    "TransformerDecoder",
    "repeat_for_captions",
    "scan_decoder",
    "shift_right",
]
