"""Multi-modality feature encoder.

The reference encoder (SURVEY.md §2 "Captioning model") linearly embeds each
modality's pre-extracted features, mean-pools over time, and concatenates
modalities.  Rebuilt TPU-first:

- every modality is projected to a shared hidden size with one Dense
  (an MXU matmul over the batch*time axis);
- the *pooled* path (mean over time, concat, fuse) initializes the decoder
  state — the reference's only path;
- additionally the per-timestep projections are concatenated along time
  into an attention memory (B, sum_m T_m, H) for the attention-LSTM and
  Transformer decoders, which the reference's mean-pool destroyed — this is
  the "attention-LSTM decoder" of the north-star and the path that scales
  to ActivityNet-length feature streams (SURVEY.md §5 long-context);
- ``fusion="modality"`` instead exposes the per-modality pooled embeddings
  as an (B, M, H) memory so the decoder's attention runs over *modalities*
  — the reference's modality-attention variant ("manet" per SURVEY.md §2
  "Captioning model", selected there via --model_type) restated on the
  same attention plumbing.
"""

from __future__ import annotations

from typing import List, Sequence

import flax.linen as nn
import jax.numpy as jnp


class FeatureEncoder(nn.Module):
    """Project + fuse per-modality features.

    Returns (memory, pooled):
      memory: (B, sum_m T_m, hidden) per-timestep encodings for attention
      pooled: (B, hidden) fused global feature for decoder-state init
    """

    hidden_size: int
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    fusion: str = "temporal"   # "temporal" | "modality" (manet-style)

    @nn.compact
    def __call__(self, feats: Sequence[jnp.ndarray], train: bool = False):
        if len(feats) == 0:
            raise ValueError("need at least one feature modality")
        projected: List[jnp.ndarray] = []
        pooled: List[jnp.ndarray] = []
        for m, x in enumerate(feats):
            if x.ndim != 3:
                raise ValueError(f"modality {m}: expected (B, T, D), got {x.shape}")
            x = x.astype(self.dtype)
            h = nn.Dense(self.hidden_size, dtype=self.dtype, name=f"embed_{m}")(x)
            h = nn.relu(h)
            projected.append(h)                    # (B, T_m, H)
            pooled.append(jnp.mean(h, axis=1))     # (B, H)
        if self.fusion == "modality":
            memory = jnp.stack(pooled, axis=1)     # (B, M, H) modality tokens
        elif self.fusion == "temporal":
            memory = jnp.concatenate(projected, axis=1)
        else:
            raise ValueError(f"unknown fusion {self.fusion!r}")
        fused = jnp.concatenate(pooled, axis=-1)
        fused = nn.Dense(self.hidden_size, dtype=self.dtype, name="fuse")(fused)
        fused = nn.tanh(fused)
        if self.dropout_rate > 0:
            fused = nn.Dropout(self.dropout_rate, deterministic=not train)(fused)
            memory = nn.Dropout(self.dropout_rate, deterministic=not train)(memory)
        return memory, fused
