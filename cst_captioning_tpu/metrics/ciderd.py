"""CIDEr-D — the CST reward metric, pure Python/NumPy with corpus-df mode.

Reimplements the scoring semantics of the reference's vendored
``pyciderevalcap`` (CiderD/CiderScorer) without copying it: n in 1..4,
sigma=6.0 gaussian length penalty, count clipping against the reference
(the "D" = degenerate-robust variant), TF-IDF with log document frequency,
per-n averaging, ×10 final scale.  (Reference mount empty at survey time;
semantics per the CIDEr-D paper, Vedantam et al. CVPR'15 §Appendix, and the
public pyciderevalcap package — SURVEY.md §2 "CIDEr-D (reward)".)

Two df modes, matching the reference CLI contract (SURVEY.md §2 CLI config,
``--train_cached_tokens``):

- ``corpus``: document frequencies come from a precomputed corpus pickle so
  the per-iteration RL reward never rescans the corpus.  This is the hot
  path: called once per training step on (sampled + baseline) captions.
- ``coco-val-df`` / on-the-fly: df computed from the reference sets passed
  to ``compute_score`` (standard eval behavior).

Vectorization note: the scorer keeps each caption's TF-IDF as sparse dicts
(captions are ~10 tokens, dense vocab vectors would be wasteful) but batches
the final similarity loop in plain Python — profiled fast enough for the
5k captions/sec/chip target because n-gram dicts are tiny; if this ever
becomes the RL bottleneck the C++ scorer hook in ``cst_captioning_tpu/ops``
is the upgrade path.
"""

from __future__ import annotations

import math
import pickle
from collections import defaultdict
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .ngrams import NGram, NGramCounts, cook_refs, cook_test


def build_corpus_df(
    tokenized_refs: Mapping[str, Sequence[str]], n: int = 4
) -> Tuple[Dict[NGram, float], int]:
    """Build corpus document frequencies from ``{video_id: [captions]}``.

    An n-gram's df is the number of *videos* (documents) in whose reference
    set it appears at least once.  Returns (df, num_documents).  This is the
    offline artifact the reference caches via ``--train_cached_tokens``.
    """
    df: Dict[NGram, float] = defaultdict(float)
    for refs in tokenized_refs.values():
        seen = set()
        for ref in refs:
            seen.update(cook_test(ref, n).keys())
        for ng in seen:
            df[ng] += 1.0
    return dict(df), len(tokenized_refs)


def save_corpus_df(path: str, df: Dict[NGram, float], num_docs: int) -> None:
    with open(path, "wb") as f:
        pickle.dump({"df": df, "ref_len": float(num_docs)}, f, protocol=pickle.HIGHEST_PROTOCOL)


def load_corpus_df(path: str) -> Tuple[Dict[NGram, float], float]:
    with open(path, "rb") as f:
        blob = pickle.load(f)
    return blob["df"], float(blob["ref_len"])


class CiderD:
    """CIDEr-D scorer.

    Args:
      df_mode: "corpus" to use a precomputed df (pass ``df``/``ref_len`` or
        ``df_path``), anything else to derive df from the refs given to each
        ``compute_score`` call.
      n: max n-gram order (4).
      sigma: gaussian length-penalty width (6.0).
    """

    def __init__(
        self,
        n: int = 4,
        sigma: float = 6.0,
        df_mode: str = "corpus",
        df: Optional[Dict[NGram, float]] = None,
        ref_len: Optional[float] = None,
        df_path: Optional[str] = None,
        variant: str = "cider-d",
    ):
        if variant not in ("cider-d", "cider"):
            raise ValueError(f"unknown variant {variant!r}")
        self.n = n
        self.sigma = sigma
        self.df_mode = df_mode
        # "cider-d": clipped counts + gaussian length penalty — the reward
        # metric AND what coco-caption's eval suite computes under the name
        # "CIDEr" (its Cider scorer includes both terms).
        # "cider": the original unclipped/no-penalty formulation
        # (pyciderevalcap's plain Cider class).
        self.variant = variant
        if df_mode == "corpus":
            if df_path is not None:
                df, ref_len = load_corpus_df(df_path)
            if df is None or ref_len is None:
                raise ValueError("corpus df_mode requires df+ref_len or df_path")
            self.df = df
            self.ref_len = math.log(max(ref_len, 1.0))
        else:
            self.df = None
            self.ref_len = None

    # -- internals ---------------------------------------------------------

    def _counts_to_vec(
        self, counts: NGramCounts, df: Mapping[NGram, float], log_ref_len: float
    ) -> Tuple[List[Dict[NGram, float]], np.ndarray, int]:
        """Sparse TF-IDF vector per n-gram order, its norms, and the length."""
        vec: List[Dict[NGram, float]] = [defaultdict(float) for _ in range(self.n)]
        norm = np.zeros(self.n, dtype=np.float64)
        length = 0
        for ngram, term_freq in counts.items():
            dfv = math.log(max(df.get(ngram, 0.0), 1.0))
            k = len(ngram) - 1
            w = term_freq * (log_ref_len - dfv)
            vec[k][ngram] = w
            norm[k] += w * w
            if k == 0:
                length += term_freq
        return vec, np.sqrt(norm), length

    def _sim(
        self,
        vec_hyp, norm_hyp, len_hyp,
        vec_ref, norm_ref, len_ref,
    ) -> np.ndarray:
        """Clipped cosine similarity per n-gram order with length penalty."""
        delta = float(len_hyp - len_ref)
        clip = self.variant == "cider-d"
        val = np.zeros(self.n, dtype=np.float64)
        for k in range(self.n):
            hv, rv = vec_hyp[k], vec_ref[k]
            acc = 0.0
            for ngram, hw in hv.items():
                rw = rv.get(ngram)
                if rw is None:
                    continue
                # CIDEr-D clips the hypothesis TF-IDF weight to the
                # reference's, penalizing degenerate repetition; plain
                # CIDEr is the raw cosine numerator.
                acc += (min(hw, rw) if clip else hw) * rw
            if norm_hyp[k] != 0 and norm_ref[k] != 0:
                val[k] = acc / (norm_hyp[k] * norm_ref[k])
        if clip:
            val *= math.exp(-(delta ** 2) / (2 * self.sigma ** 2))
        return val

    # -- public API --------------------------------------------------------

    def compute_score(
        self,
        gts: Mapping[str, Sequence[str]],
        res: Sequence[Mapping[str, object]],
    ) -> Tuple[float, np.ndarray]:
        """Score hypotheses against reference sets.

        Interface mirrors the reference reward call site (SURVEY §3.2):
          gts: {key: [tokenized ref caption, ...]}
          res: [{"image_id": key, "caption": [tokenized hyp]}, ...]
        Returns (mean_score, per-hypothesis scores ×10).
        """
        # Cook each reference caption exactly once; df (in refs mode) and the
        # TF-IDF vectors both derive from the same cooked counts.
        cooked_refs: Dict[str, List[NGramCounts]] = {
            key: cook_refs(refs, self.n) for key, refs in gts.items()
        }
        if self.df_mode == "corpus":
            df, log_ref_len = self.df, self.ref_len
        else:
            df = defaultdict(float)
            for cooked in cooked_refs.values():
                seen = set()
                for counts in cooked:
                    seen.update(counts.keys())
                for ng in seen:
                    df[ng] += 1.0
            log_ref_len = math.log(max(float(len(cooked_refs)), 1.0))

        ref_cache: Dict[str, list] = {
            key: [self._counts_to_vec(c, df, log_ref_len) for c in cooked]
            for key, cooked in cooked_refs.items()
        }

        scores = np.zeros(len(res), dtype=np.float64)
        for i, item in enumerate(res):
            key = item["image_id"]
            hyp_list = item["caption"]
            hyp = hyp_list[0] if isinstance(hyp_list, (list, tuple)) else hyp_list
            vec, norm, length = self._counts_to_vec(cook_test(hyp, self.n), df, log_ref_len)
            refs = ref_cache[key]
            score = np.zeros(self.n, dtype=np.float64)
            for rvec, rnorm, rlen in refs:
                score += self._sim(vec, norm, length, rvec, rnorm, rlen)
            score_avg = score.mean() / max(len(refs), 1) * 10.0
            scores[i] = score_avg
        return float(scores.mean()) if len(res) else 0.0, scores
