"""``language_eval`` — the metric-suite orchestrator, no Java, no subprocess.

Reimplements the reference's ``utils.language_eval`` →
``COCOEvalCap.evaluate()`` stack (SURVEY.md §3.4) as a single in-process
call: PTB-style tokenization of hypotheses and references, then
BLEU-1..4, METEOR (pure-Python approximation), ROUGE-L, CIDEr and CIDEr-D.
Accepts coco-format annotation/result structures so prediction JSONs written
by ``eval.py`` score identically to the reference workflow.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from .bleu import compute_bleu
from .ciderd import CiderD
from .meteor import compute_meteor
from .rouge import compute_rouge
from .tokenizer import tokenize_corpus

_warned_meteor = False

# Metrics whose emitted key differs from the selection name the CLI keeps
# for reference compatibility.  METEOR here is the pure-Python 2005
# approximation (no WordNet/paraphrase data in this environment), so every
# output channel — scores JSONs, metrics.jsonl, printed tables — carries it
# as METEOR_approx: a bare "METEOR" column invites silent mis-comparison
# against jar METEOR-1.5 literature numbers (VERDICT r3 #4).
# ``--eval_metric METEOR`` still selects it (see score_key).
APPROX_SCORE_KEYS = {"METEOR": "METEOR_approx"}


def score_key(metric: str) -> str:
    """Emitted-scores key for a CLI ``--eval_metric`` name."""
    return APPROX_SCORE_KEYS.get(metric, metric)


def load_cocofmt_refs(cocofmt_file: str) -> Dict[str, List[str]]:
    """Read a coco-format annotations JSON into {image_id: [caption, ...]}."""
    with open(cocofmt_file) as f:
        coco = json.load(f)
    refs: Dict[str, List[str]] = {}
    for ann in coco["annotations"]:
        refs.setdefault(str(ann["image_id"]), []).append(ann["caption"])
    return refs


def language_eval(
    predictions: Sequence[Mapping[str, object]],
    refs: Mapping[str, Sequence[str]] | str,
    scorers: Optional[Sequence[str]] = None,
) -> Dict[str, float]:
    """Score predictions [{"image_id": id, "caption": text}, ...].

    ``refs`` is either {image_id: [caption,...]} or a path to a coco-format
    annotations JSON.  Only image_ids present in ``predictions`` are scored
    (matching COCOEvalCap, which evaluates on the result set).  Returns the
    printed metric dict the reference workflow produces.
    """
    if isinstance(refs, str):
        refs = load_cocofmt_refs(refs)
    res_raw = {str(p["image_id"]): [str(p["caption"])] for p in predictions}
    gts_raw = {k: list(refs[k]) for k in res_raw.keys() if k in refs}
    missing = set(res_raw) - set(gts_raw)
    if missing:
        raise KeyError(f"predictions for ids without references: {sorted(missing)[:5]}")
    res = tokenize_corpus(res_raw)
    gts = tokenize_corpus(gts_raw)

    if scorers is None:
        scorers = ("Bleu", "METEOR", "ROUGE_L", "CIDEr")
    out: Dict[str, float] = {}
    if "Bleu" in scorers:
        bleus, _ = compute_bleu(gts, res, n=4)
        for i, b in enumerate(bleus, 1):
            out[f"Bleu_{i}"] = float(b)
    if "METEOR" in scorers or "METEOR_approx" in scorers:
        global _warned_meteor
        if not _warned_meteor:
            # An approximated METEOR column silently compared against
            # jar-METEOR literature numbers is worse than a missing one
            # (VERDICT r2) — say so once, loudly, at scoring time.
            logging.getLogger("cst_captioning_tpu.metrics").warning(
                "METEOR_approx is the pure-Python 2005-algorithm "
                "approximation (exact+stem matching, no WordNet/paraphrase "
                "modules) — NOT numerically comparable to meteor-1.5.jar "
                "numbers from the literature; see metrics/meteor.py"
            )
            _warned_meteor = True
        out["METEOR_approx"] = compute_meteor(gts, res)[0]
    if "ROUGE_L" in scorers:
        out["ROUGE_L"] = compute_rouge(gts, res)[0]
    res_list = [{"image_id": k, "caption": v} for k, v in res.items()]
    if "CIDEr" in scorers:
        # coco-caption's Cider scorer carries count clipping and the gaussian
        # length penalty (CIDEr-D semantics) despite its name; published
        # "CIDEr" columns are that metric, so the eval key must match it.
        out["CIDEr"] = CiderD(df_mode="refs", variant="cider-d").compute_score(gts, res_list)[0]
    if "CIDEr-plain" in scorers:
        # The un-clipped, no-length-penalty original formulation, kept for
        # completeness (pyciderevalcap ships it as its `Cider` class).
        out["CIDEr-plain"] = CiderD(df_mode="refs", variant="cider").compute_score(gts, res_list)[0]
    return out
