"""n-gram cooking shared by CIDEr-D, BLEU and the consensus builders.

The reference's vendored ``pyciderevalcap``/``pycocoevalcap`` each carry a
private copy of precook/cook_refs/cook_test; here there is a single
implementation.  Captions are pre-tokenized strings ("a man is cooking"),
n-grams are tuples of tokens, counts are plain dicts.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Sequence, Tuple

NGram = Tuple[str, ...]
NGramCounts = Dict[NGram, int]


def precook_tokens(tokens: Sequence, n: int = 4) -> Dict[tuple, int]:
    """Count all k-grams for k in 1..n of an already-tokenized sequence
    (words or ids — the one cooking loop every consumer shares)."""
    counts: Dict[tuple, int] = defaultdict(int)
    for k in range(1, n + 1):
        for i in range(len(tokens) - k + 1):
            counts[tuple(tokens[i : i + k])] += 1
    return dict(counts)


def precook(caption: str, n: int = 4) -> NGramCounts:
    """Count all k-grams for k in 1..n of a whitespace-tokenized caption."""
    return precook_tokens(caption.split(), n)


def cook_refs(refs: Sequence[str], n: int = 4) -> List[NGramCounts]:
    """Cook each reference caption of one video independently."""
    return [precook(r, n) for r in refs]


def cook_test(test: str, n: int = 4) -> NGramCounts:
    return precook(test, n)
