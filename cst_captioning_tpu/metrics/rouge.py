"""ROUGE-L matching coco-caption's Rouge scorer semantics.

LCS-based F-measure with beta=1.2; per segment, precision and recall are
each maximized over the reference set before combining (the
``pycocoevalcap`` Rouge definition — SURVEY.md §2 "Eval metric suite").
"""

from __future__ import annotations

from typing import List, Mapping, Sequence, Tuple

import numpy as np

BETA = 1.2


def _lcs_len(a: Sequence[str], b: Sequence[str]) -> int:
    """Classic O(len(a)*len(b)) LCS with a rolling row (captions are short)."""
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[j - 1]))
        prev = cur
    return prev[-1]


def rouge_l_segment(hyp: str, refs: Sequence[str]) -> float:
    h = hyp.split()
    prec_max = 0.0
    rec_max = 0.0
    for ref in refs:
        r = ref.split()
        lcs = _lcs_len(h, r)
        if h:
            prec_max = max(prec_max, lcs / len(h))
        if r:
            rec_max = max(rec_max, lcs / len(r))
    if prec_max == 0.0 or rec_max == 0.0:
        return 0.0
    return ((1 + BETA ** 2) * prec_max * rec_max) / (rec_max + BETA ** 2 * prec_max)


def compute_rouge(
    gts: Mapping[str, Sequence[str]],
    res: Mapping[str, Sequence[str]],
) -> Tuple[float, np.ndarray]:
    keys = sorted(res.keys())
    scores = np.array([rouge_l_segment(res[k][0], gts[k]) for k in keys])
    return float(scores.mean()) if len(scores) else 0.0, scores
