"""Corpus BLEU-1..4 matching coco-caption's Bleu scorer semantics.

The reference evaluates with the vendored ``pycocoevalcap`` Bleu package
(SURVEY.md §2 "Eval metric suite"); this is an independent implementation of
the same definition: modified n-gram precision with per-segment clipped
counts accumulated corpus-wide, "closest" effective reference length for the
brevity penalty, and the epsilon-smoothed ratio coco-caption uses so
zero-count high-order n-grams don't zero the whole corpus score.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Mapping, Sequence, Tuple

import numpy as np

from .ngrams import precook

_TINY = 1e-15
_SMALL = 1e-9


def compute_bleu(
    gts: Mapping[str, Sequence[str]],
    res: Mapping[str, Sequence[str]],
    n: int = 4,
) -> Tuple[List[float], List[np.ndarray]]:
    """Corpus-level BLEU-1..n plus per-segment scores.

    gts/res: {key: [tokenized caption string, ...]}; res has one hypothesis
    per key.  Returns ([bleu_1..bleu_n], [per-segment arrays 1..n]).
    """
    keys = sorted(res.keys())
    clipped = np.zeros(n)        # corpus clipped n-gram matches per order
    totals = np.zeros(n)         # corpus hypothesis n-gram counts per order
    hyp_len_sum = 0
    ref_len_sum = 0
    per_segment: List[List[float]] = [[] for _ in range(n)]

    for key in keys:
        hyp = res[key][0]
        refs = gts[key]
        hyp_counts = precook(hyp, n)
        max_ref_counts: Dict[tuple, int] = defaultdict(int)
        ref_lens = []
        for ref in refs:
            ref_lens.append(len(ref.split()))
            for ng, c in precook(ref, n).items():
                if c > max_ref_counts[ng]:
                    max_ref_counts[ng] = c
        hyp_len = len(hyp.split())
        # "closest" effective reference length, ties -> shorter.
        closest = min(ref_lens, key=lambda rl: (abs(rl - hyp_len), rl)) if ref_lens else 0
        hyp_len_sum += hyp_len
        ref_len_sum += closest

        seg_clipped = np.zeros(n)
        seg_total = np.zeros(n)
        for ng, c in hyp_counts.items():
            k = len(ng) - 1
            seg_total[k] += c
            seg_clipped[k] += min(c, max_ref_counts.get(ng, 0))
        clipped += seg_clipped
        totals += seg_total

        # Per-segment smoothed score (coco-caption reports these too).
        seg_bp = 1.0 if hyp_len >= closest else math.exp(1 - closest / max(hyp_len, _TINY))
        prec_prod = 1.0
        for k in range(n):
            p = (seg_clipped[k] + _TINY) / (seg_total[k] + _SMALL)
            prec_prod *= p
            per_segment[k].append(prec_prod ** (1.0 / (k + 1)) * seg_bp)

    bp = 1.0 if hyp_len_sum >= ref_len_sum else math.exp(1 - ref_len_sum / max(hyp_len_sum, _TINY))
    bleus: List[float] = []
    prec_prod = 1.0
    for k in range(n):
        p = (clipped[k] + _TINY) / (totals[k] + _SMALL)
        prec_prod *= p
        bleus.append(prec_prod ** (1.0 / (k + 1)) * bp)
    return bleus, [np.asarray(s) for s in per_segment]
