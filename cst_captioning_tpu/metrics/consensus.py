"""Consensus CIDEr-D scores — the CST paper's core offline artifact.

Powers two things (SURVEY.md §2 "CLI config" / §7 hard part (d)):

1. **WXE weights** (``--train_bcmrscores_pkl`` in the reference CLI): each
   ground-truth caption is scored with CIDEr-D against its sibling
   references for the same video (leave-one-out).  During weighted-XE
   training that scalar multiplies the caption's loss so high-consensus
   captions dominate.

2. **SCB baseline** (self-consensus baseline): during REINFORCE, instead of
   a greedy-decode baseline, the advantage baseline for a video is the mean
   consensus score of (a subset of) its reference captions — precomputed
   here, indexed at train time.

Leave-one-out semantics: caption j of video v is scored against the other
captions of v (never itself), with document frequencies from the full
training corpus so the numbers live on the same scale as RL rewards.
"""

from __future__ import annotations

import pickle
from typing import Dict, Mapping, Sequence

import numpy as np

from .ciderd import CiderD, build_corpus_df


def compute_consensus_scores(
    tokenized_refs: Mapping[str, Sequence[str]],
    n: int = 4,
    sigma: float = 6.0,
    native: bool = True,
) -> Dict[str, np.ndarray]:
    """Leave-one-out CIDEr-D of every reference caption vs its siblings.

    Returns {video_id: float array of shape (num_captions,)} in the same
    caption order as the input.  ``native=True`` uses the C++ scorer when a
    toolchain is available (MSR-VTT-scale corpora take seconds instead of
    minutes); the Python path is the oracle and fallback.
    """
    if native:
        try:
            from ..native import NativeCiderD, NativeUnavailable
        except ImportError:
            NativeCiderD = None  # package layout without native/
        if NativeCiderD is not None:
            try:
                return NativeCiderD(
                    tokenized_refs, None, n, sigma
                ).consensus_scores()
            except NativeUnavailable as e:  # missing toolchain only — any
                import logging              # real scorer bug must surface

                logging.getLogger(__name__).warning(
                    "native consensus unavailable (%s); using the slower "
                    "pure-Python path", e,
                )
    df, ndocs = build_corpus_df(tokenized_refs, n)
    scorer = CiderD(n=n, sigma=sigma, df_mode="corpus", df=df, ref_len=float(ndocs))
    out: Dict[str, np.ndarray] = {}
    for vid, caps in tokenized_refs.items():
        caps = list(caps)
        if len(caps) == 1:
            out[vid] = np.zeros(1)
            continue
        gts = {}
        res = []
        for j, c in enumerate(caps):
            key = f"{vid}#{j}"
            gts[key] = [caps[i] for i in range(len(caps)) if i != j]
            res.append({"image_id": key, "caption": [c]})
        _, scores = scorer.compute_score(gts, res)
        out[vid] = scores
    return out


def normalize_weights(
    scores: Mapping[str, np.ndarray], temperature: float = 1.0
) -> Dict[str, np.ndarray]:
    """Turn raw consensus scores into per-video softmax weights for WXE.

    The CST paper weights each caption's XE loss by a normalized consensus
    score; softmax-with-temperature over each video's caption set keeps the
    per-video total loss mass constant (so WXE and XE losses are on the same
    scale and learning rates transfer between stages).
    """
    out = {}
    for vid, s in scores.items():
        z = np.asarray(s, dtype=np.float64) / max(temperature, 1e-8)
        z = z - z.max()
        e = np.exp(z)
        out[vid] = (e / e.sum()) * len(s)   # mean weight == 1
    return out


def save_consensus(path: str, scores: Mapping[str, np.ndarray]) -> None:
    with open(path, "wb") as f:
        pickle.dump({k: np.asarray(v) for k, v in scores.items()}, f,
                    protocol=pickle.HIGHEST_PROTOCOL)


def load_consensus(path: str) -> Dict[str, np.ndarray]:
    with open(path, "rb") as f:
        return pickle.load(f)
