"""PTB-style caption tokenizer — pure Python, no Java subprocess.

The reference pipeline (cst_captioning's vendored ``coco-caption``) shells out
to the Stanford CoreNLP ``PTBTokenizer`` jar before every metric computation,
then drops a fixed punctuation list.  (Reference mount was empty at survey
time — see SURVEY.md provenance warning; behavior reconstructed from the
public pycocoevalcap package the reference vendors.)

This module reimplements that normalization as a single pass of compiled
regexes so the metric stack is a pure-Python process with no JVM, tempfiles,
or subprocess pipes.  The observable contract is:

    tokenize(caption) -> list of lowercase word tokens with PTB-style
    splitting applied and the coco-caption punctuation set removed.

Caption text in MSR-VTT / MSVD / ActivityNet annotations is simple
(lowercase-ish English sentences), so the PTB rules that matter here are:
contraction splitting (``don't`` -> ``do n't``), possessives
(``dog's`` -> ``dog 's``), punctuation isolation, and bracket
normalization.  All punctuation is subsequently dropped, matching
coco-caption's PUNCTUATIONS list, so edge-case differences in *how* a
punctuation mark was split cannot affect metric values — only mis-splitting
of word-internal apostrophes could, and those cases are covered by tests.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List

# coco-caption's PTBTokenizer wrapper removes exactly these tokens after
# the Java tokenizer runs.
PUNCTUATIONS = frozenset(
    [
        "''", "'", "``", "`",
        "-LRB-", "-RRB-", "-LCB-", "-RCB-",
        ".", "?", "!", ",", ":", "-", "--", "...", ";",
    ]
)

# PTB splits these contraction suffixes off the host word.
_CONTRACTIONS = re.compile(r"(?i)([a-z])('ll|'re|'ve|n't|'s|'m|'d)\b")
# Words PTB splits in the middle (cannot, gonna, ...).
_SPECIAL_SPLITS = {
    "cannot": ("can", "not"),
    "gonna": ("gon", "na"),
    "gotta": ("got", "ta"),
    "wanna": ("wan", "na"),
    "lemme": ("lem", "me"),
    "gimme": ("gim", "me"),
    "d'ye": ("d'", "ye"),
    "'tis": ("'t", "is"),
    "'twas": ("'t", "was"),
}
_BRACKETS = {
    "(": "-LRB-", ")": "-RRB-",
    "{": "-LCB-", "}": "-RCB-",
    "[": "-LRB-", "]": "-RRB-",
}
# Isolate punctuation / symbols. Ellipsis and -- first so they stay whole.
_PUNCT_ISOLATE = re.compile(r"(\.\.\.|--|[,;:@#$%&?!\"(){}\[\]<>=+/\\*^~|])")
# Abbreviations like "u.s." keep their periods (PTB treats them as one token);
# any other token-trailing period is sentence-terminal and is split off.
_ABBREV = re.compile(r"^([a-z]\.)+$", re.IGNORECASE)
# Contraction suffixes PTB emits as their own (kept) tokens — exempt from
# apostrophe stripping below.
_CONTRACTION_TOKENS = frozenset(["'s", "'re", "'ve", "'ll", "'m", "'d", "n't", "'t"])


def tokenize(caption: str) -> List[str]:
    """Tokenize one caption string into normalized word tokens."""
    s = caption.replace("\n", " ").replace("—", " -- ").replace("–", " -- ").strip()
    s = _PUNCT_ISOLATE.sub(r" \1 ", s)
    s = _CONTRACTIONS.sub(r"\1 \2", s)
    out: List[str] = []
    for tok in s.split():
        low = tok.lower()
        if low in _SPECIAL_SPLITS:
            out.extend(_SPECIAL_SPLITS[low])
            continue
        # Sentence-terminal period: split off unless abbreviation-shaped.
        if tok.endswith(".") and tok.strip(".") and not _ABBREV.match(tok):
            tok = tok[:-1]
        # Bare surrounding apostrophes ('hello', dogs') are quote characters
        # PTB renders as `/''; strip them — but keep contraction tokens.
        if tok.lower() not in _CONTRACTION_TOKENS:
            tok = tok.strip("'")
        if not tok:
            continue
        tok = _BRACKETS.get(tok, tok)
        low = tok.lower()
        if tok in PUNCTUATIONS or low in PUNCTUATIONS or low == '"':
            continue
        out.append(low)
    return out


def tokenize_to_str(caption: str) -> str:
    """Tokenize and re-join with single spaces (the form metrics consume)."""
    return " ".join(tokenize(caption))


_native_batch = None  # resolved lazily: callable, or False if unavailable


def _resolve_native():
    """Load the C++ tokenizer twin (native/tokenizer.cpp) once per process;
    any build/load failure pins the pure-Python path."""
    global _native_batch
    if _native_batch is None:
        try:
            from ..native import ptb_tokenize_batch

            # Self-check on representative captions before trusting it.
            probe = ["A man... isn't (really) cooking the dogs' dinner.",
                     "cannot. u.s. 'tis \"quoted\"!"]
            if ptb_tokenize_batch(probe) != [tokenize_to_str(p) for p in probe]:
                raise RuntimeError("native tokenizer parity probe failed")
            _native_batch = ptb_tokenize_batch
        except Exception:
            _native_batch = False
    return _native_batch


def tokenize_corpus(captions_for_key: Dict[str, Iterable[str]],
                    use_native: bool = True) -> Dict[str, List[str]]:
    """Tokenize a ``{key: [caption, ...]}`` mapping (coco-caption's interface).

    Returns ``{key: [tokenized_caption_str, ...]}`` preserving order, which is
    the exact shape PTBTokenizer.tokenize() returned to COCOEvalCap.

    Bulk calls (the trainer tokenizes every training caption at startup,
    ``language_eval`` every prediction) go through the C++ twin
    (``native/tokenizer.cpp``, parity-pinned by
    tests/test_native_tokenizer.py) in ONE batched call for the ASCII
    captions; non-ASCII captions and toolchain-less environments fall back
    to this module per caption.
    """
    native = _resolve_native() if use_native else False
    # Materialize once: the declared contract is Iterable[str], so each
    # value may be a one-shot generator.
    corpus = {key: list(caps) for key, caps in captions_for_key.items()}
    if not native:
        return {
            key: [tokenize_to_str(c) for c in caps]
            for key, caps in corpus.items()
        }
    out = {
        key: [None if c.isascii() else tokenize_to_str(c) for c in caps]
        for key, caps in corpus.items()
    }
    # One flat batch across every key for the ASCII captions.
    flat_keys: List[tuple] = []
    flat: List[str] = []
    for key, caps in corpus.items():
        for j, c in enumerate(caps):
            if out[key][j] is None:
                flat_keys.append((key, j))
                flat.append(c)
    try:
        toks = native(flat)
    except Exception:
        # A runtime fault of the C++ batch call (not just startup
        # unavailability) must also fall back to the Python oracle, and
        # pin the fallback so later calls don't re-fault (ADVICE r3).
        global _native_batch
        _native_batch = False
        toks = [tokenize_to_str(c) for c in flat]
    for (key, j), tok in zip(flat_keys, toks):
        out[key][j] = tok
    return out
