"""Pure-Python metric stack: tokenizer, CIDEr-D reward, BLEU/METEOR/ROUGE eval.

Replaces the reference's vendored ``cider/`` + ``coco-caption/`` packages and
their Java subprocesses (SURVEY.md §2, §3.4) with in-process implementations.
"""

from .bleu import compute_bleu
from .ciderd import CiderD, build_corpus_df, load_corpus_df, save_corpus_df
from .coco_eval import language_eval, load_cocofmt_refs
from .consensus import (
    compute_consensus_scores,
    load_consensus,
    normalize_weights,
    save_consensus,
)
from .meteor import compute_meteor
from .rouge import compute_rouge
from .tokenizer import tokenize, tokenize_corpus, tokenize_to_str

__all__ = [
    "CiderD",
    "build_corpus_df",
    "compute_bleu",
    "compute_consensus_scores",
    "compute_meteor",
    "compute_rouge",
    "language_eval",
    "load_cocofmt_refs",
    "load_consensus",
    "load_corpus_df",
    "normalize_weights",
    "save_consensus",
    "save_corpus_df",
    "tokenize",
    "tokenize_corpus",
    "tokenize_to_str",
]
