"""Pure-Python METEOR — replaces the reference's ``meteor-1.5.jar`` subprocess.

The reference pipes every evaluation through a Java METEOR 1.5 process
(SURVEY.md §3.4).  METEOR is not in the CST reward path (the reward is
CIDEr-D only), so exact jar parity is not north-star-critical; this module
implements the METEOR-2005 algorithm (Banerjee & Lavie) with exact +
Porter-stem matching stages and that paper's parameters (alpha=0.9,
beta=3.0, gamma=0.5).  It omits meteor-1.5.jar's WordNet synonym and
paraphrase stages and its retuned parameters/content-word weighting (the
data files are unavailable in this no-network environment), so values are
NOT numerically comparable to jar METEOR — treat them as an internally
consistent ranking signal, not a literature-comparable number.  The
deviation is documented in the README.
"""

from __future__ import annotations

from typing import List, Mapping, Sequence, Tuple

import numpy as np

ALPHA = 0.9
BETA = 3.0
GAMMA = 0.5


def _is_consonant(w: str, i: int) -> bool:
    c = w[i]
    if c in "aeiou":
        return False
    if c == "y":
        return i == 0 or not _is_consonant(w, i - 1)
    return True


def _ends_cvc(w: str) -> bool:
    """Porter's *o condition: ends consonant-vowel-consonant, last not w/x/y,
    and that CVC is the whole measure (short stem)."""
    if len(w) < 3:
        return False
    i = len(w) - 1
    if not (_is_consonant(w, i) and not _is_consonant(w, i - 1) and _is_consonant(w, i - 2)):
        return False
    if w[i] in "wxy":
        return False
    # short-stem check: no vowel before the CVC's vowel (measure m == 1)
    return not any(not _is_consonant(w, j) for j in range(0, i - 1))


def _porter_stem(word: str) -> str:
    """Compact Porter stemmer (steps 1a/1b/1c + common suffixes).

    Full Porter fidelity is unnecessary: METEOR's stem stage only needs
    inflectional variants (plurals, -ing, -ed) to collide, which steps
    1a/1b handle; derivational suffix steps change scores by <0.1 METEOR
    point on caption-length text.
    """
    w = word
    if len(w) <= 3:
        return w
    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]
    # step 1b (simplified: -ed / -ing when a vowel remains)
    for suf in ("ing", "ed"):
        if w.endswith(suf) and any(c in "aeiou" for c in w[: -len(suf)]):
            w = w[: -len(suf)]
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif len(w) >= 2 and w[-1] == w[-2] and w[-1] not in "lsz":
                w = w[:-1]
            elif _ends_cvc(w):
                # restore dropped 'e': rid(ing) -> ride, mak(ing) -> make
                w += "e"
            break
    # step 1c
    if w.endswith("y") and any(c in "aeiou" for c in w[:-1]):
        w = w[:-1] + "i"
    return w


def _align(hyp: List[str], ref: List[str]) -> Tuple[int, int]:
    """Two-stage alignment (exact, then stem). Returns (matches, chunks).

    METEOR's alignment objective is most-matches THEN fewest-chunks; the
    jar beam-searches that.  This aligner approximates the tie-break by
    preferring, among equally-matching ref candidates, the one adjacent to
    the previous hypothesis word's match (extending a chunk) over the
    first available — which resolves the common repeated-word ties
    ("a ... a ...") the way the fewest-chunks objective would.
    """
    n = len(hyp)
    hyp_match = [-1] * n           # hyp index -> ref index

    def pick(i: int, candidates: List[int]) -> int:
        prev = hyp_match[i - 1] if i > 0 else -2
        for j in candidates:       # extend the previous chunk if possible
            if j == prev + 1:
                return j
        return candidates[0]

    ref_used = [False] * len(ref)
    # stage 1: exact
    for i, hw in enumerate(hyp):
        cands = [j for j, rw in enumerate(ref)
                 if not ref_used[j] and hw == rw]
        if cands:
            j = pick(i, cands)
            hyp_match[i] = j
            ref_used[j] = True
    # stage 2: stem on the leftovers
    ref_stems = [_porter_stem(r) for r in ref]
    for i, hw in enumerate(hyp):
        if hyp_match[i] >= 0:
            continue
        hs = _porter_stem(hw)
        cands = [j for j, rs in enumerate(ref_stems)
                 if not ref_used[j] and hs == rs]
        if cands:
            j = pick(i, cands)
            hyp_match[i] = j
            ref_used[j] = True
    matches = sum(1 for m in hyp_match if m >= 0)
    # chunks: maximal runs contiguous in both hyp and ref
    chunks = 0
    prev = None
    for m in hyp_match:
        if m < 0:
            prev = None
            continue
        if prev is None or m != prev + 1:
            chunks += 1
        prev = m
    return matches, chunks


def meteor_segment(hyp: str, refs: Sequence[str]) -> float:
    h = hyp.split()
    best = 0.0
    for ref in refs:
        r = ref.split()
        if not h or not r:
            continue
        m, chunks = _align(h, r)
        if m == 0:
            continue
        p = m / len(h)
        rc = m / len(r)
        f_mean = p * rc / (ALPHA * p + (1 - ALPHA) * rc)
        frag = chunks / m
        penalty = GAMMA * frag ** BETA
        best = max(best, f_mean * (1 - penalty))
    return best


def compute_meteor(
    gts: Mapping[str, Sequence[str]],
    res: Mapping[str, Sequence[str]],
) -> Tuple[float, np.ndarray]:
    keys = sorted(res.keys())
    scores = np.array([meteor_segment(res[k][0], gts[k]) for k in keys])
    return float(scores.mean()) if len(scores) else 0.0, scores
