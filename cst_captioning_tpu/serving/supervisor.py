"""Process-fleet supervisor: OS-process serve.py replicas, exit-taxonomy
lifecycle, crash-proof requeue, blackbox harvest from dead replicas.

PR 13's :class:`fleet.FleetRouter` self-heals N engine replicas inside
ONE process — a single native-stack abort still kills the whole fleet.
This module moves the failure domain to the OS process: the supervisor
owns N **child processes**, each a real ``scripts/serve.py`` speaking
the existing JSONL wire over the localhost socket front end
``server.py`` already has — the wire format, streaming, deadlines, and
result semantics are unchanged; a client of ``scripts/serve_supervisor.
py`` cannot tell a process fleet from one engine except by what
survives a kill.

**Lifecycle is the exit taxonomy** (``resilience/exitcodes.classify``):

- ``resumable`` (75 preempted, 137 SIGKILL, 143 SIGTERM) and ``wedge``
  (124) child exits → restart with bounded exponential backoff
  (``backoff_ms`` base, doubling, capped) and **requeue of the dead
  replica's in-flight requests**: arrival clocks are preserved (the
  supervisor measures latency from its own intake, and forwards the
  REMAINING TTL to the new owner), and the re-decode is the same
  deterministic program on the same inputs — captions bit-identical to
  a fault-free twin.  The restart does not consume budget: resumable is
  the taxonomy's "try again" verdict.
- ``fatal`` (1, 130, uncatalogued) child exits consume the
  ``restart_limit`` budget; a replica past budget is ``dead``.  When
  EVERY replica is dead, :class:`SupervisorUnrecoverable` maps onto
  exit 124 at the front end — supervised restart one level up, exactly
  the signal this supervisor consumes from its own children.
- A replica that goes line-silent with work owed for longer than
  ``wedge_timeout_s`` is wedge-killed from OUTSIDE and classified as
  exit 124: a SIGSTOP'd child cannot run its own watchdog (every
  thread is frozen), so the supervisor enforces the same timeout the
  child's ``--wedge_timeout`` enforces internally — both roads lead to
  the one ``wedge`` classification and the one restart path.

**Streaming across a process death** stays prefix-consistent via
supervisor-level watermarks (the PR 13 discipline lifted across the
process boundary): per request, ``sent_tokens`` counts tokens already
forwarded to the client and ``cur_tokens`` counts tokens received from
the CURRENT owner; a requeued request re-decodes from step 0 on its new
child, the replayed tokens fall inside the watermark and are sliced
off (tokens and text in lockstep — ``Vocab.decode`` is one word per
non-zero token, so the text fragments concatenate to the final caption
bit for bit), and ``seq`` is re-issued supervisor-side.

**Every child death leaves evidence**: on a DELIBERATE kill the
supervisor first issues ``{"op": "dump"}`` (the child's flight recorder
lands ``blackbox.json``) with a bounded grace, then SIGKILLs; after any
death it harvests the child workdir's ``blackbox.json`` /
``heartbeat.json`` / ``telemetry.json`` / ``stderr.log`` into a
per-incident directory ``incidents/<NNN>_replica<K>_rc<RC>/`` with an
``incident.json`` index (RESILIENCE.md "Process faults";
``scripts/collect_evidence.py`` bundles these).

**One fleet health plane**: the supervisor polls ``{"op": "health"}``
per child, folds its own lifecycle view (restarts, backoff, budget) on
top, and serves worst-of-replicas + per-replica detail — the policies
(healthy-tier-first placement, route-around-degraded, fleet-edge
deadline shed) are the EXTRACTED router policies of
:mod:`serving.policy`, shared with :class:`fleet.FleetRouter` rather
than re-derived.

**The fleet observability plane rides the tick** (ISSUE 17,
:mod:`telemetry.fleetobs` / OBSERVABILITY.md "Fleet plane"): when the
supervisor is armed with a ``fleet_obs`` collaborator it stamps
per-request trace context onto the child wire (children echo it through
their lifecycle events so ``scripts/fleet_trace.py`` can stitch one
Perfetto track across the process boundary), answers timestamped
``{"op": "ping"}`` echoes into a per-process clock-skew table, scrapes
every replica slot's stats/health on a cadence into
``fleet_metrics.jsonl``, and evaluates SLO burn rates whose firing
alerts degrade the fleet health view.  All child-facing queries —
health polls included — go through the ONE paced
:meth:`ProcessFleetSupervisor.query_child` path with
:class:`serving.policy.QueryPacer` interval/backoff policy.

**Chaos is first-class** (RESILIENCE.md): ``proc_kill@replica=K`` →
SIGKILL (dump-before-kill), ``proc_wedge@replica=K`` → SIGSTOP until
the wedge timeout fires the 124 path, ``proc_preempt@replica=K`` →
SIGTERM (the child's own drain contract: residents complete, its queue
comes back ``rejected_draining`` and is REQUEUED — the fleet is not
draining — then exit 75).  Each fires once, at the first tick where the
target replica has in-flight work and has emitted at least one
response line (deterministically "mid-work").

Threading mirrors the server: reader threads (one per child socket, one
per client connection) only move lines; the single scheduler loop owns
every replica and request.  Shared with the watchdog/heartbeat thread
are ONLY the snapshot table (``serving.supervisor.health`` lock) and
the parked-request list (``serving.supervisor.requeue`` lock), in the
declared LOCK_ORDER below.  Restart spawns run on short-lived helper
threads that touch nothing but the launcher and a thread-safe hatch
queue — a mid-traffic restart (seconds of jax import in the child)
never stalls the scheduler loop.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import re
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

from ..resilience.exitcodes import (EXIT_OK, EXIT_PREEMPTED, EXIT_SIGTERM,
                                    EXIT_WEDGE, classify, describe, normalize)
from ..resilience.integrity import atomic_json_write
from ..utils.locksan import declare_order, named_lock
from .policy import (QueryPacer, deadline_unmeetable, rank_key,
                     worst_status)

log = logging.getLogger("cst_captioning_tpu.serving.supervisor")

#: Supervisor-level counters (declared at 0 — registry.declare;
#: SERVING.md "Process fleet" pins this table the way FLEET_COUNTERS
#: is pinned).
SUPERVISOR_COUNTERS = (
    "sup_requests",           # client caption/stream requests accepted
    "sup_routed",             # successful placements at a child
    "sup_rerouted",           # placed at a non-first candidate / re-placed
    "sup_requeued",           # in-flight moved off a dead/draining child
    "sup_parked",             # held while no live child could take work
    "sup_shed",               # fleet-edge sheds (incl. deadline shed)
    "sup_replica_restarts",   # child restarts performed
    "sup_replica_deaths",     # replicas dead past the fatal-exit budget
    "sup_wedge_kills",        # line-silent children killed as exit 124
    "sup_incidents",          # incident bundles harvested
    "sup_bad_lines",          # unparseable/unattributable child lines
    "sup_replicas_added",     # autoscale grow: new replica slots spawned
    "sup_replicas_retired",   # autoscale shrink: slots drained out
    "sup_journal_appends",    # intake-journal records fsync'd (ISSUE 20)
    "sup_journal_replayed",   # pre-crash requests re-submitted at relaunch
    "sup_journal_dup_hits",   # duplicate ids answered from the journal
    "sup_journal_attached",   # duplicate ids attached to an open stream
    "sup_journal_torn",       # torn journal records dropped at recovery
)

#: Declared acquisition order (cstlint:lock-order + the runtime
#: sanitizer): the health snapshot lock may nest the parked-list lock
#: (a health render that reads the parked depth), and either may reach
#: the registry's project-wide leaf — never the reverse.
LOCK_ORDER = ("serving.supervisor.health", "serving.supervisor.requeue",
              "telemetry.registry")
declare_order(*LOCK_ORDER)

#: The front end's write-before-conn law, same as serving/server.py:
#: whole response lines serialize under the server-wide write lock,
#: then the per-connection send lock.
FRONTEND_LOCK_ORDER = ("serving.supervisor.write",
                       "serving.supervisor.conn")
declare_order(*FRONTEND_LOCK_ORDER)

#: The socket child's startup announcement (serving/server.run_socket).
_PORT_RE = re.compile(r"serve: listening on 127\.0\.0\.1:(\d+)")


class SupervisorUnrecoverable(RuntimeError):
    """Every replica is dead and the fatal-exit budget is spent: this
    supervisor's supervision is exhausted.  The front end maps this
    onto ``exitcodes.EXIT_WEDGE`` (124) — the same supervised-restart
    signal the supervisor consumes from its own children."""


class ChildStartupError(RuntimeError):
    """A child exited or never announced its port during startup."""


# ---------------------------------------------------------------------------
# the real child transport
# ---------------------------------------------------------------------------


class ServeChild:
    """One serve.py OS process + its line transport: the duck-typed
    child handle the supervisor drives (tests substitute an in-process
    fake with the same surface).  The surface: ``send_line`` /
    ``lines`` / ``poll`` / ``terminate`` / ``kill`` / ``stop`` /
    ``cont`` / ``close``, plus ``workdir`` and ``pid``.  A reader
    thread moves socket lines into a thread-safe inbox; everything
    else runs on the supervisor's scheduler loop."""

    def __init__(self, proc: subprocess.Popen, sock: socket.socket,
                 workdir: str, replica: int, stderr_path: str):
        self.proc = proc
        self.workdir = workdir
        self.replica = int(replica)
        self.stderr_path = stderr_path
        self._sock = sock
        self._inbox: "queue.Queue[str]" = queue.Queue()
        threading.Thread(target=self._read,
                         name=f"sup-child-{replica}", daemon=True).start()

    @property
    def pid(self) -> int:
        return self.proc.pid

    def _read(self) -> None:
        try:
            with self._sock.makefile("r", encoding="utf-8",
                                     errors="replace") as f:
                for line in f:
                    self._inbox.put(line)
        except (OSError, ValueError):
            pass  # socket died with the child; poll() reports the exit

    def send_line(self, line: str) -> None:
        """Raises OSError when the child's socket is gone — the caller
        routes around and the next poll reaps the exit."""
        self._sock.sendall(line.encode() + b"\n")

    def lines(self) -> List[str]:
        out: List[str] = []
        while True:
            try:
                out.append(self._inbox.get_nowait())
            except queue.Empty:
                return out

    def poll(self) -> Optional[int]:
        rc = self.proc.poll()
        return None if rc is None else normalize(rc)

    def terminate(self) -> None:
        self.proc.terminate()

    def kill(self) -> None:
        self.proc.kill()

    def stop(self) -> None:
        os.kill(self.proc.pid, signal.SIGSTOP)

    def cont(self) -> None:
        os.kill(self.proc.pid, signal.SIGCONT)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            # Reap the zombie; bounded — a stuck child was SIGKILLed
            # by the caller before close.
            self.proc.wait(timeout=10)
        except (subprocess.TimeoutExpired, OSError):
            pass


def spawn_serve_child(argv: List[str], workdir: str, replica: int, *,
                      env: Optional[Dict[str, str]] = None,
                      startup_timeout_s: float = 180.0,
                      new_session: bool = False) -> ServeChild:
    """Spawn one serve.py child in socket mode and connect to it.

    The child's stderr goes to ``<workdir>/stderr.log`` (harvestable
    after a crash — no pipe to drain, no reader thread to leak); the
    ephemeral port (``--serve_port -1``) is scraped from that file's
    ``serve: listening on 127.0.0.1:<port>`` announcement.  Raises
    :class:`ChildStartupError` when the child exits or stays silent
    past ``startup_timeout_s`` (jax import + warm compile dominate).
    ``new_session=True`` gives the child its own process group — the
    journal drill (ISSUE 20) spawns a whole SUPERVISOR this way so one
    ``killpg`` takes the coordinator and its children down together,
    the worst-case death the intake journal must survive."""
    os.makedirs(workdir, exist_ok=True)
    stderr_path = os.path.join(workdir, "stderr.log")
    with open(stderr_path, "w") as errf:
        proc = subprocess.Popen(argv, stdin=subprocess.DEVNULL,
                                stdout=subprocess.DEVNULL, stderr=errf,
                                env=env, start_new_session=new_session)
    deadline = time.monotonic() + startup_timeout_s
    port = None
    while time.monotonic() < deadline:
        rc = proc.poll()
        if rc is not None:
            raise ChildStartupError(
                f"replica {replica} exited {normalize(rc)} "
                f"({describe(normalize(rc))}) during startup; see "
                f"{stderr_path}")
        try:
            with open(stderr_path) as f:
                m = _PORT_RE.search(f.read())
        except OSError:
            m = None
        if m:
            port = m.group(1)
            break
        time.sleep(0.05)
    if port is not None:
        port = int(port)
    else:
        proc.kill()
        raise ChildStartupError(
            f"replica {replica} never announced its port within "
            f"{startup_timeout_s:.0f}s; see {stderr_path}")
    sock = socket.create_connection(("127.0.0.1", port), timeout=30)
    log.info("supervisor: replica %d up (pid %d, port %d)", replica,
             proc.pid, port)
    return ServeChild(proc, sock, workdir, replica, stderr_path)


# ---------------------------------------------------------------------------
# supervisor bookkeeping
# ---------------------------------------------------------------------------


@dataclass
class ProxyRequest:
    """One client request in flight across the fleet."""

    sup_id: str                 # supervisor-unique wire id (child-facing)
    client_id: Any              # the client's id, restored on every answer
    video_id: str
    stream: bool
    respond: Callable[[Dict[str, Any]], None]
    arrival: float              # supervisor-intake monotonic clock
    ttl_ms: Optional[float]     # client TTL; remaining is forwarded
    no_cache: bool = False
    replica: Optional[int] = None
    tried: Set[int] = field(default_factory=set)
    sent_tokens: int = 0        # stream watermark: tokens the client has
    cur_tokens: int = 0         # tokens received from the CURRENT owner
    seq_out: int = 0            # supervisor-issued stream sequence
    requeues: int = 0
    key: Optional[str] = None   # intake-journal idempotency key (ISSUE 20)
    attached: bool = True       # False: journal-replayed, no live client

    def remaining_ms(self, now: float) -> Optional[float]:
        if self.ttl_ms is None:
            return None
        return self.ttl_ms - (now - self.arrival) * 1e3


class ProcReplica:
    """Supervisor-side bookkeeping for one OS-process replica slot.
    ``state``: ``starting`` (spawn in flight) → ``ok`` (serving) →
    ``backoff`` (dead, restart scheduled) → ``dead`` (budget spent) —
    plus ``drained`` once a fleet drain retires it, and ``retired``
    once an autoscale scale-down drains the slot out of service
    (terminal like ``dead``, but deliberate: it never degrades fleet
    health and is never restarted)."""

    def __init__(self, index: int):
        self.index = int(index)
        self.child = None
        self.workdir: Optional[str] = None
        self.state = "starting"
        self.restarts = 0          # restarts performed
        self.fatal_spent = 0       # fatal exits charged against budget
        self.kills = 0             # deliberate supervisor kills
        self.backoff_level = 0     # consecutive deaths since a completion
        self.backoff_until = 0.0
        self.last_line_t = 0.0     # wedge detection: last line seen
        self.lines_seen = 0        # response lines since (re)start
        self.inflight: Set[str] = set()
        self.health: Dict[str, Any] = {}
        self.compiles0: Optional[int] = None   # first post-warm compile count
        self.last_stats: Optional[Dict[str, Any]] = None
        self.last_rc: Optional[int] = None
        self.completed = 0
        self.kill_at: Optional[float] = None   # pending deliberate-kill
                                               # deadline (real monotonic)
        self.retiring = False      # autoscale drain-out in progress

    @property
    def live(self) -> bool:
        return self.state == "ok" and self.child is not None


class ProcessFleetSupervisor:
    """Own N serve.py OS-process replicas (module docstring).

    ``launcher(replica_index) -> child`` builds one replica's child
    handle (:func:`spawn_serve_child` for the real CLI; tests pass a
    fake factory).  All child-facing state is single-owner on the
    scheduler loop; see LOCK_ORDER for the two shared structures."""

    def __init__(self, launcher: Callable[[int], Any], replicas: int, *,
                 restart_limit: int = 3, backoff_ms: float = 200.0,
                 backoff_cap_ms: float = 5000.0,
                 wedge_timeout_s: float = 0.0,
                 health_interval_s: float = 0.5,
                 dump_grace_s: float = 2.0,
                 incident_dir: Optional[str] = None,
                 fault_plan=None, registry=None, lifecycle=None,
                 fleet_obs=None, autoscaler=None, journal=None,
                 clock: Callable[[], float] = time.monotonic,
                 spawn_async: bool = True):
        n = int(replicas)
        if n < 1:
            raise ValueError(f"a process fleet needs >= 1 replica, got {n}")
        self._launcher = launcher
        self.restart_limit = max(0, int(restart_limit))
        self.backoff_ms = max(0.0, float(backoff_ms))
        self.backoff_cap_ms = max(self.backoff_ms, float(backoff_cap_ms))
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.health_interval_s = float(health_interval_s)
        self.dump_grace_s = float(dump_grace_s)
        self.incident_dir = incident_dir
        self._plan = fault_plan
        self._registry = registry
        self._lifecycle = lifecycle
        # Optional fleet observability plane (telemetry/fleetobs.py,
        # ISSUE 17): trace-context stamping, clock pings, the metrics
        # scraper and the SLO monitor all hang off this one hook —
        # None costs one is-None check per call site (the house rule),
        # and keeps the wire byte-identical for unarmed fleets.
        self._fleet_obs = fleet_obs
        # Optional autoscaler (serving/autoscale.py, ISSUE 19): rides
        # the tick right after the scraper, grows/shrinks the slot list
        # through add_replica()/retire_worst(), and its brownout rung
        # tightens the shed paths — same one-is-None-check-per-site
        # rule as fleet_obs.
        self._autoscaler = autoscaler
        # Optional durable intake journal (serving/journal.py, ISSUE
        # 20): accepts are fsync'd BEFORE placement, stream chunks and
        # terminals at send time, so the supervisor process itself
        # becomes a survivable failure domain — same one-is-None-check-
        # per-site rule as fleet_obs/autoscaler.
        self._journal = journal
        self.clock = clock
        self.spawn_async = spawn_async
        # Single-owner scheduler state (the module-docstring contract).
        self._replicas: List[ProcReplica] = [  # cstlint: owned_by=scheduler
            ProcReplica(k) for k in range(n)]
        self._pending: Dict[str, ProxyRequest] = {}  # cstlint: owned_by=scheduler
        # Journal idempotency keys of OPEN requests -> their in-flight
        # ProxyRequest (duplicate submits attach here; ISSUE 20).
        self._inflight_keys: Dict[str, ProxyRequest] = {}  # cstlint: owned_by=scheduler
        self._incidents: List[Dict[str, Any]] = []  # cstlint: owned_by=scheduler
        self._seq = 0
        self._completed = 0
        self._latencies_ms: List[float] = []  # cstlint: owned_by=scheduler
        self._draining = False  # cstlint: owned_by=scheduler
        # Health polling rides the SHARED child-query pacing policy
        # (serving/policy.QueryPacer — the ISSUE 17 satellite): the
        # same interval/backoff object family the fleet scraper uses,
        # so "how often do we poke a child" cannot fork between the
        # health plane and the metrics plane.  A never-polled child is
        # due immediately (first-tick semantics preserved).
        self._health_pacer = QueryPacer(self.health_interval_s)
        self._dirty = True
        # Restart spawns hatch through a thread-safe queue: the helper
        # thread touches ONLY the launcher and this queue.
        self._hatch: "queue.Queue" = queue.Queue()
        self._spawning: Set[int] = set()  # cstlint: owned_by=scheduler
        # Shared with the watchdog/heartbeat thread, in LOCK_ORDER.
        self._health_lock = named_lock("serving.supervisor.health")
        self._requeue_lock = named_lock("serving.supervisor.requeue")
        self._snapshots: List[Dict[str, Any]] = []  # cstlint: guarded_by=self._health_lock
        self._totals: Dict[str, Any] = {}  # cstlint: guarded_by=self._health_lock
        self._parked: List[ProxyRequest] = []  # cstlint: guarded_by=self._requeue_lock
        self._c = {name: 0 for name in SUPERVISOR_COUNTERS}
        if registry is not None:
            registry.declare(*SUPERVISOR_COUNTERS)
        # Boot the fleet serially and synchronously: deterministic, and
        # a replica that cannot even START is a configuration error the
        # operator must see immediately, not a backoff loop.
        for rep in self._replicas:
            self._assign_child(rep, self._launcher(rep.index))
        self._update_snapshots()

    # -- counters ----------------------------------------------------------

    def _inc(self, name: str, n: int = 1) -> None:
        self._c[name] += n
        if self._registry is not None:
            self._registry.inc(name, n)

    def supervisor_counters(self) -> Dict[str, int]:
        """The ONE definition of the supervisor's audit view (the
        fleet_counters discipline: stats, health, the probe record, and
        serve_report all render exactly this dict)."""
        return dict(self._c)

    # -- lifecycle: spawn / death / restart --------------------------------

    def _assign_child(self, rep: ProcReplica, child) -> None:
        rep.child = child
        rep.workdir = getattr(child, "workdir", None)
        rep.state = "ok"
        rep.last_line_t = self.clock()
        rep.lines_seen = 0
        rep.health = {}
        rep.compiles0 = None
        rep.last_stats = None
        # A fresh OS process: poll it immediately, and let the fleet
        # plane drop the dead generation's pacing history and in-flight
        # clock pings (skew is per process — re-measured per restart).
        self._health_pacer.forget(rep.index)
        if self._fleet_obs is not None:
            self._fleet_obs.on_child_assigned(rep.index)
        self._dirty = True

    def _spawn_failed(self, rep: ProcReplica, err: BaseException) -> None:
        """A restart that could not even start is charged like a fatal
        exit — a replica crash-looping in its launcher must not spin
        free forever."""
        log.error("supervisor: replica %d failed to start: %s",
                  rep.index, err)
        rep.fatal_spent += 1
        if rep.fatal_spent > self.restart_limit:
            self._mark_dead(rep)
        else:
            self._schedule_restart(rep)

    def _schedule_restart(self, rep: ProcReplica) -> None:
        """Bounded exponential backoff: ``backoff_ms * 2^level`` capped
        at ``backoff_cap_ms``; the level resets when the replica next
        completes a request (it is healthy again)."""
        rep.state = "backoff"
        rep.backoff_level += 1
        delay_ms = min(self.backoff_ms * (2 ** (rep.backoff_level - 1)),
                       self.backoff_cap_ms)
        rep.backoff_until = self.clock() + delay_ms / 1e3
        self._dirty = True
        log.warning("supervisor: replica %d restarting in %.0fms "
                    "(death %d since last healthy completion)",
                    rep.index, delay_ms, rep.backoff_level)

    def _mark_dead(self, rep: ProcReplica) -> None:
        rep.state = "dead"
        self._inc("sup_replica_deaths")
        log.error("supervisor: replica %d exhausted its fatal-exit "
                  "budget (%d) and is removed from service", rep.index,
                  self.restart_limit)
        self._dirty = True
        self._check_unrecoverable()

    def _check_unrecoverable(self) -> None:
        if self._draining:
            return
        if all(r.state in ("dead", "drained", "retired")
               for r in self._replicas):
            raise SupervisorUnrecoverable(
                "every replica is dead (fatal-exit budget "
                f"{self.restart_limit} exhausted fleet-wide)")

    def _restart_due(self, now: float) -> None:
        for rep in self._replicas:
            if rep.state != "backoff" or now < rep.backoff_until:
                continue
            if rep.index in self._spawning:
                continue
            rep.restarts += 1
            self._inc("sup_replica_restarts")
            rep.state = "starting"
            self._dirty = True
            if not self.spawn_async:
                try:
                    child = self._launcher(rep.index)
                except Exception as e:
                    self._spawn_failed(rep, e)
                else:
                    self._assign_child(rep, child)
                continue
            self._spawning.add(rep.index)

            def run(ix: int = rep.index) -> None:
                # Helper-thread body: ONLY the launcher and the hatch
                # queue — no supervisor state (thread-ownership law).
                try:
                    child = self._launcher(ix)
                except Exception as e:  # hatched as a failed start
                    self._hatch.put((ix, None, e))
                else:
                    self._hatch.put((ix, child, None))

            threading.Thread(target=run, name=f"sup-spawn-{rep.index}",
                             daemon=True).start()

    def _hatch_ready(self) -> None:
        while True:
            try:
                ix, child, err = self._hatch.get_nowait()
            except queue.Empty:
                return
            rep = self._replicas[ix]
            self._spawning.discard(ix)
            if self._draining:
                if child is not None:
                    try:
                        child.kill()
                    except OSError:
                        pass
                    child.close()
                rep.state = "drained"
                continue
            if err is not None:
                self._spawn_failed(rep, err)
                continue
            self._assign_child(rep, child)

    # -- autoscale: grow / shrink the slot list ----------------------------

    def active_replicas(self) -> int:
        """Slots still in service or coming up — the autoscaler's
        notion of fleet size (terminal slots don't count)."""
        return sum(1 for r in self._replicas
                   if r.state not in ("dead", "drained", "retired"))

    def add_replica(self) -> int:
        """Append one replica slot and spawn it through the existing
        warm child recipe (the launcher IS `spawn_serve_child` in real
        fleets, so the new child pays zero post-warmup compiles).
        Mirrors `_restart_due`'s sync/async split; returns the new slot
        index immediately — the child lands via `_hatch_ready` (async)
        or inline (sync)."""
        rep = ProcReplica(len(self._replicas))
        self._replicas.append(rep)
        self._inc("sup_replicas_added")
        self._dirty = True
        log.info("supervisor: autoscale adding replica %d", rep.index)
        if not self.spawn_async:
            try:
                child = self._launcher(rep.index)
            except Exception as e:
                self._spawn_failed(rep, e)
            else:
                self._assign_child(rep, child)
            return rep.index
        self._spawning.add(rep.index)

        def run(ix: int = rep.index) -> None:
            # Helper-thread body: ONLY the launcher and the hatch
            # queue — no supervisor state (thread-ownership law).
            try:
                child = self._launcher(ix)
            except Exception as e:  # hatched as a failed start
                self._hatch.put((ix, None, e))
            else:
                self._hatch.put((ix, child, None))

        threading.Thread(target=run, name=f"sup-spawn-{rep.index}",
                         daemon=True).start()
        return rep.index

    def retire_worst(self) -> Optional[int]:
        """Drain the worst-ranked live child out of service (autoscale
        scale-down).  Strictly drain-based: ``terminate()`` flips the
        child to draining, in-flight work finishes, queue rejections
        flow back as ``rejected_draining`` and requeue elsewhere, and
        the eventual exit lands in `_on_death`'s retiring path.  Picks
        via the SHARED ``policy.rank_key`` (degraded first, then most
        loaded, then highest index) so "worst" cannot fork between
        placement and retirement.  Refuses (returns None) when it
        would leave no serving candidate."""
        cands = [r for r in self._replicas
                 if r.live and not r.retiring and r.kill_at is None]
        if len(cands) <= 1:
            return None
        worst = max(cands, key=lambda r: rank_key(
            r.health.get("status") == "degraded",
            len(r.inflight), r.index))
        worst.retiring = True
        self._dirty = True
        log.info("supervisor: autoscale retiring replica %d (drain, "
                 "%d in flight)", worst.index, len(worst.inflight))
        try:
            worst.child.terminate()
        except OSError:
            pass
        return worst.index

    def _reap_exits(self) -> None:
        for rep in self._replicas:
            if rep.child is None:
                continue
            rc = rep.child.poll()
            if rc is not None:
                self._on_death(rep, rc)

    def _on_death(self, rep: ProcReplica, rc: int, *,
                  wedged: bool = False) -> None:
        """The one exit path for a dead child: harvest evidence, move
        its in-flight requests, classify, schedule what comes next."""
        child = rep.child
        # Drain the last buffered lines BEFORE declaring the requests
        # orphaned: a drained child's final completions/rejections are
        # already in the inbox and must reach their clients.
        self._pump_one(rep)
        rep.last_rc = rc
        cls = "wedge" if wedged else classify(rc)
        log.warning("supervisor: replica %d exited %d (%s -> %s) with "
                    "%d in flight", rep.index, rc, describe(rc), cls,
                    len(rep.inflight))
        child.close()
        rep.child = None
        rep.kill_at = None
        self._dirty = True
        expected = ((self._draining or rep.retiring)
                    and cls in ("ok", "resumable"))
        if not expected:
            self._harvest_incident(rep, rc, cls)
        orphans = [self._pending[i] for i in sorted(rep.inflight)
                   if i in self._pending]
        rep.inflight.clear()
        if self._draining:
            # Mid-drain the fleet accepts no work: a child that died
            # before finishing answers its orphans the drain way.
            rep.state = "drained"
            for pr in orphans:
                self._answer_reject_draining(pr)
            return
        if rep.retiring:
            # A deliberate autoscale drain-out: the exit is the POINT,
            # so no restart and no budget charge.  A clean/resumable
            # exit retires the slot quietly; anything else already
            # harvested an incident above.  Orphans (a child that died
            # MID-drain with work aboard) fall through the ordinary
            # requeue below — exactly-once is preserved by the same
            # path a crash uses.
            rep.state = "retired"
            rep.retiring = False
            self._inc("sup_replicas_retired")
            log.info("supervisor: replica %d retired (autoscale "
                     "scale-down, rc=%d)", rep.index, rc)
            for pr in orphans:
                pr.requeues += 1
                pr.cur_tokens = 0
                pr.tried = {rep.index}
                self._inc("sup_requeued")
                if self._lifecycle is not None:
                    self._lifecycle.emit("killed", pr.sup_id,
                                         replica=rep.index, rc=rc)
                    self._lifecycle.emit("requeued", pr.sup_id)
                self._place(pr, reroute=True)
            return
        # Classify-then-schedule BEFORE requeue, so placement sees this
        # replica in its true (non-candidate) state.
        if cls == "fatal":
            rep.fatal_spent += 1
            if rep.fatal_spent > self.restart_limit:
                self._mark_dead(rep)
            else:
                self._schedule_restart(rep)
        else:
            # ok / resumable / wedge: restart free of budget — the
            # taxonomy's own "try again" verdict (an unexpected clean
            # exit 0 is restarted too: the fleet owes N replicas).
            self._schedule_restart(rep)
        for pr in orphans:
            pr.requeues += 1
            pr.cur_tokens = 0          # new owner re-decodes from step 0
            pr.tried = {rep.index}
            self._inc("sup_requeued")
            if self._lifecycle is not None:
                self._lifecycle.emit("killed", pr.sup_id,
                                     replica=rep.index, rc=rc)
                self._lifecycle.emit("requeued", pr.sup_id)
            self._place(pr, reroute=True)

    # -- evidence ----------------------------------------------------------

    def _harvest_incident(self, rep: ProcReplica, rc: int,
                          cls: str) -> None:
        """Bundle whatever the dead child left durable into a
        per-incident directory (RESILIENCE.md "Process faults"):
        blackbox.json (dumped before a deliberate kill, or written by
        the child's own 124/abort paths), heartbeat.json,
        telemetry.json, stderr.log, plus an incident.json index."""
        self._inc("sup_incidents")
        entry: Dict[str, Any] = {
            "replica": rep.index, "rc": rc, "classification": cls,
            "inflight": len(rep.inflight), "files": [],
        }
        if self.incident_dir and rep.workdir:
            name = (f"{len(self._incidents):03d}_replica{rep.index}"
                    f"_rc{rc}")
            d = os.path.join(self.incident_dir, name)
            try:
                os.makedirs(d, exist_ok=True)
                for fn in ("blackbox.json", "heartbeat.json",
                           "telemetry.json", "stderr.log"):
                    src = os.path.join(rep.workdir, fn)
                    if os.path.exists(src):
                        shutil.copyfile(src, os.path.join(d, fn))
                        entry["files"].append(fn)
                entry["dir"] = d
                atomic_json_write(os.path.join(d, "incident.json"),
                                  entry, indent=2)
            except OSError as e:
                # Evidence collection must never kill supervision.
                log.error("supervisor: incident harvest failed: %s", e)
        self._incidents.append(entry)

    def _dump_then_kill(self, rep: ProcReplica) -> None:
        """The deliberate-kill protocol: ask the child's flight
        recorder to land blackbox.json first (``{"op": "dump"}``),
        bounded grace, then SIGKILL.  The grace does NOT block the
        tick loop — a pending deadline is stamped and
        :meth:`_finish_pending_kills` lands the kill once the blackbox
        appears or the grace expires, so the health/scrape planes keep
        running through a deliberate kill (the fleet_report blackout
        gate caught the blocking version going dark).  Real wall-clock
        for the grace — a frozen test clock must not turn it into a
        wait that never expires."""
        try:
            rep.child.send_line(json.dumps({"op": "dump"}))
        except OSError:
            pass
        rep.kill_at = time.monotonic() + self.dump_grace_s

    def _finish_pending_kills(self) -> None:
        for rep in self._replicas:
            if rep.kill_at is None:
                continue
            if rep.child is None:
                rep.kill_at = None
                continue
            bb = (os.path.join(rep.workdir, "blackbox.json")
                  if rep.workdir else None)
            if (bb and os.path.exists(bb)) \
                    or time.monotonic() >= rep.kill_at:
                rep.kill_at = None
                rep.child.kill()    # reaped as 137 next tick

    # -- chaos -------------------------------------------------------------

    def _fire_proc_faults(self) -> None:
        if self._plan is None:
            return
        for rep in self._replicas:
            if not rep.live or not rep.inflight or rep.lines_seen == 0:
                # "Mid-work", deterministically: at least one request
                # in flight AND at least one response line emitted.
                continue
            if self._plan.fire_replica("proc_kill", rep.index):
                rep.kills += 1
                self._dump_then_kill(rep)       # reaped as 137 next tick
            elif self._plan.fire_replica("proc_wedge", rep.index):
                rep.child.stop()                # the wedge timer takes it
            elif self._plan.fire_replica("proc_preempt", rep.index):
                rep.child.terminate()           # child drains, exits 75

    def _check_wedges(self, now: float) -> None:
        """Line-silence wedge detection: a live child OWING work that
        has produced nothing for ``wedge_timeout_s`` is killed and
        classified exit 124 — the supervisor-side mirror of the child's
        own ``--wedge_timeout`` (which a SIGSTOP'd child cannot run)."""
        if self.wedge_timeout_s <= 0:
            return
        for rep in self._replicas:
            if not rep.live or not rep.inflight:
                continue
            if now - rep.last_line_t <= self.wedge_timeout_s:
                continue
            self._inc("sup_wedge_kills")
            rep.kills += 1
            log.error("supervisor: replica %d line-silent %.1fs with %d "
                      "in flight — wedge kill (-> %d)", rep.index,
                      now - rep.last_line_t, len(rep.inflight),
                      EXIT_WEDGE)
            try:
                rep.child.kill()   # SIGKILL works on a stopped process
            except OSError:
                pass
            self._on_death(rep, EXIT_WEDGE, wedged=True)

    # -- health plane ------------------------------------------------------

    def query_child(self, index: int, payload: Dict[str, Any]) -> bool:
        """The ONE child-query send path every timed poller routes
        through (health poll, fleet scraper, clock pings — the ISSUE 17
        share-one-path satellite): serialize, send, report success.  A
        dead socket answers False — the caller's pacer backs off and
        the next reap classifies the exit."""
        rep = self._replicas[int(index)]
        if not rep.live:
            return False
        try:
            rep.child.send_line(json.dumps(payload))
        except OSError:
            return False
        return True

    def _health_poll(self, now: float) -> None:
        for rep in self._replicas:
            if not rep.live:
                continue
            if not self._health_pacer.due(rep.index, now):
                continue
            self._health_pacer.sent(rep.index, now)
            if not self.query_child(rep.index, {"op": "health"}):
                self._health_pacer.failed(rep.index)

    def request_stats(self, index: int) -> bool:
        """Ask replica ``index`` for ``{"op": "stats"}``; the reply
        lands in its ``last_stats`` on a later tick (probe use)."""
        return self.query_child(index, {"op": "stats"})

    def dump_children(self) -> int:
        """Forward ``{"op": "dump"}`` to every live child (the fleet
        forensic snapshot behind the front end's dump op); returns how
        many children were asked."""
        n = 0
        for rep in self._replicas:
            if not rep.live:
                continue
            try:
                rep.child.send_line('{"op": "dump"}')
                n += 1
            except OSError:
                pass
        return n

    def scrape_snapshot(self) -> Dict[str, Any]:
        """The fleet scraper's per-tick view (telemetry/fleetobs.py):
        one entry per replica SLOT regardless of state — live,
        restarting or dead — so the scraped series has zero per-replica
        gaps across a child restart; the latest health/stats replies
        ride along.  Scheduler thread only."""
        with self._requeue_lock:
            parked = len(self._parked)
        lat = sorted(self._latencies_ms)

        def pct(q: float) -> Optional[float]:
            if not lat:
                return None
            ix = min(len(lat) - 1, int(round(q / 100 * (len(lat) - 1))))
            return round(lat[ix], 3)

        children = []
        for rep in self._replicas:
            children.append({
                "index": rep.index, "state": rep.state, "live": rep.live,
                "restarts": rep.restarts,
                "inflight": len(rep.inflight),
                "retiring": rep.retiring,
                "pid": (rep.child.pid if rep.child is not None else None),
                "health": dict(rep.health),
                "stats": (dict(rep.last_stats)
                          if rep.last_stats is not None else None),
            })
        fleet = {
            "replicas": len(self._replicas),
            "active": self.active_replicas(),
            "in_service": sum(1 for r in self._replicas if r.live),
            "outstanding": len(self._pending),
            "parked": parked,
            "completed": self._completed,
            "latency_p50_ms": pct(50),
            "latency_p99_ms": pct(99),
            "supervisor": self.supervisor_counters(),
        }
        if self._autoscaler is not None:
            fleet["autoscale"] = self._autoscaler.status()
        return {"fleet": fleet, "children": children}

    def _update_snapshots(self) -> None:
        snaps: List[Dict[str, Any]] = []
        for rep in self._replicas:
            h = rep.health
            if rep.state == "ok":
                status = h.get("status", "ok")
            elif rep.state in ("starting", "backoff"):
                status = "restarting"
            elif rep.state == "retired":
                status = "retired"
            else:
                status = "dead"
            snaps.append({
                "replica": rep.index, "status": status,
                "state": rep.state,
                "queue_depth": h.get("queue_depth") or 0,
                "residents": h.get("residents") or 0,
                "inflight": len(rep.inflight),
                "completed": rep.completed,
                "restarts": rep.restarts, "kills": rep.kills,
                "fatal_spent": rep.fatal_spent,
                "last_rc": rep.last_rc,
                "compiles": h.get("compiles"),
                # The post-warm baseline (first health after (re)start):
                # compiles - compiles0 is the replica's recompile count,
                # readable over the wire by the journal drill (ISSUE 20).
                "compiles0": rep.compiles0,
                "min_service_ms": h.get("min_service_ms"),
                "pid": (rep.child.pid if rep.child is not None
                        else None),
            })
        totals = {
            "outstanding": len(self._pending),
            "completed": self._completed,
            "incidents": len(self._incidents),
        }
        with self._health_lock:
            self._snapshots = snaps
            self._totals = totals

    def health_payload(self) -> Dict[str, Any]:
        """The fleet health view: worst-of-replicas plus per-replica
        detail, the supervisor's lifecycle folded in.  Snapshot-backed
        — safe from the watchdog's heartbeat thread while the
        scheduler owns the children (LOCK_ORDER: health then requeue,
        never the reverse)."""
        with self._health_lock:
            per = [dict(s) for s in self._snapshots]
            totals = dict(self._totals)
            with self._requeue_lock:
                parked = len(self._parked)
        # A retired slot is a DELIBERATE absence (autoscale scale-down)
        # — it must never degrade the worst-of view the way a dead or
        # restarting slot does.  All-retired cannot outlive a tick
        # (_check_unrecoverable), so the filtered view stays honest.
        status = worst_status(s["status"] for s in per
                              if s["status"] != "retired")
        out: Dict[str, Any] = {}
        if self._fleet_obs is not None:
            if self._fleet_obs.alerting:
                # A fast-burning SLO is a fleet-health fact: the
                # worst-of view degrades while the alert is firing
                # (ISSUE 17), even when every replica reports ok.
                status = worst_status((status, "degraded"))
            out["slo"] = self._fleet_obs.slo_status()
        return {
            **out,
            "status": status,
            "replicas": len(per),
            "in_service": sum(1 for s in per
                              if s["status"] in ("ok", "degraded")),
            "queue_depth": sum(s["queue_depth"] for s in per),
            "residents": sum(s["residents"] for s in per),
            "outstanding": totals.get("outstanding", 0),
            "parked": parked,
            "completed": totals.get("completed", 0),
            "supervisor": self.supervisor_counters(),
            "per_replica": per,
        }

    def stats(self) -> Dict[str, Any]:
        """The probe/report view (scheduler thread)."""
        self._update_snapshots()
        with self._health_lock:
            per = [dict(s) for s in self._snapshots]
        with self._requeue_lock:
            parked = len(self._parked)
        lat = sorted(self._latencies_ms)

        def pct(q: float) -> Optional[float]:
            if not lat:
                return None
            ix = min(len(lat) - 1, int(round(q / 100 * (len(lat) - 1))))
            return round(lat[ix], 3)

        out = {
            "replicas": len(self._replicas),
            "active": self.active_replicas(),
            "in_service": sum(1 for r in self._replicas if r.live),
            "outstanding": len(self._pending),
            "parked": parked,
            "completed": self._completed,
            "latency_p50_ms": pct(50),
            "latency_p99_ms": pct(99),
            "supervisor": self.supervisor_counters(),
            "per_replica": per,
            "incidents": [dict(i) for i in self._incidents],
        }
        if self._fleet_obs is not None:
            out["slo"] = self._fleet_obs.slo_status()
        if self._autoscaler is not None:
            out["autoscale"] = self._autoscaler.status()
        if self._journal is not None:
            out["journal"] = self._journal.stats()
        return out

    # -- routing -----------------------------------------------------------

    def submit(self, client_id: Any, video_id: str, *,
               respond: Callable[[Dict[str, Any]], None],
               stream: bool = False, deadline_ms: Optional[float] = None,
               no_cache: bool = False, idem: Optional[str] = None,
               have_seq: Optional[int] = None) -> None:
        """Accept one client request; every path answers eventually
        (immediate shed/expiry answers now, through ``respond``).

        With the intake journal armed (ISSUE 20) every request carries
        an idempotency key — the wire's ``idem`` field, or
        ``"<id>|<video_id>"`` when the client sent none.  A duplicate
        of an already-TERMINAL key is answered from the journal with
        zero decode work (``idempotent: true``); a duplicate of an
        OPEN key attaches this channel to the in-flight request,
        catching it up from the journaled chunk marks past
        ``have_seq``.  Fresh accepts are fsync'd BEFORE placement."""
        key = None
        if self._journal is not None:
            key = str(idem) if idem is not None \
                else f"{client_id}|{video_id}"
            prev = self._journal.terminal_for(key)
            if prev is not None:
                self._inc("sup_journal_dup_hits")
                out = dict(prev)
                out["id"] = client_id
                out["idempotent"] = True
                respond(out)
                return
            live = self._inflight_keys.get(key)
            if live is not None:
                self._attach(live, respond, have_seq)
                return
        self._seq += 1
        pr = ProxyRequest(
            sup_id=f"s{self._seq}", client_id=client_id,
            video_id=str(video_id), stream=bool(stream), respond=respond,
            arrival=self.clock(),
            ttl_ms=(None if deadline_ms is None else float(deadline_ms)),
            no_cache=bool(no_cache), key=key)
        self._inc("sup_requests")
        self._pending[pr.sup_id] = pr
        if key is not None:
            # Accept-before-placement: once this append returns, a
            # supervisor crash cannot lose the request.
            self._inflight_keys[key] = pr
            self._journal.accept(
                key, client_id, pr.video_id, stream=pr.stream,
                ttl_ms=pr.ttl_ms, no_cache=pr.no_cache)
            self._inc("sup_journal_appends")
        if self._lifecycle is not None:
            self._lifecycle.emit("received", pr.sup_id,
                                 client_id=client_id, video_id=video_id)
        if self._draining:
            self._answer_reject_draining(pr)
            return
        if (pr.stream and self._autoscaler is not None
                and self._autoscaler.brownout_rung() >= 3):
            # Brownout rung 3 (the last before collapse): new stream
            # ops — the long-held, token-by-token kind — are rejected
            # at intake with a typed shed; one-shot captions still
            # flow through admission.
            self._autoscaler.note_shed("stream")
            self._inc("sup_shed")
            self._finish(pr, {"id": pr.client_id, "error": "shed",
                              "video_id": pr.video_id,
                              "why": "brownout_stream"},
                         "shed", where="fleet",
                         reason="brownout_stream")
            return
        self._place(pr)

    def _attach(self, pr: ProxyRequest, respond: Callable[[Dict[str, Any]],
                None], have_seq: Optional[int]) -> None:
        """A duplicate submit of an OPEN key adopts the new channel:
        the journaled chunk marks past ``have_seq`` (all of them when
        the client sent none) are replayed first, then live chunks and
        the terminal flow to this channel — a prefix-consistent
        continuation no matter where the reconnect fell."""
        self._inc("sup_journal_attached")
        pr.respond = respond
        pr.attached = True
        if pr.stream and pr.key is not None:
            floor = -1 if have_seq is None else int(have_seq)
            for m in self._journal.marks_for(pr.key):
                if m["seq"] <= floor:
                    continue
                pr.respond({"id": pr.client_id, "video_id": pr.video_id,
                            "stream": True, "seq": m["seq"],
                            "tokens": list(m["tokens"]),
                            "text": m["text"], "final": False})
        if self._lifecycle is not None:
            self._lifecycle.emit("queued", pr.sup_id,
                                 where="journal_attach")

    def replay_journal(self) -> Dict[str, Any]:
        """Re-enter every accepted-but-unanswered pre-crash request
        into the serving plane (called once by the front end right
        after construction, children already live).  Arrival clocks
        and remaining TTLs are preserved across the process death via
        the journal's wall clock; stream watermarks are primed from
        the journaled marks so continuation chunks start exactly where
        the dead supervisor stopped sending.  Returns the recovery
        ledger document (auditable via the blackbox/incident
        machinery)."""
        if self._journal is None:
            return {"schema": 1, "enabled": False}
        rec = self._journal.recovery
        if rec.torn_records:
            self._inc("sup_journal_torn", rec.torn_records)
        now = self.clock()
        replayed: List[Dict[str, Any]] = []
        for acc in self._journal.open_requests():
            key = acc["key"]
            self._seq += 1
            # Wall-clock delta is the ONLY clock that survives the
            # dead process (monotonic-deadline's exemption: the
            # journal's injected wall clock, not bare time.time()); it
            # rebases the arrival into THIS incarnation's monotonic
            # domain, never into a deadline comparison directly.
            elapsed_s = max(
                self._journal.wall() - acc["arrival_wall"], 0.0)
            pr = ProxyRequest(
                sup_id=f"s{self._seq}", client_id=acc["client_id"],
                video_id=acc["video_id"], stream=bool(acc["stream"]),
                respond=lambda obj: None,   # detached until a client
                arrival=now - elapsed_s,    # re-submits the same key
                ttl_ms=acc["ttl_ms"], no_cache=bool(acc["no_cache"]),
                key=key, attached=False)
            marks = self._journal.marks_for(key)
            if marks:
                last = marks[-1]
                # cstlint: disable=device-scalar-fetch -- journaled JSON mark fields: host ints, never device arrays
                pr.sent_tokens = int(last["sent_tokens"])
                # cstlint: disable=device-scalar-fetch -- journaled JSON mark fields: host ints, never device arrays
                pr.seq_out = int(last["seq"]) + 1
            self._inc("sup_requests")
            self._inc("sup_journal_replayed")
            self._pending[pr.sup_id] = pr
            self._inflight_keys[key] = pr
            if self._lifecycle is not None:
                # No "received" — intake happened in the DEAD process;
                # the replayed-headed chain is accounted truncated
                # (telemetry/lifecycle.EVENT_KINDS).
                self._lifecycle.emit("replayed", pr.sup_id,
                                     key=key, video_id=pr.video_id,
                                     seq_out=pr.seq_out,
                                     sent_tokens=pr.sent_tokens)
            replayed.append({"key": key, "sup_id": pr.sup_id,
                             "video_id": pr.video_id,
                             "stream": bool(acc["stream"]),
                             "sent_tokens": pr.sent_tokens,
                             "seq_out": pr.seq_out})
            self._place(pr)
        self._dirty = True
        return {
            "schema": 1,
            "enabled": True,
            "replayed": replayed,
            "recovered_terminals": len(rec.terminals),
            "torn_records": rec.torn_records,
            "segments_scanned": rec.segments_scanned,
            "high_water": self._journal.high_water(),
        }

    def _candidates(self, tried: Set[int]) -> List[ProcReplica]:
        """Live replicas not yet tried for this placement, in the
        SHARED policy order (serving/policy.rank_key): healthy tier
        first (the child's own health status), the supervisor's
        in-flight count as the load, index tiebreak."""
        active = [r for r in self._replicas
                  if r.live and r.kill_at is None
                  and not r.retiring and r.index not in tried]
        return sorted(active, key=lambda r: rank_key(
            r.health.get("status") == "degraded",
            len(r.inflight), r.index))

    def _place(self, pr: ProxyRequest, reroute: bool = False) -> None:
        now = self.clock()
        rem = pr.remaining_ms(now)
        if rem is not None and rem <= 0:
            self._answer_expired(pr)
            return
        cands = self._candidates(pr.tried)
        if not cands:
            if any(r.state in ("starting", "backoff")
                   or r.kill_at is not None
                   for r in self._replicas):
                # Momentarily no live child (restarts in flight): HOLD
                # — the request outlives the replica that owned it.
                self._park(pr)
                return
            if not any(r.live for r in self._replicas):
                self._check_unrecoverable()
            self._answer_shed(pr)
            return
        if rem is not None:
            floors = [None if s.health.get("min_service_ms") is None
                      else float(s.health["min_service_ms"]) / 1e3
                      for s in cands]
            if deadline_unmeetable(rem, floors):
                # Provably unmeetable EVERYWHERE: shed at the fleet edge
                # with an explicit answer (SERVING.md "Fleet").
                self._answer_expired(pr, why="deadline_unmeetable")
                return
            if (self._autoscaler is not None
                    and self._autoscaler.brownout_rung() >= 1
                    and deadline_unmeetable(
                        rem, floors,
                        margin=self._autoscaler.deadline_margin)):
                # Brownout rung 1: the fleet is pinned at max and still
                # burning, so admission tightens — a deadline without
                # margin-x headroom over every service floor is shed
                # NOW rather than admitted to miss (SERVING.md
                # "Autoscaling & brownout").
                self._autoscaler.note_shed("deadline")
                self._answer_expired(pr, why="brownout_deadline")
                return
        msg: Dict[str, Any] = {"id": pr.sup_id, "video_id": pr.video_id,
                               "op": "stream" if pr.stream else "caption"}
        if rem is not None:
            msg["deadline_ms"] = rem
        if pr.no_cache:
            msg["no_cache"] = True
        if self._fleet_obs is not None:
            # Cross-process trace context (SERVING.md wire addendum):
            # the child threads this through its lifecycle events, so
            # fleet_trace.py can join its async track to the
            # supervisor's.  `recv_s` is the supervisor's intake clock
            # (its own monotonic domain — context, not a timestamp the
            # child may compare against its clocks).
            msg["trace"] = {"id": pr.sup_id, "recv_s": pr.arrival}
        line = json.dumps(msg)
        for i, rep in enumerate(cands):
            try:
                rep.child.send_line(line)
            except OSError:
                pr.tried.add(rep.index)   # dying child; reaped next tick
                continue
            pr.replica = rep.index
            rep.inflight.add(pr.sup_id)
            self._inc("sup_routed")
            if i or reroute:
                self._inc("sup_rerouted")
            if self._lifecycle is not None:
                self._lifecycle.emit("routed", pr.sup_id,
                                     replica=rep.index, candidate=i)
            self._dirty = True
            return
        # Every candidate's socket failed mid-send: hold for the reaper.
        self._park(pr)

    def _park(self, pr: ProxyRequest) -> None:
        if (self._autoscaler is not None
                and self._autoscaler.brownout_rung() >= 2):
            with self._requeue_lock:
                depth = len(self._parked)
            if depth >= self._autoscaler.parked_cap:
                # Brownout rung 2: the hold queue is capacity the fleet
                # no longer has — overflow is shed honestly with a
                # typed answer instead of parking into a miss.
                self._autoscaler.note_shed("parked")
                self._inc("sup_shed")
                self._finish(pr, {"id": pr.client_id, "error": "shed",
                                  "video_id": pr.video_id,
                                  "why": "brownout_parked"},
                             "shed", where="fleet",
                             reason="brownout_parked")
                return
        pr.replica = None
        pr.tried = set()   # a fresh attempt reconsiders everyone
        self._inc("sup_parked")
        if self._lifecycle is not None:
            self._lifecycle.emit("queued", pr.sup_id, where="supervisor")
        with self._requeue_lock:
            self._parked.append(pr)

    def _retry_parked(self, now: float) -> None:
        with self._requeue_lock:
            if not self._parked:
                return
            parked, self._parked = self._parked, []
        for pr in parked:
            rem = pr.remaining_ms(now)
            if rem is not None and rem <= 0:
                self._answer_expired(pr)
                continue
            if self._draining:
                self._answer_reject_draining(pr)
                continue
            self._place(pr, reroute=True)

    # -- child line handling -----------------------------------------------

    def _pump_children(self) -> int:
        n = 0
        for rep in self._replicas:
            n += self._pump_one(rep)
        return n

    def _pump_one(self, rep: ProcReplica) -> int:
        child = rep.child
        if child is None:
            return 0
        moved = 0
        for raw in child.lines():
            moved += 1
            rep.last_line_t = self.clock()
            try:
                obj = json.loads(raw)
            except ValueError:
                self._inc("sup_bad_lines")
                continue
            if not isinstance(obj, dict):
                self._inc("sup_bad_lines")
                continue
            op = obj.get("op")
            if op == "health":
                rep.health = obj
                self._health_pacer.ok(rep.index)
                if rep.compiles0 is None and "compiles" in obj:
                    # First health after (re)start: the post-warm
                    # compile baseline the probe's zero-recompile
                    # check is measured against.
                    rep.compiles0 = obj.get("compiles")
                self._dirty = True
                continue
            if op == "stats":
                rep.last_stats = obj
                if self._fleet_obs is not None:
                    self._fleet_obs.on_stats(rep.index)
                continue
            if op == "ping":
                # Clock-sync echo (ISSUE 17): only the fleet plane
                # sends pings, so an unarmed supervisor never sees one.
                if self._fleet_obs is not None:
                    self._fleet_obs.on_ping(rep.index, obj,
                                            t1=self.clock())
                continue
            if op == "dump":
                continue   # the child announced where its blackbox went
            if "id" in obj:
                rep.lines_seen += 1
                self._on_response(rep, obj)
                continue
            self._inc("sup_bad_lines")
        return moved

    def _on_response(self, rep: ProcReplica, obj: Dict[str, Any]) -> None:
        pr = self._pending.get(obj.get("id"))
        if pr is None or pr.replica != rep.index:
            # Stale: a line from an owner this request already left
            # (answered, requeued, or expired) — drop, never double-
            # answer a client id.
            return
        err = obj.get("error")
        if err is None and obj.get("stream") and not obj.get("final"):
            self._forward_chunk(pr, obj)
            return
        if err == "shed":
            # The child's bounded queue shed it: route around.
            rep.inflight.discard(pr.sup_id)
            pr.tried.add(rep.index)
            pr.replica = None
            self._place(pr, reroute=True)
            return
        if err == "rejected_draining" and not self._draining:
            # The CHILD is draining (proc_preempt / external SIGTERM)
            # but the fleet is not: the client must never see a drain
            # it did not cause — requeue.
            rep.inflight.discard(pr.sup_id)
            pr.tried.add(rep.index)
            pr.replica = None
            pr.cur_tokens = 0
            pr.requeues += 1
            self._inc("sup_requeued")
            if self._lifecycle is not None:
                self._lifecycle.emit("requeued", pr.sup_id,
                                     replica=rep.index)
            self._place(pr, reroute=True)
            return
        self._terminal(rep, pr, obj)

    def _forward_chunk(self, pr: ProxyRequest, obj: Dict[str, Any]) -> None:
        """The supervisor-level stream watermark (module docstring):
        only tokens beyond ``sent_tokens`` reach the client, text
        sliced in lockstep, ``seq`` re-issued supervisor-side."""
        toks = obj.get("tokens") or []
        start = pr.cur_tokens
        pr.cur_tokens = start + len(toks)
        if pr.cur_tokens <= pr.sent_tokens:
            return   # fully inside the watermark: a replayed chunk
        skip = max(pr.sent_tokens - start, 0)
        out_toks = toks[skip:]
        # Vocab.decode is one word per non-zero token (zeros only pad
        # the tail), so the word list is a prefix-aligned mirror of the
        # token list and slices at the same offset.
        words = str(obj.get("text") or "").split()
        out_text = " ".join(words[skip:]) if skip < len(words) else ""
        pr.sent_tokens = pr.cur_tokens
        out = {"id": pr.client_id, "video_id": pr.video_id,
               "stream": True, "seq": pr.seq_out,
               "tokens": [int(t) for t in out_toks],
               "text": out_text, "final": False}
        pr.seq_out += 1
        if self._journal is not None and pr.key is not None:
            # Watermark + chunk journaled at send time: a relaunch
            # resumes exactly past what this append proves was sent,
            # and a reconnecting client is caught up from the record.
            self._journal.mark(pr.key, out["seq"], out["tokens"],
                               out["text"], pr.sent_tokens)
            self._inc("sup_journal_appends")
        pr.respond(out)

    def _terminal(self, rep: ProcReplica, pr: ProxyRequest,
                  obj: Dict[str, Any]) -> None:
        """Forward a child's terminal answer with the client's id (and
        the client's clocks) restored."""
        rep.inflight.discard(pr.sup_id)
        self._pending.pop(pr.sup_id, None)
        self._dirty = True
        out = dict(obj)
        out["id"] = pr.client_id
        if "latency_ms" in out:
            # The ARRIVAL clock is the supervisor's intake: a requeued
            # request's latency spans its whole story, not only its
            # final owner's share.
            lat = (self.clock() - pr.arrival) * 1e3
            out["latency_ms"] = round(lat, 3)
            self._latencies_ms.append(lat)
        if pr.stream and out.get("final") and "chunks" in out:
            out["chunks"] = pr.seq_out   # chunks the CLIENT saw
        err = out.get("error")
        if self._fleet_obs is not None:
            self._fleet_obs.observe_request(
                err is None and "caption" in out,
                out.get("latency_ms"), self.clock())
        if err is None and "caption" in out:
            rep.completed += 1
            rep.backoff_level = 0   # healthy again: backoff resets
            self._completed += 1
            if self._lifecycle is not None:
                self._lifecycle.emit("completed", pr.sup_id,
                                     replica=rep.index,
                                     requeues=pr.requeues)
        elif self._lifecycle is not None:
            self._lifecycle.emit("dropped", pr.sup_id,
                                 reason=str(err), replica=rep.index)
        self._journal_terminal(pr, out)
        pr.respond(out)
        if self._lifecycle is not None:
            self._lifecycle.emit("responded", pr.sup_id,
                                 status=(err or "ok"))

    # -- terminal answers the supervisor itself writes ---------------------

    def _journal_terminal(self, pr: ProxyRequest,
                          obj: Dict[str, Any]) -> None:
        """Journal a terminal at send time and retire the open key —
        EVERY terminal path (child answer, shed, expiry, drain reject)
        funnels through here before ``respond``."""
        if self._journal is None or pr.key is None:
            return
        self._inflight_keys.pop(pr.key, None)
        self._journal.terminal(pr.key, obj)
        self._inc("sup_journal_appends")

    def _finish(self, pr: ProxyRequest, obj: Dict[str, Any],
                kind: str, **attrs) -> None:
        if self._fleet_obs is not None:
            # Every supervisor-written terminal (shed/expired/drain
            # reject) is a failed outcome in the SLO books.
            self._fleet_obs.observe_request(False, None, self.clock())
        self._pending.pop(pr.sup_id, None)
        if pr.replica is not None:
            self._replicas[pr.replica].inflight.discard(pr.sup_id)
        if pr.stream:
            obj["stream"] = True
            obj["final"] = True   # the _mark_stream_terminal invariant
        if self._lifecycle is not None:
            self._lifecycle.emit(kind, pr.sup_id, **attrs)
            self._lifecycle.emit("responded", pr.sup_id,
                                 status=obj.get("error", "ok"))
        self._journal_terminal(pr, obj)
        pr.respond(obj)

    def _answer_shed(self, pr: ProxyRequest) -> None:
        self._inc("sup_shed")
        self._finish(pr, {"id": pr.client_id, "error": "shed",
                          "video_id": pr.video_id,
                          "queue_depth": len(self._pending)},
                     "shed", where="fleet")

    def _answer_expired(self, pr: ProxyRequest,
                        why: Optional[str] = None) -> None:
        obj = {"id": pr.client_id, "video_id": pr.video_id,
               "error": "expired", "where": "fleet"}
        if why is not None:
            obj["why"] = why
            self._inc("sup_shed")
        self._finish(pr, obj, "dropped",
                     reason=(why or "expired"), where="fleet")

    def _answer_reject_draining(self, pr: ProxyRequest) -> None:
        self._finish(pr, {"id": pr.client_id, "video_id": pr.video_id,
                          "error": "rejected_draining"},
                     "dropped", reason="rejected_draining",
                     where="fleet_drain")

    # -- the scheduler tick ------------------------------------------------

    def tick(self) -> int:
        """One supervision step, called by the front-end loop: hatch
        finished spawns, reap exits, restart what is due, move child
        lines, fire armed chaos, wedge-check, health-poll, retry
        parked.  Returns an activity count (0 = idle)."""
        now = self.clock()
        self._hatch_ready()
        self._reap_exits()
        self._restart_due(now)
        moved = self._pump_children()
        self._fire_proc_faults()
        self._finish_pending_kills()
        self._check_wedges(now)
        self._health_poll(now)
        if self._fleet_obs is not None:
            self._fleet_obs.tick(self, now)
        if self._autoscaler is not None and not self._draining:
            # Right after the scraper: the autoscaler decides from the
            # sample the scraper may just have appended, same tick.
            self._autoscaler.tick(self, now)
        self._retry_parked(now)
        if self._dirty:
            self._dirty = False
            self._update_snapshots()
        return moved

    @property
    def quiet(self) -> bool:
        """Nothing owed: no pending requests, nothing parked, no spawn
        in flight (EOF may exit)."""
        with self._requeue_lock:
            parked = len(self._parked)
        return (not self._pending and not parked
                and not self._spawning and self._hatch.empty())

    @property
    def outstanding(self) -> int:
        return len(self._pending)

    # -- drain / shutdown --------------------------------------------------

    def begin_drain(self) -> None:
        """First-signal semantics: TERM every child (each runs its OWN
        drain contract — residents complete, queues reject), answer
        everything parked, accept nothing new.  Children exiting 75/0
        during the drain are expected: no incident, no restart."""
        self._draining = True
        self._dirty = True
        for rep in self._replicas:
            if rep.child is None:
                continue
            try:
                rep.child.terminate()
            except OSError:
                pass
        with self._requeue_lock:
            parked, self._parked = self._parked, []
        for pr in parked:
            self._answer_reject_draining(pr)

    def drain_done(self) -> bool:
        return (not self._pending
                and all(r.child is None for r in self._replicas)
                and not self._spawning and self._hatch.empty())

    def hard_abort(self) -> None:
        """Second-signal semantics: SIGKILL every child NOW and answer
        every outstanding id ``rejected_draining`` — lost in-flight
        work is honest, a silent drop never is."""
        for rep in self._replicas:
            if rep.child is None:
                continue
            try:
                rep.child.kill()
            except OSError:
                pass
            rep.child.close()
            rep.child = None
            rep.state = "drained"
        with self._requeue_lock:
            parked, self._parked = self._parked, []
        for pr in parked + list(self._pending.values()):
            self._answer_reject_draining(pr)
        self._update_snapshots()

    def shutdown(self, timeout_s: float = 60.0) -> None:
        """EOF shutdown: nothing is owed (``quiet``) — TERM children,
        bounded wait for their clean 75s, SIGKILL stragglers."""
        self._draining = True
        for rep in self._replicas:
            if rep.child is None:
                continue
            try:
                rep.child.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(r.child is None or r.child.poll() is not None
                   for r in self._replicas):
                break
            time.sleep(0.05)
        for rep in self._replicas:
            if rep.child is None:
                continue
            if rep.child.poll() is None:
                try:
                    rep.child.kill()
                except OSError:
                    pass
            rep.child.close()
            rep.child = None
            rep.state = "drained"
        if self._journal is not None:
            self._journal.close()
        self._update_snapshots()


# ---------------------------------------------------------------------------
# the client front end
# ---------------------------------------------------------------------------


class SupervisorServer:
    """The supervisor's own JSONL front end — the CaptionServer wire
    (stdin or localhost socket), proxied: caption/stream requests route
    through the :class:`ProcessFleetSupervisor`; ``health`` answers the
    aggregated fleet plane; ``stats`` the supervisor view;
    ``dump`` writes the supervisor's own blackbox AND forwards the op
    to every child.  Same shutdown contract as serve.py: first signal
    drains (children first), second hard-stops with every outstanding
    id answered ``rejected_draining``; stdin EOF finishes everything
    and exits 0."""

    def __init__(self, sup: ProcessFleetSupervisor, *, handler=None,
                 out=None, idle_sleep: float = 0.002, watchdog=None,
                 registry=None, lifecycle=None, blackbox_path=None):
        self.sup = sup
        self.handler = handler
        self.out = out if out is not None else sys.stdout
        self.idle_sleep = idle_sleep
        self.watchdog = watchdog
        self.registry = registry
        self._lifecycle = lifecycle
        self.blackbox_path = blackbox_path
        if registry is not None:
            registry.declare("serve_bad_lines", "serve_health_queries",
                             "serve_stats_queries", "serve_dump_queries")
        self._inbox: "queue.Queue" = queue.Queue()
        self._eof = threading.Event()
        self._write_lock = named_lock("serving.supervisor.write")
        self._draining = False  # cstlint: owned_by=scheduler
        self.bound_port: Optional[int] = None

    # -- responses ---------------------------------------------------------

    def _write(self, respond: Callable[[str], None],
               obj: Dict[str, Any]) -> None:
        with self._write_lock:
            respond(json.dumps(obj))

    def _stdout_respond(self, line: str) -> None:
        self.out.write(line + "\n")
        self.out.flush()

    def _count(self, name: str) -> None:
        if self.registry is not None:
            self.registry.inc(name)

    def health_payload(self) -> Dict[str, Any]:
        h = self.sup.health_payload()
        if self._draining and h["status"] != "draining":
            h["status"] = "draining"
        h["op"] = "health"
        return h

    # -- intake ------------------------------------------------------------

    def _handle_line(self, line: str,
                     respond: Callable[[str], None]) -> None:
        try:
            self._handle_line_inner(line, respond)
        except SupervisorUnrecoverable:
            raise   # the front end's 124 path, never a bad_request
        except Exception as e:  # one bad line must never kill the loop
            self._count("serve_bad_lines")
            try:
                self._write(respond, {"id": None, "error": "bad_request",
                                      "detail":
                                          f"line handling failed: {e}"})
            except Exception as werr:
                log.debug("error response write failed: %r", werr)

    def _handle_line_inner(self, line: str,
                           respond: Callable[[str], None]) -> None:
        line = line.strip()
        if not line:
            return
        try:
            req = json.loads(line)
        except ValueError:
            self._count("serve_bad_lines")
            self._write(respond, {"id": None, "error": "bad_request",
                                  "detail": "unparseable JSON line"})
            return
        if not isinstance(req, dict):
            self._count("serve_bad_lines")
            self._write(respond, {"id": None, "error": "bad_request",
                                  "detail": "expected {'id', 'video_id'}"})
            return
        op = req.get("op", "caption")
        if op == "health":
            self._count("serve_health_queries")
            self._write(respond, self.health_payload())
            return
        if op == "stats":
            self._count("serve_stats_queries")
            self._write(respond, {"op": "stats", **self.sup.stats()})
            return
        if op == "dump":
            self._count("serve_dump_queries")
            asked = self.sup.dump_children()
            if self._lifecycle is None:
                self._write(respond, {"op": "dump", "error": "no_recorder",
                                      "children_asked": asked,
                                      "detail": "lifecycle tracing is "
                                                "disarmed"})
                return
            path = req.get("path") or self.blackbox_path
            if not path:
                self._write(respond, {"op": "dump", "error": "no_path",
                                      "children_asked": asked,
                                      "detail": "no blackbox path "
                                                "configured or supplied"})
                return
            doc = self._lifecycle.dump(path, reason="wire_dump")
            self._write(respond, {"op": "dump", "path": str(path),
                                  "children_asked": asked,
                                  "events": doc["events_retained"],
                                  "emitted": doc["events_emitted"]})
            return
        if op not in ("caption", "stream"):
            self._count("serve_bad_lines")
            self._write(respond, {"id": req.get("id"),
                                  "error": "unknown_op", "op": op,
                                  "detail": "expected op 'caption', "
                                            "'stream', 'health', 'stats' "
                                            "or 'dump'"})
            return
        rid = req.get("id")
        vid = req.get("video_id")
        if vid is None:
            self._count("serve_bad_lines")
            self._write(respond, {"id": rid, "error": "bad_request",
                                  "detail": "expected {'id', 'video_id'}"})
            return
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
                if deadline_ms < 0:
                    raise ValueError
            except (TypeError, ValueError):
                self._count("serve_bad_lines")
                self._write(respond, {"id": rid, "error": "bad_request",
                                      "detail": "deadline_ms must be a "
                                                "number >= 0"})
                return
        idem = req.get("idem")
        if idem is not None and not isinstance(idem, str):
            self._count("serve_bad_lines")
            self._write(respond, {"id": rid, "error": "bad_request",
                                  "detail": "idem must be a string"})
            return
        have_seq = req.get("have_seq")
        if have_seq is not None:
            try:
                have_seq = int(have_seq)
            except (TypeError, ValueError):
                self._count("serve_bad_lines")
                self._write(respond, {"id": rid, "error": "bad_request",
                                      "detail": "have_seq must be an "
                                                "integer"})
                return
        # Unknown-video stays the CHILD's verdict (it owns the feature
        # table) — the error comes back as a terminal and is forwarded,
        # so the wire semantics match serve.py exactly.
        self.sup.submit(
            rid, vid,
            respond=lambda obj: self._write(respond, obj),
            stream=(op == "stream"), deadline_ms=deadline_ms,
            no_cache=bool(req.get("no_cache")),
            idem=idem, have_seq=have_seq)

    # -- scheduler loop ----------------------------------------------------

    def _drain_and_exit(self) -> int:
        self._draining = True
        count0 = getattr(self.handler, "signal_count", 0)

        def aborted() -> bool:
            return getattr(self.handler, "signal_count", 0) > count0

        alive = sum(1 for r in self.sup._replicas if r.child is not None)
        print(f"serve_supervisor: draining {self.sup.outstanding} "
              f"outstanding across {alive} child(ren); a second signal "
              "aborts", file=sys.stderr)
        sys.stderr.flush()
        self.sup.begin_drain()
        while not self.sup.drain_done():
            if aborted():
                break
            if self.watchdog is not None:
                self.watchdog.beat()
            if not self.sup.tick():
                time.sleep(self.idle_sleep)
        if aborted():
            unfinished = self.sup.outstanding
            self.sup.hard_abort()
            if self._lifecycle is not None and self.blackbox_path:
                self._lifecycle.dump(self.blackbox_path,
                                     reason="drain_abort")
            print(f"serve_supervisor: drain aborted by a second signal "
                  f"with {unfinished} outstanding; exiting "
                  f"{EXIT_SIGTERM} (sigterm_unwind)", file=sys.stderr)
            return EXIT_SIGTERM
        print(f"serve_supervisor: drained; exiting {EXIT_PREEMPTED} "
              "(preempted/resumable)", file=sys.stderr)
        return EXIT_PREEMPTED

    def _loop(self) -> int:
        while True:
            if self.watchdog is not None:
                self.watchdog.beat()
            if self.handler is not None and self.handler.requested:
                return self._drain_and_exit()
            moved = False
            while True:
                try:
                    line, respond = self._inbox.get_nowait()
                except queue.Empty:
                    break
                self._handle_line(line, respond)
                moved = True
            if self.sup.tick():
                moved = True
            if self._eof.is_set() and self.sup.quiet \
                    and self._inbox.empty():
                self.sup.shutdown()
                return EXIT_OK
            if not moved:
                time.sleep(self.idle_sleep)

    # -- stdin front end ---------------------------------------------------

    def run_stdin(self, lines=None) -> int:
        src = lines if lines is not None else sys.stdin

        def read():
            try:
                for line in src:
                    self._inbox.put((line, self._stdout_respond))
            finally:
                self._eof.set()

        threading.Thread(target=read, name="sup-stdin",
                         daemon=True).start()
        return self._loop()

    # -- localhost socket front end ----------------------------------------

    def run_socket(self, port: int) -> int:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", int(port)))
        srv.listen()
        srv.settimeout(0.2)
        bound = srv.getsockname()[1]
        self.bound_port = bound
        print(f"serve: listening on 127.0.0.1:{bound}", file=sys.stderr)
        sys.stderr.flush()
        conns: List[socket.socket] = []

        def reader(conn: socket.socket) -> None:
            lock = named_lock("serving.supervisor.conn")

            def respond(line: str) -> None:
                with lock:
                    try:
                        conn.sendall(line.encode() + b"\n")
                    except OSError:
                        pass  # client went away; the caption is dropped

            try:
                with conn.makefile("r", encoding="utf-8",
                                   errors="replace") as f:
                    for line in f:
                        self._inbox.put((line, respond))
            except OSError:
                pass

        def accept() -> None:
            while not self._eof.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                conns.append(conn)
                threading.Thread(target=reader, args=(conn,),
                                 name="sup-conn", daemon=True).start()

        threading.Thread(target=accept, name="sup-accept",
                         daemon=True).start()
        try:
            return self._loop()
        finally:
            self._eof.set()
            for conn in conns:
                try:
                    conn.close()
                except OSError:
                    pass
            srv.close()
