"""Attribution-driven autoscaler + overload brownout (ISSUE 19 /
SERVING.md "Autoscaling & brownout").

The fleet-observability plane (telemetry/fleetobs.py) was built as "the
autoscaler-facing view"; this module closes the loop.  An
:class:`Autoscaler` rides the supervisor tick right after the scraper
and decides from LATENCY ATTRIBUTION, not from raw latency:

- **scale up** when the per-child ``queue_wait`` p99 burns over the
  ``queue_hi_ms`` threshold in BOTH the fast and the slow sample window
  while the ``decode`` p99 stays flat — requests are waiting for a
  replica, not for the model, so a replica helps;
- **scale down** when ``queue_wait`` p99 sits at/under ``queue_lo_ms``
  for the ENTIRE slow window (hysteresis: ``queue_lo_ms <
  queue_hi_ms``) and no SLO objective is firing — there is provably
  nothing for the extra replica to absorb.

Thrash damping is the SLO monitor's own dual-window discipline plus
per-direction cooldowns and the requirement that the fleet is SETTLED
(no replica starting, backing off, or draining out) before any
decision.  Decisions act through the supervisor: ``sup.add_replica()``
spawns through the existing warm child recipe; ``sup.retire_worst()``
drains the worst-ranked child via ``policy.rank_key`` — in-flight work
finishes, nothing is requeued by the scale-down itself, and a child
that dies mid-drain falls through the supervisor's existing requeue
path.

When the fleet is pinned at ``max_replicas`` and the up-signal keeps
burning, a **brownout ladder** replaces collapse — three rungs, entered
one at a time on sustained burn and exited one at a time on sustained
calm:

1. tighten fleet-edge deadline admission (``deadline_unmeetable`` with
   an inflated service-floor margin);
2. cap the parked-request depth (overflow answered with a typed shed);
3. reject new stream ops at intake.

Each rung's sheds are typed (``why: brownout_*``) and counted, so the
overflow is shed honestly while admitted requests keep bounded p99.

Every decision (scale_up / scale_down / brownout_enter / brownout_exit)
is a typed lifecycle event AND one fsync'd line in the durable
``autoscale_decisions.jsonl`` (the slo_alerts.jsonl appender
discipline) — the evidence trail fleet_report/serve_report gate and
collect_evidence bundles.

Threading: :meth:`tick` and the shed hooks run on the supervisor's
scheduler thread; :meth:`brownout_rung` / :meth:`status` may be read
from a health/heartbeat thread — hence the named state lock.  Nothing
is emitted, counted, or written while holding it (the fleetobs ring-
lock rule).  Pure host code, all time through injected clocks.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils.locksan import declare_order, named_lock

#: autoscale_decisions.jsonl line format version (every line stamped).
AUTOSCALE_SCHEMA = 1

#: Registry counters this plane owns (declared at 0; the table is
#: test-pinned in SERVING.md "Autoscaling & brownout").
AUTOSCALE_COUNTERS = (
    "autoscale_ticks",            # scrape samples ingested for decisions
    "autoscale_scale_ups",        # replicas added
    "autoscale_scale_downs",      # replicas retired (drain-based)
    "autoscale_holds_cooldown",   # signal present, per-direction cooldown held
    "autoscale_holds_bounds",     # signal present, min/max bound held
    "brownout_entries",           # ladder rung escalations
    "brownout_exits",             # ladder rung de-escalations
    "brownout_shed_deadline",     # rung-1 sheds (tightened admission)
    "brownout_shed_parked",       # rung-2 sheds (parked-depth cap)
    "brownout_shed_stream",       # rung-3 sheds (stream intake rejected)
)

#: Declared acquisition order (cstlint:lock-order + runtime sanitizer):
#: the autoscaler state lock is a near-leaf read from the health thread;
#: it may in principle reach the registry leaf, never the reverse — in
#: practice nothing counts under it (the fleetobs ring-lock rule).
LOCK_ORDER = ("serving.autoscale.state", "telemetry.registry")
declare_order(*LOCK_ORDER)

#: Brownout ladder rungs, in escalation order (RESILIENCE.md row).
BROWNOUT_RUNGS = ("deadline", "parked", "stream")


class Autoscaler:
    """Grow/shrink the process fleet from the scraped attribution feed.

    ``fleet_obs`` supplies :meth:`~telemetry.fleetobs.FleetObs.series`
    (the sample ring); the supervisor passed to :meth:`tick` is
    duck-typed — anything with ``add_replica() -> int`` and
    ``retire_worst() -> Optional[int]`` works, so tests drive the
    decision engine with stubs.  All thresholds are attribution
    milliseconds; cooldowns are seconds on the supervisor's injected
    monotonic clock (``now`` flows in through :meth:`tick`).
    """

    def __init__(self, fleet_obs, *, min_replicas: int = 1,
                 max_replicas: int = 4, queue_hi_ms: float = 50.0,
                 queue_lo_ms: float = 5.0, fast_samples: int = 3,
                 slow_samples: int = 9, up_cooldown_s: float = 2.0,
                 down_cooldown_s: float = 10.0,
                 decode_flat_factor: float = 2.0,
                 brownout_patience: int = 3,
                 deadline_margin: float = 4.0, parked_cap: int = 8,
                 out_dir: Optional[str] = None,
                 wall: Callable[[], float] = time.time,
                 registry=None, lifecycle=None):
        if int(min_replicas) < 1:
            raise ValueError(
                f"autoscale min must be >= 1, got {min_replicas}")
        if int(max_replicas) < int(min_replicas):
            raise ValueError(
                f"autoscale max ({max_replicas}) must be >= min "
                f"({min_replicas})")
        if float(queue_lo_ms) >= float(queue_hi_ms):
            raise ValueError(
                f"hysteresis needs queue_lo_ms ({queue_lo_ms}) < "
                f"queue_hi_ms ({queue_hi_ms})")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_hi_ms = float(queue_hi_ms)
        self.queue_lo_ms = float(queue_lo_ms)
        self.fast_samples = max(1, int(fast_samples))
        self.slow_samples = max(self.fast_samples, int(slow_samples))
        self.up_cooldown_s = max(0.0, float(up_cooldown_s))
        self.down_cooldown_s = max(0.0, float(down_cooldown_s))
        self.decode_flat_factor = max(1.0, float(decode_flat_factor))
        self.brownout_patience = max(1, int(brownout_patience))
        self.deadline_margin = max(1.0, float(deadline_margin))
        self.parked_cap = max(0, int(parked_cap))
        self.wall = wall
        self._fleet_obs = fleet_obs
        self._registry = registry
        self._lifecycle = lifecycle
        self.decisions_path = (
            os.path.join(os.path.abspath(out_dir),
                         "autoscale_decisions.jsonl")
            if out_dir else None)
        # Decision state below is tick-thread-only...
        self._window: deque = deque(maxlen=self.slow_samples)  # cstlint: owned_by=supervisor_tick
        self._last_seq = 0             # cstlint: owned_by=supervisor_tick
        self._last_up_t: Optional[float] = None    # cstlint: owned_by=supervisor_tick
        self._last_down_t: Optional[float] = None  # cstlint: owned_by=supervisor_tick
        self._sat_ticks = 0            # cstlint: owned_by=supervisor_tick
        self._calm_ticks = 0           # cstlint: owned_by=supervisor_tick
        self._seq = 0                  # cstlint: owned_by=supervisor_tick
        self.decisions: List[Dict[str, Any]] = []  # cstlint: owned_by=supervisor_tick
        # ...except the brownout rung, which the health/heartbeat thread
        # may read through brownout_rung()/status() while the tick
        # thread escalates — hence the named state lock (LOCK_ORDER).
        self._state_lock = named_lock("serving.autoscale.state")
        self._rung = 0  # cstlint: guarded_by=self._state_lock
        self._c = {name: 0 for name in AUTOSCALE_COUNTERS}
        if registry is not None:
            registry.declare(*AUTOSCALE_COUNTERS)

    # -- counters ----------------------------------------------------------

    def _inc(self, name: str, n: int = 1) -> None:
        self._c[name] += n
        if self._registry is not None:
            self._registry.inc(name, n)

    def counters(self) -> Dict[str, int]:
        """The ONE definition of the autoscaler's audit view (the
        supervisor_counters discipline)."""
        return dict(self._c)

    # -- brownout hooks (read by the supervisor's shed paths) --------------

    def brownout_rung(self) -> int:
        """Current ladder rung (0 = no brownout).  Safe from any
        thread."""
        with self._state_lock:
            return self._rung

    def note_shed(self, rung: str) -> None:
        """Count one typed brownout shed (``deadline``/``parked``/
        ``stream``) — called by the supervisor at the shed site."""
        self._inc(f"brownout_shed_{rung}")

    # -- the decision tick -------------------------------------------------

    def tick(self, sup, now: float) -> None:
        """One decision turn, on the supervisor tick right after the
        scraper: ingest fresh samples from the ring, evaluate the
        dual-window signals, act at most once."""
        fresh = [s for s in self._fleet_obs.series()
                 if s.get("seq", 0) > self._last_seq]
        if not fresh:
            return
        self._last_seq = fresh[-1]["seq"]
        for s in fresh:
            self._window.append(self._digest(s))
            self._inc("autoscale_ticks")
        self._decide(sup, now)

    @staticmethod
    def _digest(sample: Dict[str, Any]) -> Dict[str, Any]:
        """Reduce one scrape sample to the decision inputs: the WORST
        live child's queue_wait/decode attribution p99 (the starving
        child is the one a new replica relieves), plus settledness and
        the SLO firing set."""
        qws: List[float] = []
        dcs: List[float] = []
        settled = True
        for c in sample.get("children", []):
            state = c.get("state")
            if state in ("starting", "backoff") or c.get("retiring"):
                settled = False
            if not c.get("live"):
                continue
            attr = c.get("attribution_p99_ms") or {}
            qw = attr.get("queue_wait")
            dc = attr.get("decode")
            # The child's attribution p99 is ring-cumulative (it never
            # decays after a burst), so a child with NO current work —
            # empty admission queue, nothing in flight — contributes
            # zero queue pressure: the down-signal reads "is anything
            # waiting NOW", the up-signal reads "how long did waiting
            # take" — both from the same scraped row.
            idle = (not c.get("inflight")
                    and not (c.get("queue_depth") or 0))
            if qw is not None and not idle:
                qws.append(qw)
            if dc is not None:
                dcs.append(dc)
        return {
            "queue_wait_ms": float(max(qws)) if qws else 0.0,
            "decode_ms": float(max(dcs)) if dcs else 0.0,
            "settled": settled,
            "slo_firing": bool((sample.get("slo") or {}).get("firing")),
        }

    def _signals(self) -> Dict[str, Any]:
        """The dual-window burn view over the ingested samples."""
        win = list(self._window)
        fast = win[-self.fast_samples:]

        def mean(rows, key):
            return (sum(r[key] for r in rows) / len(rows)) if rows else 0.0

        fast_qw = mean(fast, "queue_wait_ms")
        slow_qw = mean(win, "queue_wait_ms")
        fast_dc = mean(fast, "decode_ms")
        slow_dc = mean(win, "decode_ms")
        # Decode "flat": the fast-window decode p99 has not outgrown the
        # slow baseline — queueing is rising on its own, so capacity
        # (not the model) is the bottleneck.  An empty baseline (no
        # completions yet) counts as flat.
        decode_flat = (slow_dc <= 0.0
                       or fast_dc <= self.decode_flat_factor * slow_dc)
        up = (len(win) >= self.fast_samples
              and fast_qw >= self.queue_hi_ms
              and slow_qw >= self.queue_hi_ms
              and decode_flat)
        down = (len(win) == self.slow_samples
                and all(r["queue_wait_ms"] <= self.queue_lo_ms
                        for r in win)
                and not any(r["slo_firing"] for r in win))
        return {
            "up": up, "down": down,
            "settled": bool(win and win[-1]["settled"]),
            "queue_wait_fast_ms": round(fast_qw, 3),
            "queue_wait_slow_ms": round(slow_qw, 3),
            "decode_fast_ms": round(fast_dc, 3),
            "decode_slow_ms": round(slow_dc, 3),
            "decode_flat": decode_flat,
        }

    def _decide(self, sup, now: float) -> None:
        sig = self._signals()
        n = sup.active_replicas()
        if sig["up"]:
            self._calm_ticks = 0
            if n >= self.max_replicas:
                self._inc("autoscale_holds_bounds")
                self._sat_ticks += 1
                if self._sat_ticks >= self.brownout_patience:
                    self._sat_ticks = 0
                    self._escalate(sup, now, sig, n)
                return
            self._sat_ticks = 0
            if not sig["settled"]:
                return   # a spawn/drain is already in flight: let it land
            if (self._last_up_t is not None
                    and now - self._last_up_t < self.up_cooldown_s):
                self._inc("autoscale_holds_cooldown")
                return
            added = sup.add_replica()
            self._last_up_t = now
            self._inc("autoscale_scale_ups")
            self._record(sup, now, "scale_up", sig, n, n + 1,
                         replica=added)
            return
        self._sat_ticks = 0
        if self.brownout_rung() > 0:
            self._calm_ticks += 1
            if self._calm_ticks >= self.brownout_patience:
                self._calm_ticks = 0
                self._deescalate(sup, now, sig, n)
            return
        if sig["down"]:
            if n <= self.min_replicas:
                self._inc("autoscale_holds_bounds")
                return
            if not sig["settled"]:
                return
            if (self._last_down_t is not None
                    and now - self._last_down_t < self.down_cooldown_s):
                self._inc("autoscale_holds_cooldown")
                return
            retired = sup.retire_worst()
            if retired is None:
                return
            self._last_down_t = now
            self._inc("autoscale_scale_downs")
            self._record(sup, now, "scale_down", sig, n, n - 1,
                         replica=retired)
            # A shrink empties the window's claim to a full quiet slow
            # window at the NEW size — re-earn it before the next one.
            self._window.clear()

    # -- the brownout ladder -----------------------------------------------

    def _escalate(self, sup, now: float, sig: Dict[str, Any],
                  n: int) -> None:
        with self._state_lock:
            if self._rung >= len(BROWNOUT_RUNGS):
                return
            self._rung += 1
            rung = self._rung
        self._inc("brownout_entries")
        self._record(sup, now, "brownout_enter", sig, n, n, rung=rung,
                     rung_name=BROWNOUT_RUNGS[rung - 1])

    def _deescalate(self, sup, now: float, sig: Dict[str, Any],
                    n: int) -> None:
        with self._state_lock:
            if self._rung <= 0:
                return
            left = BROWNOUT_RUNGS[self._rung - 1]
            self._rung -= 1
            rung = self._rung
        self._inc("brownout_exits")
        self._record(sup, now, "brownout_exit", sig, n, n, rung=rung,
                     rung_name=left)

    # -- the decisions log -------------------------------------------------

    def _record(self, sup, now: float, action: str, sig: Dict[str, Any],
                before: int, after: int, **attrs) -> None:
        self._seq += 1
        rec = {
            "schema": AUTOSCALE_SCHEMA,
            "kind": "autoscale_decision",
            "seq": self._seq,
            "action": action,
            "t": float(now),
            "wall": self.wall(),
            "replicas_before": int(before),
            "replicas_after": int(after),
            "rung": self.brownout_rung(),
            "reason": {k: sig[k] for k in
                       ("queue_wait_fast_ms", "queue_wait_slow_ms",
                        "decode_fast_ms", "decode_slow_ms",
                        "decode_flat")},
            "thresholds": {"queue_hi_ms": self.queue_hi_ms,
                           "queue_lo_ms": self.queue_lo_ms},
            **attrs,
        }
        self.decisions.append(rec)
        if self.decisions_path is not None:
            # The slo_alerts.jsonl appender discipline: append-only
            # JSONL, fsync'd per decision (decisions are rare by
            # construction — the cooldowns bound the rate).
            with open(self.decisions_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
        if self._lifecycle is not None:
            self._lifecycle.emit(
                "autoscale_decision", f"autoscale:{self._seq}",
                action=action, replicas_before=int(before),
                replicas_after=int(after), rung=rec["rung"],
                queue_wait_fast_ms=sig["queue_wait_fast_ms"],
                queue_wait_slow_ms=sig["queue_wait_slow_ms"])

    # -- views --------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The embedded status doc (scrape rows, stats, the probe
        record).  Safe from any thread."""
        return {
            "enabled": True,
            "min": self.min_replicas,
            "max": self.max_replicas,
            "rung": self.brownout_rung(),
            "queue_hi_ms": self.queue_hi_ms,
            "queue_lo_ms": self.queue_lo_ms,
            "scale_ups": self._c["autoscale_scale_ups"],
            "scale_downs": self._c["autoscale_scale_downs"],
            "brownout_entries": self._c["brownout_entries"],
            "brownout_exits": self._c["brownout_exits"],
            "decisions": len(self.decisions),
            "counters": self.counters(),
        }
