"""Caption-serving engine: continuous batching over the compiled decode path.

The training side of this repo rolls out captions in large fixed-shape
batches; serving traffic arrives one video at a time.  This package closes
that gap without ever recompiling per request (the cache/compile
discipline of PAPERS.md arXiv 2603.09555):

- ``buckets.py``  — a small FIXED set of batch-shape buckets with a
  compile-once program cache and an explicit recompile counter (0 under
  steady load, by contract);
- ``engine.py``   — the step-driven scheduler: bucketed batch slots,
  one-encoder-pass admission that writes encoder outputs + decoder carry
  into the slot in place, a per-row finished predicate
  (``ops.sampling.finished_mask``) that frees a slot mid-flight,
  bit-identical captions vs the offline ``eval.py`` decode (test-pinned),
  request deadlines with mid-flight TTL eviction, and a chaos-drilled
  self-healing ladder (deterministic chunk re-run -> ProgramCache-warm
  engine rebuild -> exit taxonomy — RESILIENCE.md "Serving faults");
- ``server.py``   — stdin/JSONL + optional localhost-socket front end with
  bounded-queue backpressure, hardened per-line intake, the
  ``{"op": "health"}`` ok|degraded|draining query, and graceful SIGTERM
  drain (second signal = hard stop) through the ``resilience``
  preemption/exit-code taxonomy;
- ``bench.py``    — the open-loop Poisson serving probe (seeded,
  deterministic arrivals; p50/p99 latency + captions/s) that joins the
  repo bench's JSON line and cache;
- ``fleet.py``    — the health-aware router over N supervised engine
  replicas (shared ProgramCache/result cache, route-around-degraded,
  draining rotation, supervised replica restart with resident re-queue,
  fleet-edge deadline shed) speaking the engine's scheduler surface so
  ``server.py`` drives a fleet unchanged (``scripts/serve_fleet.py``);
- ``policy.py``   — the placement/health policy shared by both fleets:
  status ranking, replica ordering, and the deadline-unmeetable floor;
- ``supervisor.py`` — the OS-process fleet: N real ``scripts/serve.py``
  children on localhost sockets, lifecycle driven by the exit taxonomy
  (resumable restart + crash-proof requeue, fatal restart budget,
  wedge kill), stream-prefix watermarks across requeue, and blackbox
  harvest from dead replicas (``scripts/serve_supervisor.py``).

Architecture, bucket policy, and the drain contract: SERVING.md.
"""

from .buckets import DEFAULT_BUCKETS, ProgramCache, parse_buckets  # noqa: F401

# Engine/server exports are lazy (PEP 562): buckets.py is pure host code,
# but engine.py imports jax — and opts.py validates --serve_buckets at
# parse time, which must not drag a jax init into every CLI parse.
_LAZY = {"Completion": ".engine", "Request": ".engine",
         "ServingEngine": ".engine", "serve_decode_split": ".engine",
         "CaptionServer": ".server", "serving_probe": ".bench",
         "FleetRouter": ".fleet", "FleetUnrecoverable": ".fleet",
         "FLEET_COUNTERS": ".fleet",
         "ProcessFleetSupervisor": ".supervisor",
         "SupervisorServer": ".supervisor",
         "SupervisorUnrecoverable": ".supervisor",
         "SUPERVISOR_COUNTERS": ".supervisor"}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module = importlib.import_module(_LAZY[name], __name__)
        return getattr(module, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
