"""Open-loop Poisson serving probe: p50/p99 latency + captions/s.

Open-loop means arrivals come from a PREDETERMINED schedule (seeded
exponential inter-arrival draws), never gated on completions — the honest
load model for "millions of users" traffic, where a slow server doesn't
slow the users down, it grows its own queue.  Latency is measured from
the SCHEDULED arrival, so queueing delay is part of the number.

The probe also enforces the compile discipline: ``engine.warm()`` pays
for every bucket's programs up front, and any program build after that
raises — steady-state serving must read 0 recompiles (the acceptance
contract; ``buckets.ProgramCache`` is the counter).

Determinism: the arrival schedule and per-request features are seeded,
so two runs issue the identical request stream; the measured latencies
are wall-clock (that is the point).  The repo bench (`bench.py --stage
serving`) feeds this into its one-JSON-line + cache machinery.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

from .buckets import DEFAULT_BUCKETS
from .engine import ServingEngine


def poisson_arrivals(num_requests: int, rate_hz: float,
                     seed: int = 0) -> np.ndarray:
    """Cumulative arrival times (seconds) for an open-loop Poisson stream."""
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / float(rate_hz),
                                     size=int(num_requests)))


def serving_probe(model, variables, feat_shapes: Sequence,
                  *, num_requests: int = 24, rate_hz: float = 8.0,
                  max_len: int = 30, beam_size: int = 1,
                  length_norm: float = 0.0, decode_chunk: int = 8,
                  bucket_sizes: Sequence[int] = DEFAULT_BUCKETS,
                  queue_limit: int = 0, seed: int = 0,
                  registry=None, tracer=None,
                  clock=time.perf_counter) -> Dict[str, Any]:
    """Drive one engine through a seeded Poisson load; -> metrics dict.

    Raises ``RuntimeError`` if any program compiles after warmup — the
    0-recompiles-under-steady-load assert, in the probe itself so a
    regression fails the bench rather than shipping a latency cliff.
    """
    n = int(num_requests)
    arrivals = poisson_arrivals(n, rate_hz, seed)
    feat_rng = np.random.default_rng(seed + 1)
    feats = [
        [feat_rng.standard_normal(s).astype(np.float32)
         for s in feat_shapes]
        for _ in range(n)
    ]
    engine = ServingEngine(
        model, variables, feat_shapes, max_len=max_len,
        beam_size=beam_size, length_norm=length_norm,
        decode_chunk=decode_chunk, bucket_sizes=bucket_sizes,
        queue_limit=queue_limit, registry=registry, tracer=tracer,
        clock=clock)
    warm_builds = engine.warm()["compiles"]

    t0 = clock()
    submitted = 0
    latencies: Dict[Any, float] = {}
    shed = 0
    while len(latencies) + shed < n:
        now = clock() - t0
        while submitted < n and arrivals[submitted] <= now:
            if not engine.submit(submitted, feats[submitted]):
                shed += 1
            submitted += 1
        for comp in engine.step():
            # Latency from the SCHEDULED arrival (open-loop convention).
            latencies[comp.request_id] = (
                (comp.done_at - t0) - arrivals[comp.request_id])
        if engine.idle and submitted < n:
            time.sleep(min(max(arrivals[submitted] - (clock() - t0), 0.0),
                           0.01))
    makespan = clock() - t0

    stats = engine.stats()
    recompiles = stats["compiles"] - warm_builds
    if recompiles != 0:
        raise RuntimeError(
            f"serving recompiled under steady load: {recompiles} program "
            f"build(s) after warmup (bucket discipline violated — "
            "SERVING.md 'Bucket policy')")
    lat_ms = np.asarray(sorted(latencies.values())) * 1e3
    pct = (lambda q: round(float(np.percentile(lat_ms, q)), 3)
           if lat_ms.size else None)
    return {
        "captions_per_sec": round(len(latencies) / makespan, 2),
        "latency_p50_ms": pct(50),
        "latency_p99_ms": pct(99),
        "latency_mean_ms": (round(float(lat_ms.mean()), 3)
                            if lat_ms.size else None),
        "num_requests": n,
        "completed": len(latencies),
        "shed": shed,
        "rate_hz": float(rate_hz),
        "arrival_seed": int(seed),
        "makespan_s": round(makespan, 3),
        "recompiles_after_warmup": recompiles,
        "program_builds_warm": warm_builds,
        "buckets": list(engine.buckets),
        "slots": stats["slots"],
        "beam_size": engine.beam_size,
        "decode_chunk": engine.chunk,
        "max_len": int(max_len),
        # Fault-tolerance audit (all 0 on a healthy fault-free probe;
        # scripts/serve_report.py renders them and FAILS on a
        # rebuild-recompile violation — RESILIENCE.md "Serving faults").
        **engine.recovery_counters(),
    }
