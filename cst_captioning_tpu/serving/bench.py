"""Open-loop Poisson serving probe: p50/p99 latency + captions/s.

Open-loop means arrivals come from a PREDETERMINED schedule (seeded
exponential inter-arrival draws), never gated on completions — the honest
load model for "millions of users" traffic, where a slow server doesn't
slow the users down, it grows its own queue.  Latency is measured from
the SCHEDULED arrival, so queueing delay is part of the number.

The probe also enforces the compile discipline: ``engine.warm()`` pays
for every bucket's programs up front, and any program build after that
raises — steady-state serving must read 0 recompiles (the acceptance
contract; ``buckets.ProgramCache`` is the counter).

Latency-floor extensions (SERVING.md "Streaming & result cache"):

- ``zipf_alpha``/``unique_videos`` shape the request mix: real traffic
  is zipfian, so the stream draws each request's video from a seeded
  rank-``1/r^alpha`` distribution over ``unique_videos`` distinct
  feature sets (0 = the historical one-unique-video-per-request mix).
- ``cache_size`` arms the exact-result cache (serving/cache.py) and the
  probe keeps a DRILL RECORD: every cache-hit caption is compared bit
  for bit against its miss twin (the first decoded completion of the
  same video) — ``scripts/serve_report.py`` exits 1 on any mismatch.
- ``stream`` submits every request as streaming traffic, asserts PREFIX
  CONSISTENCY (the concatenation of a request's chunks must equal its
  final caption — a violation raises, failing the bench), and reports
  time-to-first-token and inter-chunk-gap percentiles beside p50/p99.

Fleet extension (SERVING.md "Fleet"): ``replicas > 1`` drives the SAME
seeded request stream through a :class:`fleet.FleetRouter` over N
engine replicas sharing one ProgramCache (and one result cache when
armed); ``kill_replica >= 0`` hard-kills that replica once half the
stream is in — the probe then proves the PR-9 bar FLEET-WIDE: every
request answered, zero program builds after warmup including through
the replica restart, and every caption bit-identical to a fault-free
single-engine decode of the same videos (the reference run at the end;
``scripts/serve_report.py`` exits 1 on a parity or recompile
violation).  The headline captions/s is caps/s/fleet by construction.

Determinism: the arrival schedule, per-video features, and the zipfian
mix are seeded, so two runs issue the identical request stream; the
measured latencies are wall-clock (that is the point).  The repo bench
(`bench.py --stage serving`) feeds this into its one-JSON-line + cache
machinery.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..telemetry.lifecycle import LifecycleTracer
from .buckets import DEFAULT_BUCKETS, ProgramCache
from .cache import ResultCache
from .engine import ServingEngine, _trim_eos


def poisson_arrivals(num_requests: int, rate_hz: float,
                     seed: int = 0) -> np.ndarray:
    """Cumulative arrival times (seconds) for an open-loop Poisson stream."""
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / float(rate_hz),
                                     size=int(num_requests)))


def _thinned_arrivals(num_requests: int, peak_hz: float,
                      rate_at, seed: int) -> np.ndarray:
    """Lewis–Shedler thinning: draw candidate gaps at the PEAK rate,
    accept each candidate with probability ``rate_at(t)/peak`` — an
    exact non-homogeneous Poisson process, deterministic per seed."""
    rng = np.random.default_rng(seed)
    out = np.empty(int(num_requests), dtype=np.float64)
    t = 0.0
    k = 0
    peak = float(peak_hz)
    while k < out.size:
        t += rng.exponential(1.0 / peak)
        if rng.random() * peak <= rate_at(t):
            out[k] = t
            k += 1
    return out


def diurnal_arrivals(num_requests: int, rate_hz: float, seed: int = 0,
                     period_s: float = 60.0,
                     depth: float = 0.9) -> np.ndarray:
    """Seeded diurnal sinusoid: the mean rate is ``rate_hz`` but the
    instantaneous rate swings ``±depth`` around it over ``period_s`` —
    the compressed day/night cycle of user traffic."""
    base = float(rate_hz)
    d = min(max(float(depth), 0.0), 1.0)
    w = 2.0 * np.pi / float(period_s)

    def rate_at(t: float) -> float:
        return base * (1.0 + d * np.sin(w * t))

    return _thinned_arrivals(num_requests, base * (1.0 + d),
                             rate_at, seed)


def burst_arrivals(num_requests: int, rate_hz: float, seed: int = 0,
                   period_s: float = 8.0, duty: float = 0.25,
                   burst_factor: float = 4.0) -> np.ndarray:
    """Square-wave burst storms: quiet at ``rate_hz`` for most of each
    ``period_s``, then a ``burst_factor``x storm for the ``duty``
    fraction — the traffic the fleet was NOT sized for (the autoscale
    drill's shape)."""
    base = float(rate_hz)
    f = max(1.0, float(burst_factor))
    du = min(max(float(duty), 0.0), 1.0)
    p = float(period_s)

    def rate_at(t: float) -> float:
        return base * f if (t % p) < du * p else base

    return _thinned_arrivals(num_requests, base * f, rate_at, seed)


def replay_arrivals(path: str, num_requests: int) -> np.ndarray:
    """Arrival times replayed from a JSONL trace (one ``{"t": seconds}``
    object per line — the shape fleet_metrics/lifecycle tooling can
    produce from production logs).  Times are sorted and rebased to 0;
    the trace must supply at least ``num_requests`` events (extra
    events are truncated)."""
    import json as _json

    ts = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            ts.append(float(_json.loads(line)["t"]))
    n = int(num_requests)
    if len(ts) < n:
        raise ValueError(
            f"arrival trace {path} has {len(ts)} events, need {n}")
    arr = np.sort(np.asarray(ts, dtype=np.float64))[:n]
    return arr - arr[0]


def make_arrivals(shape: str, num_requests: int, rate_hz: float,
                  seed: int = 0,
                  trace_path: Optional[str] = None) -> np.ndarray:
    """Dispatch on ``--arrival_shape``: the one place the probe's
    traffic models live, so the CLI choices and the generators cannot
    drift apart."""
    if shape == "poisson":
        return poisson_arrivals(num_requests, rate_hz, seed)
    if shape == "diurnal":
        return diurnal_arrivals(num_requests, rate_hz, seed)
    if shape == "burst":
        return burst_arrivals(num_requests, rate_hz, seed)
    if shape == "replay":
        if not trace_path:
            raise ValueError(
                "--arrival_shape replay needs --arrival_trace")
        return replay_arrivals(trace_path, num_requests)
    raise ValueError(
        f"unknown arrival shape {shape!r} "
        "(expected poisson|diurnal|burst|replay)")


def zipfian_mix(num_requests: int, unique_videos: int, alpha: float,
                seed: int = 0) -> np.ndarray:
    """Video index per request: rank-``1/r^alpha`` draws over the unique
    set (``alpha`` <= 0 = deterministic round-robin, the historical
    every-request-distinct mix when ``unique_videos == num_requests``)."""
    n, u = int(num_requests), max(1, int(unique_videos))
    if alpha <= 0:
        return np.arange(n) % u
    ranks = np.arange(1, u + 1, dtype=np.float64)
    p = ranks ** -float(alpha)
    p /= p.sum()
    return np.random.default_rng(seed).choice(u, size=n, p=p)


def serving_probe(model, variables, feat_shapes: Sequence,
                  *, num_requests: int = 24, rate_hz: float = 8.0,
                  max_len: int = 30, beam_size: int = 1,
                  length_norm: float = 0.0, decode_chunk: int = 8,
                  bucket_sizes: Sequence[int] = DEFAULT_BUCKETS,
                  queue_limit: int = 0, seed: int = 0,
                  stream: bool = False, cache_size: int = 0,
                  unique_videos: Optional[int] = None,
                  zipf_alpha: float = 0.0,
                  replicas: int = 1, kill_replica: int = -1,
                  arrival_shape: str = "poisson",
                  arrival_trace: Optional[str] = None,
                  lifecycle: bool = False,
                  blackbox_path: Optional[str] = None,
                  registry=None, tracer=None,
                  clock=time.perf_counter) -> Dict[str, Any]:
    """Drive one engine through a seeded Poisson load; -> metrics dict.

    Raises ``RuntimeError`` if any program compiles after warmup (the
    0-recompiles-under-steady-load assert) or, under ``stream``, if any
    request's concatenated chunks differ from its final caption — both
    in the probe itself so a regression fails the bench rather than
    shipping a latency cliff or a lying stream.
    """
    n = int(num_requests)
    uniq = n if unique_videos is None else max(1, min(int(unique_videos), n))
    arrivals = make_arrivals(arrival_shape, n, rate_hz, seed,
                             trace_path=arrival_trace)
    feat_rng = np.random.default_rng(seed + 1)
    feats = [
        [feat_rng.standard_normal(s).astype(np.float32)
         for s in feat_shapes]
        for _ in range(uniq)
    ]
    video_of = zipfian_mix(n, uniq, zipf_alpha, seed + 2)
    cache = ResultCache(int(cache_size)) if cache_size else None
    fleet_n = max(1, int(replicas))
    programs = ProgramCache(registry)   # shared across replicas/restarts
    # The request-lifecycle tracing plane (telemetry/lifecycle.py): the
    # probe's measured-latency twin — per-request attribution must
    # reconcile with the probe's own completion latencies, so the
    # tracer shares the probe clock.  Disarmed (the default), nothing
    # below pays more than an is-None check per hook — the "no caps/s
    # regression" mode the bench line is normally measured in.
    recorder = (LifecycleTracer(clock=clock, tracer=tracer,
                                registry=registry)
                if lifecycle or blackbox_path else None)

    def build_engine(_k=0):
        lc = None
        if recorder is not None:
            lc = (recorder.for_replica(_k) if fleet_n > 1
                  else recorder)
        return ServingEngine(
            model, variables, feat_shapes, max_len=max_len,
            beam_size=beam_size, length_norm=length_norm,
            decode_chunk=decode_chunk, bucket_sizes=bucket_sizes,
            queue_limit=queue_limit, result_cache=cache,
            program_cache=programs, lifecycle=lc,
            registry=registry, tracer=tracer, clock=clock)

    if fleet_n > 1:
        from .fleet import FleetRouter

        engine = FleetRouter(build_engine, fleet_n, lifecycle=recorder,
                             registry=registry, clock=clock)
    else:
        engine = build_engine()
    warm_builds = engine.warm()["compiles"]
    kill_at = (n // 2 if fleet_n > 1 and kill_replica >= 0 else None)
    killed = False

    t0 = clock()
    submitted = 0
    latencies: Dict[Any, float] = {}
    tokens: Dict[Any, np.ndarray] = {}
    hit: Dict[Any, bool] = {}
    chunks: Dict[Any, list] = {}
    shed = 0
    dropped = 0

    def harvest(comps):
        nonlocal shed, dropped
        for comp in comps:
            # Latency from the SCHEDULED arrival (open-loop convention).
            latencies[comp.request_id] = (
                (comp.done_at - t0) - arrivals[comp.request_id])
            tokens[comp.request_id] = np.asarray(comp.tokens)
            hit[comp.request_id] = bool(comp.cache_hit)
        # A drop record is an ANSWER (expired / shed / admit-failed);
        # a fault-free probe sees zero, but the loop must terminate on
        # them (the fleet kill drill's worst case), never spin.
        dropped += len(engine.pop_dropped())
        if stream:
            for ch in engine.pop_stream_chunks():
                chunks.setdefault(ch.request_id, []).append(ch)

    while len(latencies) + shed + dropped < n:
        now = clock() - t0
        while submitted < n and arrivals[submitted] <= now:
            if not engine.submit(submitted,
                                 feats[int(video_of[submitted])],
                                 stream=stream):
                shed += 1
            submitted += 1
        if kill_at is not None and not killed and submitted >= kill_at:
            # The hard kill/restart drill: one replica dies mid-flight
            # with residents aboard; its requests re-queue and the
            # restarted replica re-warms from the shared ProgramCache.
            engine.kill_replica(int(kill_replica) % fleet_n)
            killed = True
        harvest(engine.step())
        if engine.idle and submitted < n:
            time.sleep(min(max(arrivals[submitted] - (clock() - t0), 0.0),
                           0.01))
    makespan = clock() - t0

    stats = engine.stats()
    recompiles = stats["compiles"] - warm_builds
    if recompiles != 0:
        raise RuntimeError(
            f"serving recompiled under steady load: {recompiles} program "
            f"build(s) after warmup (bucket discipline violated — "
            "SERVING.md 'Bucket policy')")

    stream_out: Dict[str, Any] = {"enabled": bool(stream)}
    if stream:
        # Prefix consistency, end to end: every request's streamed chunks
        # must concatenate to its final caption, bit for bit.
        bad = []
        for rid, row in tokens.items():
            got = (np.concatenate([np.asarray(c.tokens) for c in
                                   sorted(chunks.get(rid, []),
                                          key=lambda c: c.seq)])
                   if chunks.get(rid) else np.zeros((0,), np.int32))
            if not np.array_equal(got, _trim_eos(row)):
                bad.append(rid)
        if bad:
            raise RuntimeError(
                f"streamed chunks are not prefix-consistent with the "
                f"final caption for request(s) {bad[:5]} — the streaming "
                "contract is broken (SERVING.md)")
        stream_out.update({
            "chunks": stats["stream_chunks"],
            "ttft_p50_ms": stats["ttft_p50_ms"],
            "ttft_p99_ms": stats["ttft_p99_ms"],
            "chunk_gap_p50_ms": stats["chunk_gap_p50_ms"],
            "chunk_gap_p99_ms": stats["chunk_gap_p99_ms"],
            "prefix_ok": True,
        })

    cache_out: Dict[str, Any] = {"enabled": bool(cache_size)}
    if cache_size:
        # The drill record: every hit must be bit-identical to its miss
        # twin (the first DECODED completion of the same video at this
        # configuration).  serve_report exits 1 on a mismatch.
        twin: Dict[int, np.ndarray] = {}
        for rid in sorted(tokens):
            if not hit[rid]:
                twin.setdefault(int(video_of[rid]), tokens[rid])
        mismatches = sum(
            1 for rid in tokens
            if hit[rid] and not np.array_equal(
                tokens[rid], twin.get(int(video_of[rid]))))
        hm = stats["cache_hits"] + stats["cache_misses"]
        cache_out.update({
            "hits": stats["cache_hits"],
            "misses": stats["cache_misses"],
            "evictions": stats["cache_evictions"],
            "bypass": stats["cache_bypass"],
            "errors": stats["cache_errors"],
            "entries": stats["cache_entries"],
            "capacity": stats["cache_capacity"],
            "hit_rate": round(stats["cache_hits"] / hm, 4) if hm else None,
            "parity_ok": mismatches == 0,
            "parity_mismatches": mismatches,
        })

    fleet_out: Dict[str, Any] = {"enabled": fleet_n > 1}
    if fleet_n > 1:
        # The fleet acceptance record (SERVING.md "Fleet"): every
        # caption bit-identical to a fault-free SINGLE-ENGINE decode of
        # the same videos.  The reference engine shares the ProgramCache
        # (same config identity -> zero builds, asserted below) but
        # never the result cache (a hit would skip the reference
        # decode and prove nothing).
        ref_engine = ServingEngine(
            model, variables, feat_shapes, max_len=max_len,
            beam_size=beam_size, length_norm=length_norm,
            decode_chunk=decode_chunk, bucket_sizes=bucket_sizes,
            queue_limit=0, program_cache=programs, clock=clock)
        for v in range(uniq):
            ref_engine.submit(("ref", v), feats[v])
        ref: Dict[int, np.ndarray] = {}
        for comp in ref_engine.run_until_idle():
            ref[int(comp.request_id[1])] = np.asarray(comp.tokens)
        mismatches = sum(
            1 for rid, row in tokens.items()
            if not np.array_equal(row, ref.get(int(video_of[rid]))))
        ref_builds = programs.builds - warm_builds
        if ref_builds != 0:
            raise RuntimeError(
                f"the fault-free reference engine compiled {ref_builds} "
                "program(s) through the shared fleet ProgramCache — the "
                "config identity is broken (SERVING.md 'Fleet')")
        st = engine.stats()
        fleet_out.update({
            "replicas": fleet_n,
            **st["fleet"],
            "killed_replica": (int(kill_replica) % fleet_n if killed
                               else None),
            "answered": len(latencies) + shed + dropped,
            "dropped": dropped,
            "parity_ok": mismatches == 0,
            "parity_mismatches": mismatches,
            "per_replica": st["per_replica"],
        })

    lifecycle_out: Dict[str, Any] = {"enabled": recorder is not None}
    attribution: Optional[Dict[str, Any]] = None
    if recorder is not None:
        # Terminal accounting (every submitted id reaches exactly one
        # terminal event) + per-request attribution reconciled against
        # the engine's measured latencies — serve_report exits 1 on
        # either gate failing (the ISSUE-14 acceptance checks).
        attribution = recorder.attribution_report()
        lifecycle_out.update({
            "events": recorder.emitted(),
            "retained": len(recorder.events()),
            **recorder.accounting(),
        })
        if blackbox_path:
            recorder.attach(
                health=engine.health,
                # The flat counter map — the documented blackbox shape
                # (SERVING.md schema 1), same as the serving front ends.
                counters=((lambda: registry.snapshot().get("counters"))
                          if registry is not None else None),
                program_cache=lambda: {"builds": programs.builds,
                                       "entries": len(programs)})
            recorder.dump(blackbox_path, reason="probe_end")
            lifecycle_out["blackbox"] = str(blackbox_path)

    lat_ms = np.asarray(sorted(latencies.values())) * 1e3
    pct = (lambda q: round(float(np.percentile(lat_ms, q)), 3)
           if lat_ms.size else None)
    return {
        "captions_per_sec": round(len(latencies) / makespan, 2),
        "latency_p50_ms": pct(50),
        "latency_p99_ms": pct(99),
        "latency_mean_ms": (round(float(lat_ms.mean()), 3)
                            if lat_ms.size else None),
        "num_requests": n,
        "completed": len(latencies),
        "shed": shed,
        "dropped": dropped,
        "rate_hz": float(rate_hz),
        "arrival_shape": str(arrival_shape),
        "arrival_seed": int(seed),
        "unique_videos": uniq,
        "zipf_alpha": float(zipf_alpha),
        "makespan_s": round(makespan, 3),
        "recompiles_after_warmup": recompiles,
        "program_builds_warm": warm_builds,
        "buckets": list(engine.buckets),
        "slots": stats["slots"],
        "chunk_dispatches": stats["chunk_dispatches"],
        "beam_size": engine.beam_size,
        "decode_chunk": engine.chunk,
        "max_len": int(max_len),
        "stream": stream_out,
        "cache": cache_out,
        # Request-lifecycle record (telemetry/lifecycle.py): terminal
        # accounting + (when armed) the latency-attribution components;
        # serve_report gates on both.
        "lifecycle": lifecycle_out,
        **({"attribution": attribution} if attribution is not None
           else {}),
        # Fleet record (serve_report renders per-replica rows and gates
        # on parity_ok; absent/disabled on single-engine probes so old
        # records keep their exact shape).
        **({"fleet": fleet_out} if fleet_n > 1 else {}),
        # Fault-tolerance audit (all 0 on a healthy fault-free probe;
        # scripts/serve_report.py renders them and FAILS on a
        # rebuild-recompile violation — RESILIENCE.md "Serving faults").
        **engine.recovery_counters(),
    }
