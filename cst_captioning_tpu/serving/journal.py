"""Durable intake journal: the supervisor process as a failure domain.

Every rung of the resilience ladder so far — engine self-heal (PR 9),
replica restart (PR 13), child-process requeue (PR 16) — keeps its
exactly-once bookkeeping in the SUPERVISOR's memory.  Kill the
supervisor mid-storm and every parked, in-flight, and half-streamed
request vanishes with no terminal answer ever sent.  This module is the
write-ahead record that survives that death:

- **accept** records are appended (fsync'd, schema-stamped) *before*
  placement: once the supervisor has said yes to a request, a crash
  cannot unsay it;
- **mark** records journal each streamed chunk at send time (the
  supervisor-level watermark plus the chunk's tokens/text), so a
  relaunch resumes the stream prefix-consistently and can replay the
  journaled prefix to a reconnecting client;
- **term** records journal the terminal response at send time:
  a duplicate submit of an already-terminal idempotency key is answered
  from the record with zero decode work.

**Torn-tail tolerance**: records are framed one per line with a
content checksum; a crash mid-append leaves at most one torn final
line, which the scan drops — a SEALED record (checksummed + newline-
terminated) is never dropped and never double-applied.  Every journal
open starts a FRESH segment, so new appends never land after torn
bytes.

**Segment rotation + compaction bound disk**: when the active segment
passes ``segment_bytes`` it is sealed and a new one starts; with
compaction on, the sealed state is rewritten into one
``compact-<N>.wal`` (terminal records retire their accept/mark
entries; only a bounded tail of terminals is kept for idempotent
re-answers) published through ``integrity.durable_rename`` and the
retired segments are unlinked.  The scan order is: newest compact
file, then every ``seg-J.wal`` with ``J >=`` its covers-up-to counter.

Threading: all append/lookup paths are single-owner on the
supervisor's scheduler loop (the PR 16 ownership law); only the small
stats/high-water view is shared with the exit-snapshot writer, under
the one declared journal lock.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional

from ..resilience.integrity import durable_rename, fsync_dir
from ..utils.locksan import declare_order, named_lock

log = logging.getLogger("cst_captioning_tpu.serving.journal")

#: Journal record/file format version (schema-stamped on every record).
JOURNAL_SCHEMA = 1

#: Record kinds (a typo'd kind is a programming error, like lifecycle's
#: EVENT_KINDS).
RECORD_KINDS = ("accept", "mark", "term")

#: Bounded idempotency window: how many terminal responses stay
#: replayable for duplicate-id answering.  Terminals past the bound are
#: retired by compaction (and from memory) — the disk bound the ISSUE
#: requires; a duplicate of a retired id is simply a fresh request.
TERMINAL_KEEP = 4096

_SEG_RE = re.compile(r"^seg-(\d{8})\.wal$")
_COMPACT_RE = re.compile(r"^compact-(\d{8})\.wal$")

#: Declared acquisition order (cstlint:lock-order + the runtime
#: sanitizer): the journal's one shared structure — the stats/high-water
#: view read by the exit-snapshot writer — is a leaf; nothing nests
#: inside it.
LOCK_ORDER = ("serving.journal.state",)
declare_order(*LOCK_ORDER)


def _crc(payload: bytes) -> str:
    """Content checksum for one record line (sha256 prefix — torn-write
    detection, not cryptographic integrity)."""
    return hashlib.sha256(payload).hexdigest()[:12]


def _encode(rec: Dict[str, Any]) -> bytes:
    """One journal record -> one framed line: canonical JSON plus a
    checksum over the canonical bytes, newline-terminated.  The
    newline + checksum together make every sealed record provably
    whole under any byte-boundary truncation."""
    payload = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    framed = json.dumps({"v": payload, "crc": _crc(payload.encode())},
                        sort_keys=True, separators=(",", ":"))
    return framed.encode() + b"\n"


def _decode_line(line: bytes) -> Optional[Dict[str, Any]]:
    """-> the record, or None for a torn/corrupt line."""
    try:
        frame = json.loads(line.decode("utf-8"))
        payload = frame["v"]
        if frame["crc"] != _crc(payload.encode()):
            return None
        rec = json.loads(payload)
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        return None
    if not isinstance(rec, dict) or rec.get("kind") not in RECORD_KINDS:
        return None
    return rec


class JournalRecovery:
    """What a scan found: the replayable state plus the honesty
    counters (torn lines dropped, segments read)."""

    def __init__(self) -> None:
        self.terminals: Dict[str, Dict[str, Any]] = {}
        self.accepts: Dict[str, Dict[str, Any]] = {}
        self.marks: Dict[str, List[Dict[str, Any]]] = {}
        self.torn_records = 0
        self.segments_scanned = 0
        self.records = 0
        #: insertion order of terminal keys (compaction retention).
        self.terminal_order: List[str] = []

    def apply(self, rec: Dict[str, Any]) -> None:
        kind = rec["kind"]
        key = rec.get("key")
        self.records += 1
        if kind == "accept":
            # Idempotent on rescan: the FIRST accept wins (a compacted
            # rewrite precedes any later live appends in scan order).
            self.accepts.setdefault(key, rec)
        elif kind == "mark":
            self.marks.setdefault(key, []).append(rec)
        elif kind == "term":
            if key not in self.terminals:
                self.terminal_order.append(key)
            self.terminals[key] = rec
            # Terminal retires the stream marks: replay never needs
            # them once the full caption is on record.
            self.marks.pop(key, None)

    def open_requests(self) -> List[Dict[str, Any]]:
        """Accepted-but-unanswered records, intake order — the replay
        set."""
        return [rec for key, rec in self.accepts.items()
                if key not in self.terminals]


def _scan_segment(path: str, rec_out: JournalRecovery) -> bool:
    """Apply every sealed record in one segment; -> True when the
    segment ended in a torn line (counted, dropped).  A sealed record
    is newline-terminated with a matching checksum — anything else is
    the torn tail of a crashed append and scanning stops there (bytes
    after a torn line are unframed garbage by definition)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    rec_out.segments_scanned += 1
    torn = False
    end = 0
    while end < len(data):
        nl = data.find(b"\n", end)
        if nl < 0:
            # Unterminated tail: the crash landed mid-append.
            torn = True
            break
        rec = _decode_line(data[end:nl])
        if rec is None:
            torn = True
            break
        rec_out.apply(rec)
        end = nl + 1
    if torn:
        rec_out.torn_records += 1
    return torn


def list_segments(root: str) -> List[str]:
    """Scan-ordered segment basenames: the newest compact file (if
    any), then every ``seg-J.wal`` at or after the counter it covers
    up to.  Older segments/compacts are superseded leftovers."""
    segs: Dict[int, str] = {}
    compacts: Dict[int, str] = {}
    try:
        names = os.listdir(root)
    except OSError:
        return []
    for name in names:
        m = _SEG_RE.match(name)
        if m:
            # cstlint: disable=device-scalar-fetch -- regex group of a filename: host string, never a device array
            segs[int(m.group(1))] = name
            continue
        m = _COMPACT_RE.match(name)
        if m:
            # cstlint: disable=device-scalar-fetch -- regex group of a filename: host string, never a device array
            compacts[int(m.group(1))] = name
    floor = max(compacts) if compacts else 0
    ordered: List[str] = []
    if compacts:
        ordered.append(compacts[floor])
    ordered.extend(segs[n] for n in sorted(segs) if n >= floor)
    return ordered


def scan_dir(root: str) -> JournalRecovery:
    """Read-only recovery scan (the fleet_report cross-check uses this
    without constructing a journal — no new segment is started)."""
    rec = JournalRecovery()
    for name in list_segments(root):
        _scan_segment(os.path.join(root, name), rec)
    return rec


class IntakeJournal:
    """The write-ahead intake journal (module docstring).

    ``wall`` is the injectable wall clock (arrival clocks must cross a
    process death, which no monotonic clock survives); ``clock`` is
    unused here but mirrors the supervisor's injection seam.  All
    mutating methods are scheduler-thread-only
    (cstlint: owned_by=scheduler); :meth:`high_water` and
    :meth:`stats` are safe from the exit-snapshot writer."""

    def __init__(self, root: str, *, segment_bytes: int = 1 << 20,
                 compact: bool = True,
                 wall: Callable[[], float] = time.time):
        self.root = os.path.abspath(root)
        self.segment_bytes = max(1, int(segment_bytes))
        self.compact_enabled = bool(compact)
        self.wall = wall
        os.makedirs(self.root, exist_ok=True)
        #: What the pre-crash journal held — the supervisor's replay
        #: input (read once at construction; never mutated after).
        self.recovery = self._recover()
        # Live idempotency state, primed from recovery.  Scheduler-
        # owned: lookups and appends both happen on the one loop.
        self._terminals = dict(self.recovery.terminals)  # cstlint: owned_by=scheduler
        self._terminal_order = list(self.recovery.terminal_order)  # cstlint: owned_by=scheduler
        self._accepts = dict(self.recovery.accepts)  # cstlint: owned_by=scheduler
        self._marks = {k: list(v) for k, v
                       in self.recovery.marks.items()}  # cstlint: owned_by=scheduler
        self._trim_terminals()
        # The shared stats/high-water view (exit snapshot, health).
        self._state_lock = named_lock("serving.journal.state")
        self._hw: Dict[str, Any] = {}  # cstlint: guarded_by=self._state_lock
        self._c = {"appends": 0, "rotations": 0, "compactions": 0,
                   "fsyncs": 0}  # cstlint: guarded_by=self._state_lock
        # Every open starts a FRESH segment: appends never land after a
        # torn tail, and recovery evidence stays byte-frozen on disk.
        self._seg_n = self._next_counter()
        self._f = None
        self._offset = 0
        self._open_segment()

    # -- segment plumbing --------------------------------------------------

    def _next_counter(self) -> int:
        best = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            names = []
        for name in names:
            for rx in (_SEG_RE, _COMPACT_RE):
                m = rx.match(name)
                if m:
                    # cstlint: disable=device-scalar-fetch -- regex group of a filename: host string, never a device array
                    best = max(best, int(m.group(1)))
        return best + 1

    def _seg_name(self, n: int) -> str:
        return f"seg-{n:08d}.wal"

    def _open_segment(self) -> None:
        path = os.path.join(self.root, self._seg_name(self._seg_n))
        self._f = open(path, "ab")
        self._offset = 0
        fsync_dir(self.root)   # the segment's directory entry is durable
        self._publish_hw()

    def _recover(self) -> JournalRecovery:
        rec = JournalRecovery()
        for name in list_segments(self.root):
            _scan_segment(os.path.join(self.root, name), rec)
        return rec

    def _publish_hw(self) -> None:
        hw = {"segment": self._seg_name(self._seg_n),
              "offset": int(self._offset)}
        with self._state_lock:
            self._hw = hw

    # -- THE one append path -----------------------------------------------

    def _append(self, rec: Dict[str, Any]) -> None:
        """The ONE fsync'd journal append helper — every durable
        journal byte goes through here (cstlint:journal-append enforces
        that no other module opens a ``*.wal`` for writing).  The
        record is schema-stamped, framed with a checksum, written,
        flushed, and fsync'd BEFORE the caller proceeds: when this
        returns, the record survives a SIGKILL."""
        rec = dict(rec)
        rec["schema"] = JOURNAL_SCHEMA
        data = _encode(rec)
        self._f.write(data)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._offset += len(data)
        with self._state_lock:
            self._c["appends"] += 1
            self._c["fsyncs"] += 1
        self._publish_hw()
        if self._offset >= self.segment_bytes:
            self._rotate()

    def _rotate(self) -> None:
        """Seal the active segment and start the next; compaction (when
        enabled) folds every sealed segment into one compact file so
        terminal records retire their entries and disk stays bounded."""
        self._f.close()
        with self._state_lock:
            self._c["rotations"] += 1
        self._seg_n += 1
        self._open_segment()
        if self.compact_enabled:
            self._compact()

    def _compact(self) -> None:
        """Rewrite the sealed state (everything before the active
        segment) into ``compact-<active>.wal``: open requests keep
        their accept + marks, terminal keys keep ONLY their (bounded)
        terminal record.  Published through the one durable-rename
        discipline, then the superseded files are unlinked — a crash
        at any point leaves either the old segment set or the new
        compact file authoritative, never neither."""
        active = self._seg_name(self._seg_n)
        superseded = [n for n in list_segments(self.root) if n != active]
        tmp = os.path.join(self.root, f"compact-{self._seg_n:08d}.tmp")
        dst = os.path.join(self.root, f"compact-{self._seg_n:08d}.wal")
        with open(tmp, "wb") as f:
            for key, acc in self._accepts.items():
                if key in self._terminals:
                    continue
                f.write(_encode(acc))
                for m in self._marks.get(key, ()):
                    f.write(_encode(m))
            for key in self._terminal_order:
                term = self._terminals.get(key)
                if term is not None:
                    f.write(_encode(term))
            f.flush()
            os.fsync(f.fileno())
        durable_rename(tmp, dst)
        for name in superseded:
            try:
                os.unlink(os.path.join(self.root, name))
            except OSError:
                pass
        fsync_dir(self.root)
        with self._state_lock:
            self._c["compactions"] += 1
        log.info("journal: compacted %d segment(s) into %s",
                 len(superseded), os.path.basename(dst))

    # -- record writers (scheduler thread) ---------------------------------

    def accept(self, key: str, client_id: Any, video_id: str, *,
               stream: bool, ttl_ms: Optional[float], no_cache: bool,
               arrival_wall: Optional[float] = None) -> None:
        """Journal one accepted request BEFORE placement."""
        rec = {"kind": "accept", "key": str(key), "client_id": client_id,
               "video_id": str(video_id), "stream": bool(stream),
               "ttl_ms": (None if ttl_ms is None else float(ttl_ms)),
               "no_cache": bool(no_cache),
               "arrival_wall": (self.wall() if arrival_wall is None
                                else float(arrival_wall))}
        self._accepts.setdefault(rec["key"], rec)
        self._append(rec)

    def mark(self, key: str, seq: int, tokens: List[int],
             text: str, sent_tokens: int) -> None:
        """Journal one streamed chunk at send time: the watermark a
        relaunch resumes from, plus the chunk itself so a reconnecting
        client can be caught up from the record."""
        rec = {"kind": "mark", "key": str(key), "seq": int(seq),
               "tokens": [int(t) for t in tokens], "text": str(text),
               "sent_tokens": int(sent_tokens)}
        self._marks.setdefault(rec["key"], []).append(rec)
        self._append(rec)

    def terminal(self, key: str, resp: Dict[str, Any]) -> None:
        """Journal the terminal response at send time; retires the
        key's stream marks (the caption on record is authoritative)."""
        key = str(key)
        rec = {"kind": "term", "key": key, "resp": dict(resp)}
        if key not in self._terminals:
            self._terminal_order.append(key)
        self._terminals[key] = rec
        self._marks.pop(key, None)
        self._trim_terminals()
        self._append(rec)

    def _trim_terminals(self) -> None:
        while len(self._terminal_order) > TERMINAL_KEEP:
            old = self._terminal_order.pop(0)
            self._terminals.pop(old, None)
            self._accepts.pop(old, None)

    # -- lookups (scheduler thread) ----------------------------------------

    def terminal_for(self, key: str) -> Optional[Dict[str, Any]]:
        """The journaled terminal response for ``key`` (the idempotent
        duplicate-id answer), or None."""
        rec = self._terminals.get(str(key))
        return None if rec is None else dict(rec["resp"])

    def marks_for(self, key: str) -> List[Dict[str, Any]]:
        """The journaled chunks for an OPEN key, seq order — the
        catch-up replay a reconnecting stream client receives."""
        return [dict(m) for m in self._marks.get(str(key), ())]

    def is_open(self, key: str) -> bool:
        return (str(key) in self._accepts
                and str(key) not in self._terminals)

    def open_requests(self) -> List[Dict[str, Any]]:
        """Pre-crash accepts still unanswered (replay input)."""
        return self.recovery.open_requests()

    # -- shared views ------------------------------------------------------

    def high_water(self) -> Dict[str, Any]:
        """The durable high-water mark: the active segment + byte
        offset every sealed record lies at or below.  Safe off the
        scheduler thread (exit snapshot / fleet_report cross-check)."""
        with self._state_lock:
            return dict(self._hw)

    def stats(self) -> Dict[str, Any]:
        with self._state_lock:
            c = dict(self._c)
            hw = dict(self._hw)
        return {
            "schema": JOURNAL_SCHEMA,
            "dir": self.root,
            "high_water": hw,
            "appends": c["appends"],
            "fsyncs": c["fsyncs"],
            "rotations": c["rotations"],
            "compactions": c["compactions"],
            "open": sum(1 for k in self._accepts
                        if k not in self._terminals),
            "terminals": len(self._terminals),
            "recovered_open": len(self.recovery.open_requests()),
            "recovered_terminals": len(self.recovery.terminals),
            "torn_records": self.recovery.torn_records,
            "segments_scanned": self.recovery.segments_scanned,
        }

    def close(self) -> None:
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except (OSError, ValueError):
            pass
        try:
            self._f.close()
        except OSError:
            pass
