"""Fleet routing policy — the ONE definition, shared across topologies.

The in-process :class:`fleet.FleetRouter` (N engines, one process) and
the process-fleet supervisor (:mod:`serving.supervisor` — N serve.py OS
processes over sockets) implement the same serving policies:

- **healthy-tier-first placement** (:func:`rank_key`): candidates sort
  into the healthy tier before the degraded one, least-loaded within a
  tier, replica index as the deterministic tiebreak;
- **worst-of health** (:func:`worst_status`): the fleet's one-word
  status is its sickest replica's, with per-replica detail alongside;
- **fleet-edge deadline shed** (:func:`deadline_unmeetable`): a TTL
  provably below EVERY candidate's p99 service floor is shed at the
  edge with an explicit answer, before it wastes a queue slot anywhere;
- **paced child queries** (:class:`QueryPacer`): the ONE interval +
  failure-backoff policy for everything the supervisor asks a child on
  a timer (health polls, the fleet-metrics scraper, clock pings) — one
  policy object per query family, so "how often do we poke a struggling
  child" cannot fork between the health plane and the metrics plane.

Both routers import these functions rather than re-deriving the policy,
so a policy change cannot fork the two topologies (SERVING.md "Fleet" /
"Process fleet").  Pure host code — no jax, importable by a supervisor
process that never touches an accelerator.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

#: Worst-of ordering for the fleet health status (SERVING.md "Fleet"):
#: a rotating replica makes the honest worst-of view ``draining``; the
#: per-replica detail disambiguates.  ``dead`` replicas (and any status
#: outside the table — ``restarting``, ``starting``) rank as
#: ``degraded`` fleet-wide: capacity lost, the survivors still serve.
STATUS_RANK = {"ok": 0, "degraded": 1, "draining": 2}


def rank_key(degraded: bool, load: int, index: int) -> Tuple[int, int, int]:
    """Candidate sort key: healthy tier first, least-loaded within a
    tier, index as the deterministic tiebreak.  ``load`` is whatever the
    caller can measure cheaply (queue + residents for an in-process
    engine; the supervisor's own in-flight count over a socket)."""
    return (1 if degraded else 0, int(load), int(index))


def worst_status(statuses: Iterable[str]) -> str:
    """The fleet's one-word health: the worst replica status under
    :data:`STATUS_RANK` (unknown statuses rank as ``degraded``); an
    empty fleet is ``degraded``, never silently ``ok``."""
    ranks = [STATUS_RANK.get(s, STATUS_RANK["degraded"]) for s in statuses]
    worst = max(ranks) if ranks else STATUS_RANK["degraded"]
    return next(k for k, v in STATUS_RANK.items() if v == worst)


def deadline_unmeetable(ttl_ms: float,
                        floors_s: Iterable[Optional[float]],
                        margin: float = 1.0) -> bool:
    """True when ``ttl_ms`` is provably below every candidate's service
    floor (one p99 decode chunk, seconds) — the fleet-edge shed test.
    Conservative: any unknown floor (``None``, a replica whose latency
    window is not yet honest) makes the answer False — never shed on a
    guess.  ``margin`` inflates the floors (brownout rung 1 tightens
    admission by demanding margin-x headroom); the default 1.0 is the
    plain provably-unmeetable test."""
    floors = list(floors_s)
    if not floors or any(f is None for f in floors):
        return False
    return float(ttl_ms) / 1e3 < min(floors) * float(margin)


class QueryPacer:
    """Per-key interval pacing with failure backoff — the shared policy
    behind every timed supervisor→child query (ISSUE 17 satellite: the
    health poll and the fleet scraper must not each invent their own).

    A key (replica index, or any hashable) is **due** when its interval
    has elapsed since the last :meth:`sent`; a never-queried key is due
    immediately (the supervisor's first tick polls everything — the PR 16
    health-poll semantics, preserved).  Consecutive :meth:`failed` marks
    double the key's effective interval (capped at ``backoff_cap``
    multiples) so a wedged child is poked gently; one :meth:`ok` snaps
    it back.  :meth:`forget` resets a key entirely — call it when a
    replica restarts, so the fresh process is queried immediately.

    Pure host bookkeeping around a caller-supplied ``now`` (the
    supervisor's injected clock) — no threads, no time reads of its own,
    deterministic under a fake clock.
    """

    def __init__(self, interval_s: float, backoff_cap: int = 8):
        self.interval_s = max(float(interval_s), 0.0)
        self.backoff_cap = max(int(backoff_cap), 1)
        self._last: dict = {}      # key -> last sent `now`
        self._failures: dict = {}  # key -> consecutive failures

    def due(self, key, now: float) -> bool:
        last = self._last.get(key)
        if last is None:
            return True
        mult = min(2 ** self._failures.get(key, 0), self.backoff_cap)
        return (now - last) >= self.interval_s * mult

    def sent(self, key, now: float) -> None:
        self._last[key] = float(now)

    def ok(self, key) -> None:
        self._failures.pop(key, None)

    def failed(self, key) -> None:
        self._failures[key] = self._failures.get(key, 0) + 1

    def forget(self, key) -> None:
        self._last.pop(key, None)
        self._failures.pop(key, None)
