"""Batch-shape buckets + compile-once program cache for the serving engine.

XLA programs are shape-specialized, so a serving engine that sized its
batch to the instantaneous load would recompile on every queue-depth
change — the exact failure mode the compiler-first caching discipline
(PAPERS.md arXiv 2603.09555) exists to rule out.  Instead the engine runs
at one of a SMALL FIXED set of slot counts (the buckets), and every
compiled program is cached by a configuration-identity key built the same
way as bench's cache-config identity (``bench.resolved_config``): the
perf-affecting axes (bucket, beam, max_len, decode_chunk, decode_kernel,
scan_unroll, feature geometry, dtype), nothing request-dependent.

The cache keeps an explicit *builds* counter.  After ``warm()`` has paid
for every bucket's programs, steady-state load MUST read 0 new builds —
the serving bench probe asserts exactly that, and the counter is exported
through the metrics registry (``serve_compiles``) so a recompile storm in
production is a visible counter, not a silent latency cliff.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from ..utils.locksan import declare_order, named_lock

#: The shipped bucket ladder: smallest-sufficient bucket per load level,
#: grow-only under pressure (SERVING.md "Bucket policy").
DEFAULT_BUCKETS = (1, 4, 8)

#: Declared acquisition order (cstlint:lock-order + the runtime
#: sanitizer): the cache lock may be held while bumping the registry's
#: counter lock (`get` counts a won build inside its critical section),
#: never the reverse — the registry is a leaf lock project-wide.
LOCK_ORDER = ("serving.programs", "telemetry.registry")
declare_order(*LOCK_ORDER)


def parse_buckets(spec) -> Tuple[int, ...]:
    """``"1,4,8"`` (or an int sequence) -> sorted unique positive tuple.

    Raises ``ValueError`` with a one-line message naming the bad token —
    surfaced by opts.py as an argparse usage error.
    """
    if isinstance(spec, str):
        tokens = [t for t in spec.replace(" ", "").split(",") if t]
    else:
        tokens = list(spec)
    if not tokens:
        raise ValueError("bucket spec is empty; expected e.g. '1,4,8'")
    sizes = []
    for tok in tokens:
        try:
            n = int(tok)
        except (TypeError, ValueError):
            raise ValueError(
                f"bad bucket size {tok!r}; expected positive integers "
                "like '1,4,8'") from None
        if n < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {n}")
        sizes.append(n)
    return tuple(sorted(set(sizes)))


def pick_bucket(buckets: Tuple[int, ...], needed: int) -> int:
    """Smallest bucket that fits ``needed`` slots; the largest bucket when
    demand exceeds every bucket (excess waits in the queue)."""
    for b in buckets:
        if b >= needed:
            return b
    return buckets[-1]


class ProgramCache:
    """Compile-once cache for the engine's jitted programs.

    ``get(key, build)`` returns the cached callable or builds it exactly
    once, bumping ``builds`` (and the ``serve_compiles`` registry counter
    when a registry is attached).  Keys must carry the full configuration
    identity — two configs that could compile differently must never share
    a key.  Thread-safe: the server's front-end threads only enqueue, but
    a warm() racing a first request must not double-build.
    """

    def __init__(self, registry=None):
        self._lock = named_lock("serving.programs")
        self._programs: Dict[tuple, Callable] = {}  # cstlint: guarded_by=self._lock
        self._registry = registry
        # builds is read lock-free by the engine's stats path (a torn
        # int read is impossible under the GIL); writes stay locked.
        self.builds = 0

    def get(self, key: tuple, build: Callable[[], Callable]) -> Callable:
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                return fn
        # Build OUTSIDE the lock (jit closure construction may be slow);
        # a racing builder for the same key loses and its result is
        # dropped without counting.
        fn = build()
        with self._lock:
            won = self._programs.setdefault(key, fn)
            if won is fn:
                self.builds += 1
                if self._registry is not None:
                    self._registry.inc("serve_compiles")
            return won

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)


def config_key(*, bucket: int, beam_size: int, max_len: int,
               decode_chunk: int, length_norm: float, decode_kernel: str,
               scan_unroll: int, feat_shapes, dtype: str,
               kind: Optional[str] = None) -> tuple:
    """One canonical identity tuple for the program cache — the serving
    twin of bench's ``resolved_config`` (same axes, same spirit: a tuned
    run and its explicit-flag twin share an entry; different shapes never
    do)."""
    return (
        kind, int(bucket), int(beam_size), int(max_len), int(decode_chunk),
        float(length_norm), str(decode_kernel), int(scan_unroll),
        tuple(tuple(int(x) for x in s) for s in feat_shapes), str(dtype),
    )
