"""Health-aware fleet router over N self-healing serving engines.

One process used to own exactly one :class:`ServingEngine` on one
device.  The fleet layer keeps that engine exactly as it is — compiled
programs, continuous batching, the PR-9 self-healing ladder — and adds
the piece "millions of users" needs: a router that spreads JSONL
requests across N supervised engine REPLICAS (in-process, one engine
per replica; per-device via ``jax.default_device`` where devices
exist), consuming the health/exit taxonomy the single-engine plane
already speaks:

- **Routing policy** (``submit``): candidate replicas are the
  in-service ones (not draining, not dead), ranked healthy-first then
  least-loaded — the router ROUTES AROUND ``degraded`` replicas (a
  replica inside its recovery window only receives work when no ``ok``
  replica can take it) and never routes to a ``draining`` one.  A
  replica whose bounded queue sheds is skipped for the next candidate
  (``fleet_rerouted``); only when EVERY candidate sheds does the fleet
  shed (``fleet_shed``).
- **Fleet-edge deadline shed**: a request whose TTL cannot cover even
  one p99 decode chunk at ANY replica (every candidate's
  ``min_service_s`` floor is known and above the TTL) is shed at the
  fleet edge — ``Dropped(reason="deadline_shed", where="fleet")`` —
  before it ever queues at a replica and wastes decode steps there.
- **Replica lifecycle** (the supervised-restart contract): an engine
  that exhausts its own recovery ladder raises
  :class:`ServingUnrecoverable` — the in-process equivalent of exit 124
  in the taxonomy — and the router treats it exactly as a supervisor
  treats 124: restart the replica (fresh engine through the SHARED
  :class:`buckets.ProgramCache`, so the re-warm compiles NOTHING) and
  re-queue its residents onto the other replicas (``requeue`` preserves
  arrival clocks and deadlines; the re-decode is the same deterministic
  program on the same inputs, so captions stay bit-identical to a
  fault-free run).  ``kill_replica`` is the chaos drill's hard kill —
  same path, counted separately.  A replica that exhausts
  ``restart_limit`` is removed from service (``dead``); when no replica
  is left, :class:`FleetUnrecoverable` maps onto exit 124 at the fleet
  front end — the whole-process supervised restart.
- **Draining rotation** (``rotate``): mark a replica ``draining`` — the
  router stops routing to it and moves its queued-but-unadmitted work
  to live replicas immediately — let its residents finish, then rebuild
  its engine warm from the shared ProgramCache and return it to
  service.  A rolling engine rebuild that never stalls the fleet.
- **Shared result cache**: every replica is built over ONE
  :class:`cache.ResultCache` (serving/cache.py is engine-shareable by
  design), so a caption decoded at replica 0 is a hit at replica 3.
- **Health snapshots**: the scheduler refreshes a per-replica snapshot
  table after every step under ``named_lock("serving.fleet.health")``;
  ``health()`` (safe from the watchdog/heartbeat thread) renders the
  fleet view from those snapshots — worst-of-replicas status plus
  per-replica detail — without ever touching an engine off-thread.

The router SPEAKS THE ENGINE'S SCHEDULER SURFACE (``submit`` / ``step``
/ ``drain`` / ``pop_dropped`` / ``pop_stream_chunks`` / ``stats`` /
``health`` / ``idle`` ...), so :class:`serving.server.CaptionServer`
drives a fleet exactly like one engine — same JSONL wire format, same
drain contract, zero front-end forks (``scripts/serve_fleet.py``).

Streaming across a restart: the router keeps per-request fleet-level
watermarks (``_stream_sent`` / ``_stream_cur``): a killed replica's
request re-decodes from step 0 on its new owner, and the re-derived
tokens fall inside the watermark and are filtered — the engine-rebuild
replay discipline lifted one level, so a streaming client never sees a
duplicate token and the concatenated chunks stay prefix-consistent.

Threading: the router is single-owner like the engine — ``submit`` /
``step`` / ``drain`` / ``rotate`` / ``kill_replica`` run on the
server's scheduler loop thread (the ``owned_by=scheduler`` state
below); only the snapshot table is shared with the watchdog thread,
under the declared ``serving.fleet.health`` lock (a LEAF toward the
registry, per LOCK_ORDER).
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

from ..utils.locksan import declare_order, named_lock
from .engine import (Completion, Dropped, Request, ServingEngine,
                     ServingUnrecoverable, StreamChunk)
from .policy import (STATUS_RANK, deadline_unmeetable, rank_key,
                     worst_status)

log = logging.getLogger("cst_captioning_tpu.serving.fleet")

#: Fleet-level counters (declared at 0 — registry.declare; SERVING.md
#: "Fleet" pins this table the way engine.COUNTERS is pinned).
FLEET_COUNTERS = ("fleet_routed", "fleet_rerouted", "fleet_shed",
                  "fleet_replica_restarts", "fleet_replica_kills")

#: Declared acquisition order (cstlint:lock-order + the runtime
#: sanitizer): the snapshot lock may be held while the registry's leaf
#: lock is taken (a snapshot refresh that also bumps a counter), never
#: the reverse — the registry stays a project-wide leaf.
LOCK_ORDER = ("serving.fleet.health", "telemetry.registry")
declare_order(*LOCK_ORDER)

#: Worst-of ordering for the fleet health status: now the shared
#: :mod:`serving.policy` table (the process-fleet supervisor ranks with
#: the same one); kept under the old private name for in-tree readers.
_STATUS_RANK = STATUS_RANK


class FleetUnrecoverable(RuntimeError):
    """Every replica is out of service and the restart budget is spent:
    in-process supervision is exhausted.  The fleet front end maps this
    onto ``exitcodes.EXIT_WEDGE`` (124) — the same supervised-restart
    signal a single engine's :class:`ServingUnrecoverable` carries."""


class Replica:
    """One supervised engine replica: the engine plus its lifecycle
    bookkeeping (draining flag, restart/kill counts, completed-total
    across engine generations).  ``device`` (optional) pins every engine
    call under ``jax.default_device`` so per-device replicas place their
    state and programs without any engine change."""

    def __init__(self, index: int, factory: Callable[[int], ServingEngine],
                 device=None):
        self.index = int(index)
        self.device = device
        self._factory = factory
        self.engine: Optional[ServingEngine] = None
        self.draining = False
        self.dead = False
        self.restarts = 0
        self.kills = 0
        #: Completions harvested by engines this replica has since
        #: retired (restart/rotation) — per-replica lifetime totals.
        self.completed_prior = 0

    def on_device(self):
        if self.device is None:
            return contextlib.nullcontext()
        return jax.default_device(self.device)

    def start(self, warm: bool = False) -> None:
        with self.on_device():
            self.engine = self._factory(self.index)
            if warm:
                self.engine.warm()

    @property
    def in_service(self) -> bool:
        return self.engine is not None and not self.draining \
            and not self.dead

    def completed_total(self) -> int:
        live = (self.engine.health()["completed"]
                if self.engine is not None else 0)
        return self.completed_prior + live


class FleetRouter:
    """Route requests across N supervised :class:`Replica` instances.

    ``engine_factory(replica_index) -> ServingEngine`` builds one
    replica's engine; the caller bakes the SHARED ``ProgramCache`` /
    ``ResultCache`` and any per-replica fault plan
    (``FaultPlan.for_replica``) into the factory, and the router keeps
    it so a restarted replica rebuilds the same way.  ``devices`` (a
    sequence of jax devices, optional) is assigned round-robin;
    ``restart_limit`` bounds UNPLANNED restarts per replica (rotations
    are maintenance and do not burn it).  All engines must share one
    configuration (the router reports replica 0's geometry as its own).
    """

    def __init__(self, engine_factory: Callable[[int], ServingEngine],
                 replicas: int, *, devices: Optional[Sequence] = None,
                 restart_limit: int = 3, registry=None, lifecycle=None,
                 clock: Callable[[], float] = time.monotonic):
        n = int(replicas)
        if n < 1:
            raise ValueError(f"a fleet needs >= 1 replica, got {n}")
        devs = ([None] * n if not devices
                else [devices[k % len(devices)] for k in range(n)])
        self.restart_limit = max(0, int(restart_limit))
        self._registry = registry
        # The fleet-wide request-lifecycle tracer (telemetry/
        # lifecycle.py, the BASE object): the router owns the intake
        # events (received / routed / fleet-edge shed+drop / killed);
        # replica engines hold `lifecycle.for_replica(k)` labeled views
        # — the caller bakes those into `engine_factory` the same way
        # it bakes the shared caches.  None = untraced (one is-None
        # check per hook).
        self._lifecycle = lifecycle
        self.clock = clock
        # Single-owner scheduler state (the module-docstring contract).
        self._replicas: List[Replica] = [  # cstlint: owned_by=scheduler
            Replica(k, engine_factory, devs[k]) for k in range(n)]
        self._dropped: List[Dropped] = []  # cstlint: owned_by=scheduler
        self._stream_chunks: List[StreamChunk] = []  # cstlint: owned_by=scheduler
        self._evac_done: List[Completion] = []  # cstlint: owned_by=scheduler
        # Fleet stream watermarks: tokens already SENT per request vs
        # tokens emitted by the request's CURRENT owning engine.
        self._stream_sent: Dict[Any, int] = {}  # cstlint: owned_by=scheduler
        self._stream_cur: Dict[Any, int] = {}  # cstlint: owned_by=scheduler
        self._stream_seq: Dict[Any, int] = {}  # cstlint: owned_by=scheduler
        self._routed = 0
        self._rerouted = 0
        self._fleet_shed = 0
        self._restarts = 0
        self._kills = 0
        self._health_lock = named_lock("serving.fleet.health")
        self._snapshots: List[Dict[str, Any]] = []  # cstlint: guarded_by=self._health_lock
        if registry is not None:
            registry.declare(*FLEET_COUNTERS)
        for rep in self._replicas:
            rep.start()
        first = self._replicas[0].engine
        # Fleet-wide config view (shared by construction; the server's
        # stream-degeneracy warn and the fleet-edge shed read these).
        self.chunk = first.chunk
        self.max_len = first.max_len
        self.beam_size = first.beam_size
        self.buckets = first.buckets
        self.deadline_ms = first.deadline_ms
        self._update_snapshots()

    # -- routing -----------------------------------------------------------

    def _candidates(self) -> List[Replica]:
        """In-service replicas, healthy tier first, least-loaded within
        a tier (queue + residents), index as the deterministic tiebreak."""
        active = [r for r in self._replicas if r.in_service]

        def key(rep: Replica):
            # Cheap property reads, not engine.health() — this ranking
            # runs once per routed request (cstlint HOT_PATHS).  The
            # key itself is the shared policy (serving/policy.py), so
            # the process-fleet supervisor places identically.
            eng = rep.engine
            return rank_key(eng.degraded(),
                            eng.queue_depth + eng.resident_count,
                            rep.index)

        return sorted(active, key=key)

    def submit(self, request_id, feats, meta: Optional[dict] = None,
               deadline_ms: Optional[float] = None, stream: bool = False,
               no_cache: bool = False) -> bool:
        """Route one request.  True = accepted somewhere (or answered at
        the fleet edge via a drop record); False = every candidate's
        bounded queue shed it — the fleet-wide backpressure signal."""
        if self._lifecycle is not None:
            # The ROUTER is the fleet's intake: replica engines carry
            # labeled views that drop received/shed (lifecycle.py), so
            # one fleet request is exactly one "received" no matter how
            # many candidates were tried.
            self._lifecycle.emit("received", request_id)
        cands = self._candidates()
        if not cands:
            if any(r.in_service or r.draining for r in self._replicas):
                # Momentarily no routable replica (e.g. the last live
                # one is mid-rotation): SHED — the client's retry signal
                # — never a process-level failure; the rotation will
                # finish and service resumes.
                self._fleet_shed += 1
                self._inc("fleet_shed")
                if self._lifecycle is not None:
                    self._lifecycle.emit("shed", request_id,
                                         where="fleet")
                return False
            raise FleetUnrecoverable(
                "every replica is dead (per-replica restart budget "
                f"{self.restart_limit} exhausted fleet-wide)")
        # A fresh submission is a fresh stream: clear any watermark a
        # previous request with this (client-chosen) id left behind, so
        # a reused id is never silently filtered against stale state.
        self._stream_forget(request_id)
        ttl = (self.deadline_ms if deadline_ms is None
               else float(deadline_ms))
        if ttl and ttl > 0:
            if deadline_unmeetable(
                    ttl, (rep.engine.min_service_s() for rep in cands)):
                # Provably unmeetable EVERYWHERE: shed at the edge, with
                # an explicit answer — never a silent loss, never a
                # queue slot wasted at a replica.
                self._fleet_shed += 1
                self._inc("fleet_shed")
                self._dropped.append(Dropped(request_id, "deadline_shed",
                                             "fleet", meta=meta))
                if self._lifecycle is not None:
                    self._lifecycle.emit("dropped", request_id,
                                         reason="deadline_shed",
                                         where="fleet")
                return True
        for i, rep in enumerate(cands):
            with rep.on_device():
                ok = rep.engine.submit(request_id, feats, meta=meta,
                                       deadline_ms=deadline_ms,
                                       stream=stream, no_cache=no_cache)
            if ok:
                self._routed += 1
                self._inc("fleet_routed")
                if i:
                    self._rerouted += 1
                    self._inc("fleet_rerouted")
                if self._lifecycle is not None:
                    self._lifecycle.emit("routed", request_id,
                                         replica=rep.index,
                                         candidate=i)
                return True
        self._fleet_shed += 1
        self._inc("fleet_shed")
        if self._lifecycle is not None:
            self._lifecycle.emit("shed", request_id, where="fleet")
        return False

    # -- lifecycle ---------------------------------------------------------

    def kill_replica(self, index: int) -> None:
        """Hard replica kill (the chaos drill's stand-in for a replica
        process dying with exit 124): evacuate and re-queue everything
        it owes, then restart it through the shared ProgramCache."""
        rep = self._replicas[int(index)]
        if rep.engine is None:
            return
        rep.kills += 1
        self._kills += 1
        self._inc("fleet_replica_kills")
        log.warning("fleet: hard kill of replica %d (%d resident, "
                    "%d queued)", rep.index, rep.engine.resident_count,
                    rep.engine.queue_depth)
        self._restart_replica(rep)

    def rotate(self, index: int) -> None:
        """Begin draining replica ``index`` for a rolling rebuild: the
        router stops routing to it, queued-but-unadmitted work moves to
        live replicas NOW (it must not wait out the rotation), residents
        finish over the next steps, then ``step`` rebuilds the engine
        warm (zero compiles — shared ProgramCache) and returns the
        replica to service."""
        rep = self._replicas[int(index)]
        if rep.engine is None or rep.dead:
            raise ValueError(f"replica {index} is not serving")
        if rep.draining:
            return
        rep.draining = True
        done, queued = rep.engine.evacuate(include_residents=False)
        self._evac_done.extend(done)
        self._requeue(queued)
        log.info("fleet: rotating replica %d (%d resident(s) draining, "
                 "%d queued moved)", rep.index,
                 rep.engine.resident_count, len(queued))
        self._update_snapshots()

    def _restart_replica(self, rep: Replica) -> None:
        """The supervised-restart path shared by the hard kill and the
        in-process 124 (:class:`ServingUnrecoverable`): evacuate, count,
        rebuild warm (or mark dead past the budget), re-queue."""
        rep.restarts += 1                # budget spend (attempts)
        rep.completed_prior = rep.completed_total()
        self._collect(rep)               # drops/chunks it already owed
        done, reqs = rep.engine.evacuate()
        if self._lifecycle is not None:
            # Every evacuated request was aboard when the replica died:
            # the kill starts its "requeue" attribution window and is
            # the kill→requeue→responded chain the chaos drill pins.
            for req in reqs:
                self._lifecycle.emit("killed", req.request_id,
                                     replica=rep.index)
        self._evac_done.extend(done)
        # A dead replica is not draining: a zombie draining flag would
        # keep the all-dead check below (and ``idle``) from ever firing.
        rep.draining = False
        if rep.restarts > self.restart_limit:
            rep.dead = True
            rep.engine = None
            log.error("fleet: replica %d exhausted its restart budget "
                      "(%d) and is removed from service", rep.index,
                      self.restart_limit)
        else:
            # Counted HERE, where a restart actually happens — the
            # budget-exhausted branch above removes the replica and
            # restarts nothing.
            self._restarts += 1
            self._inc("fleet_replica_restarts")
            rep.start(warm=True)
            log.warning("fleet: replica %d restarted (restart %d/%d); "
                        "re-queuing %d request(s)", rep.index,
                        rep.restarts, self.restart_limit, len(reqs))
        self._requeue(reqs)
        self._update_snapshots()
        if not any(r.in_service or r.draining for r in self._replicas):
            raise FleetUnrecoverable(
                "every replica is dead (per-replica restart budget "
                f"{self.restart_limit} exhausted)")

    def _requeue(self, reqs: List[Request]) -> None:
        """Re-route evacuated requests.  Each placed one counts as
        rerouted; one no candidate accepts is ANSWERED as a fleet-level
        drop — a request may die with its replica's answer, never
        silently."""
        for req in reqs:
            # The new owner re-decodes from step 0; its re-derived
            # stream tokens must fall inside the fleet watermark.
            self._stream_cur[req.request_id] = 0
            placed = False
            for rep in self._candidates():
                with rep.on_device():
                    if rep.engine.requeue(req):
                        placed = True
                        break
            if placed:
                self._rerouted += 1
                self._inc("fleet_rerouted")
                continue
            self._stream_forget(req.request_id)   # terminal answer
            self._dropped.append(Dropped(req.request_id, "admit_failed",
                                         "fleet", meta=req.meta))
            if self._lifecycle is not None:
                self._lifecycle.emit("dropped", req.request_id,
                                     reason="admit_failed", where="fleet")

    def _finish_rotation(self, rep: Replica) -> None:
        """The drained replica's warm rebuild: fresh engine through the
        shared ProgramCache (zero compiles), back in service."""
        self._restarts += 1
        self._inc("fleet_replica_restarts")
        rep.completed_prior = rep.completed_total()
        self._collect(rep)
        rep.start(warm=True)
        rep.draining = False
        log.info("fleet: replica %d rotation complete — rebuilt warm and "
                 "back in service", rep.index)

    # -- scheduling --------------------------------------------------------

    def step(self) -> List[Completion]:
        """One fleet scheduler step: step every replica that has work
        (catching a replica's in-process 124 and restarting it in
        place), finish any rotation whose residents drained, collect
        drops and stream chunks.  Completions evacuated from killed
        replicas (cache hits) are returned first."""
        done: List[Completion] = list(self._evac_done)
        self._evac_done.clear()
        for rep in self._replicas:
            if rep.engine is None:
                continue
            if rep.engine.idle:
                if rep.draining:
                    self._finish_rotation(rep)
                continue
            try:
                with rep.on_device():
                    comps = rep.engine.step()
            except ServingUnrecoverable as e:
                log.error("fleet: replica %d unrecoverable (%s) — "
                          "supervised restart", rep.index, e)
                self._restart_replica(rep)
                done.extend(self._evac_done)
                self._evac_done.clear()
                continue
            done.extend(comps)
            self._collect(rep)
        for comp in done:
            self._stream_forget(comp.request_id)
        self._update_snapshots()
        return done

    def _collect(self, rep: Replica) -> None:
        if rep.engine is None:
            return
        drops = rep.engine.pop_dropped()
        for d in drops:
            # A drop is a TERMINAL answer: release the stream watermark
            # (long-running fleets must not leak an entry per dropped
            # streamed request).
            self._stream_forget(d.request_id)
        self._dropped.extend(drops)
        for ch in rep.engine.pop_stream_chunks():
            out = self._stream_filter(ch)
            if out is not None:
                self._stream_chunks.append(out)

    # -- streaming continuity ----------------------------------------------

    def _stream_filter(self, ch: StreamChunk) -> Optional[StreamChunk]:
        """Fleet-level prefix discipline: only the tokens beyond the
        fleet watermark reach the client, re-sequenced fleet-side — so a
        restart's replayed tokens are filtered and the concatenation of
        a request's chunks still equals its final caption bit for bit."""
        rid = ch.request_id
        sent = self._stream_sent.get(rid, 0)
        cur = self._stream_cur.get(rid, 0) + len(ch.tokens)
        self._stream_cur[rid] = cur
        if cur <= sent:
            return None
        fresh = np.asarray(ch.tokens, np.int32)
        if cur - sent < len(fresh):
            fresh = fresh[len(fresh) - (cur - sent):]
        self._stream_sent[rid] = cur
        seq = self._stream_seq.get(rid, 0)
        self._stream_seq[rid] = seq + 1
        return StreamChunk(rid, seq, fresh, meta=ch.meta)

    def _stream_forget(self, rid) -> None:
        self._stream_sent.pop(rid, None)
        self._stream_cur.pop(rid, None)
        self._stream_seq.pop(rid, None)

    # -- the engine scheduler surface --------------------------------------

    def pop_dropped(self) -> List[Dropped]:
        out, self._dropped = self._dropped, []
        return out

    def pop_stream_chunks(self) -> List[StreamChunk]:
        out, self._stream_chunks = self._stream_chunks, []
        return out

    @property
    def idle(self) -> bool:
        # A pending rotation keeps the fleet non-idle: the next step()
        # finishes it (rebuild + return to service), so step-driven
        # loops (run_until_idle, the server's scheduler) can never
        # stall a replica in ``draining`` forever.
        return (not self._dropped and not self._stream_chunks
                and not self._evac_done
                and not any(r.draining for r in self._replicas)
                and all(r.engine is None or r.engine.idle
                        for r in self._replicas))

    @property
    def resident_count(self) -> int:
        return sum(r.engine.resident_count for r in self._replicas
                   if r.engine is not None)

    @property
    def queue_depth(self) -> int:
        return sum(r.engine.queue_depth for r in self._replicas
                   if r.engine is not None)

    def resident_requests(self) -> List[Request]:
        out: List[Request] = []
        for rep in self._replicas:
            if rep.engine is not None:
                out.extend(rep.engine.resident_requests())
        return out

    def drain(self, abort: Optional[Callable[[], bool]] = None
              ) -> Tuple[List[Completion], List[Request]]:
        """Fleet-wide graceful shutdown: drain every replica (reject its
        queue, finish its residents), same contract as the engine."""
        done: List[Completion] = list(self._evac_done)
        self._evac_done.clear()
        rejected: List[Request] = []
        for rep in self._replicas:
            if rep.engine is None:
                continue
            with rep.on_device():
                d, r = rep.engine.drain(abort=abort)
            done.extend(d)
            rejected.extend(r)
            self._collect(rep)
        self._update_snapshots()
        return done, rejected

    def run_until_idle(self) -> List[Completion]:
        done: List[Completion] = []
        while not self.idle:
            done.extend(self.step())
        return done

    def warm(self) -> Dict[str, Any]:
        """Warm every replica (replica 0 pays the shared ProgramCache's
        builds; the rest re-execute warm) -> ``stats()``."""
        for rep in self._replicas:
            if rep.engine is not None:
                with rep.on_device():
                    rep.engine.warm()
        self._update_snapshots()
        return self.stats()

    # -- stats / health ----------------------------------------------------

    def _engines(self) -> List[ServingEngine]:
        return [r.engine for r in self._replicas if r.engine is not None]

    def fleet_counters(self) -> Dict[str, int]:
        """The ONE definition of the router's audit view (the
        recovery_counters discipline: stats, health, the bench probe,
        and serve_report all render exactly this dict)."""
        return {
            "fleet_routed": self._routed,
            "fleet_rerouted": self._rerouted,
            "fleet_shed": self._fleet_shed,
            "fleet_replica_restarts": self._restarts,
            "fleet_replica_kills": self._kills,
        }

    def recovery_counters(self) -> Dict[str, int]:
        """Replica recovery counters summed fleet-wide (live engines
        only — a restarted engine starts its ladder at 0, which is the
        point: the FLEET counters carry the lifecycle history)."""
        out: Dict[str, int] = {}
        for eng in self._engines():
            for k, v in eng.recovery_counters().items():
                out[k] = out.get(k, 0) + v
        return out

    def cache_counters(self) -> Dict[str, Any]:
        engines = self._engines()
        out: Dict[str, Any] = {"cache_armed": False, "cache_hits": 0,
                               "cache_misses": 0, "cache_evictions": 0,
                               "cache_bypass": 0, "cache_errors": 0,
                               "cache_entries": 0, "cache_capacity": 0}
        for eng in engines:
            c = eng.cache_counters()
            out["cache_armed"] = out["cache_armed"] or c["cache_armed"]
            for k in ("cache_hits", "cache_misses", "cache_evictions",
                      "cache_bypass", "cache_errors"):
                out[k] += c[k]
            # One shared cache: entries/capacity are a property of the
            # cache, not a per-replica sum.
            if c["cache_armed"]:
                out["cache_entries"] = c["cache_entries"]
                out["cache_capacity"] = c["cache_capacity"]
        return out

    def stream_stats(self) -> Dict[str, Any]:
        ttft: List[float] = []
        gaps: List[float] = []
        chunks = 0
        for eng in self._engines():
            t, g = eng.stream_windows_s()
            ttft.extend(t)
            gaps.extend(g)
            chunks += eng.stream_stats()["stream_chunks"]
        t_ms = np.asarray(ttft, np.float64) * 1e3
        g_ms = np.asarray(gaps, np.float64) * 1e3
        p = (lambda a, q: round(float(np.percentile(a, q)), 3)
             if a.size else None)
        return {
            "stream_chunks": chunks,
            "ttft_p50_ms": p(t_ms, 50),
            "ttft_p99_ms": p(t_ms, 99),
            "chunk_gap_p50_ms": p(g_ms, 50),
            "chunk_gap_p99_ms": p(g_ms, 99),
        }

    def stats(self) -> Dict[str, Any]:
        """The engine ``stats()`` shape, aggregated fleet-wide, plus the
        ``per_replica`` rows and the fleet lifecycle counters — so every
        consumer of engine stats (server shed responses, the bench
        probe, serve.py's exit line) reads a fleet unchanged."""
        engines = self._engines()
        estats = [e.stats() for e in engines]
        lat = np.asarray([x for e in engines for x in e.latency_window_s()],
                         np.float64) * 1e3
        pct = (lambda q: float(np.percentile(lat, q)) if lat.size else None)
        out = {
            "replicas": len(self._replicas),
            "in_service": sum(1 for r in self._replicas if r.in_service),
            "slots": sum(s["slots"] for s in estats),
            "buckets": list(self.buckets),
            "beam_size": self.beam_size,
            "decode_chunk": self.chunk,
            "residents": self.resident_count,
            "queue_depth": self.queue_depth,
            "submitted": self._routed,
            "completed": sum(r.completed_total() for r in self._replicas),
            "shed": self._fleet_shed,
            "rejected_drain": sum(s["rejected_drain"] for s in estats),
            # One shared ProgramCache: builds are a fleet-wide property,
            # not a per-replica sum.
            "compiles": estats[0]["compiles"] if estats else 0,
            "chunk_dispatches": sum(s["chunk_dispatches"]
                                    for s in estats),
            "latency_p50_ms": pct(50),
            "latency_p99_ms": pct(99),
            "latency_mean_ms": float(lat.mean()) if lat.size else None,
            "fleet": self.fleet_counters(),
            "per_replica": self.per_replica(),
            **self.recovery_counters(),
            **self.cache_counters(),
            **self.stream_stats(),
        }
        if self._lifecycle is not None:
            # Fleet-wide latency attribution + the per-replica
            # component breakdown (requests grouped by the replica that
            # COMPLETED them — a requeued request counts at its final
            # owner, where its whole story ended).
            out["attribution"] = self._lifecycle.attribution_report()
        return out

    def per_replica(self) -> List[Dict[str, Any]]:
        """Per-replica rows for serve_report / the bench line, from the
        same snapshot table ``health()`` renders."""
        with self._health_lock:
            return [dict(s) for s in self._snapshots]

    def _update_snapshots(self) -> None:
        snaps: List[Dict[str, Any]] = []
        for rep in self._replicas:
            if rep.engine is None:
                h: Dict[str, Any] = {"status": "dead", "queue_depth": 0,
                                     "residents": 0, "recovery": {},
                                     "compiles": 0}
            else:
                h = rep.engine.health()
                if rep.draining:
                    h["status"] = "draining"
            h["replica"] = rep.index
            h["restarts"] = rep.restarts
            h["kills"] = rep.kills
            h["completed"] = rep.completed_total()
            snaps.append(h)
        with self._health_lock:
            self._snapshots = snaps

    def health(self) -> Dict[str, Any]:
        """The fleet health view: worst-of-replicas status plus the
        per-replica detail.  Snapshot-backed — safe to call from the
        watchdog's heartbeat thread while the scheduler owns the
        engines."""
        with self._health_lock:
            per = [dict(s) for s in self._snapshots]
        status = worst_status(s["status"] for s in per)  # dead -> degraded
        return {
            "status": status,
            "replicas": len(per),
            "in_service": sum(1 for s in per
                              if s["status"] in ("ok", "degraded")),
            "queue_depth": sum(s["queue_depth"] for s in per),
            "residents": sum(s["residents"] for s in per),
            "completed": sum(s["completed"] for s in per),
            "fleet": self.fleet_counters(),
            "per_replica": per,
        }

    # -- telemetry ---------------------------------------------------------

    def _inc(self, name: str, n: float = 1) -> None:
        if self._registry is not None:
            self._registry.inc(name, n)
