"""Exact-result cache: one decode per distinct video per configuration.

Real serving traffic is zipfian — the same viral video arrives millions
of times — and an autoregressive decode is deterministic, so the second
identical request should cost a dictionary lookup, not an encoder pass
plus ``max_len`` decode steps.  This is the compiler-first O(1)
autoregressive-caching discipline (PAPERS.md arXiv 2603.09555) applied
one level up: where ``buckets.ProgramCache`` caches *programs* by
configuration identity, this module caches *results* by

    (configuration identity, parameter fingerprint, feature fingerprint)

The identity tuple is built by the engine from the SAME axes as the bench
cache-config identity (``buckets.config_key``: beam, max_len,
decode_chunk, length_norm, decode_kernel, scan_unroll, feature geometry,
dtype), so a tuned-config, kernel, or beam change can never replay a
stale caption — two configurations that could decode differently never
share an entry.  The parameter fingerprint (hashed once at engine
startup) extends that rule to the weights: two engines serving different
checkpoints never share entries either.

Bounded LRU: ``capacity`` entries, least-recently-HIT evicted first.
Hit/miss/evict/bypass counters live with the engine (declared at 0 in
``engine.COUNTERS``); the cache itself is policy-free storage.

Threading: entries live under a named lock (``serving.result_cache``)
so a cache instance may be shared across engines; the lock is a LEAF —
no other project lock is ever acquired while holding it, and callers
keep their registry bumps outside it, so it needs no LOCK_ORDER row.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..utils.locksan import named_lock


def feature_fingerprint(feats: Sequence[np.ndarray]) -> str:
    """Content hash of one request's per-modality features.

    SHA-256 over each array's shape, dtype, and raw bytes — exact, not
    approximate: the cache contract is BIT-identical replay, so only
    bit-identical inputs may share a key.  Host-side numpy only (the
    arrays are the request's pre-``device_put`` host features).
    """
    h = hashlib.sha256()
    for f in feats:
        a = np.ascontiguousarray(np.asarray(f, np.float32))
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def params_fingerprint(variables) -> str:
    """Content hash of the model variables (params tree).

    Paid ONCE at engine startup when a result cache is attached — ~100ms
    for the shipped model — so a shared cache can never serve checkpoint
    A's caption to checkpoint B's engine.  Leaves are hashed in
    deterministic tree order (jax tree flatten order is stable for a
    given structure).
    """
    import jax

    h = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves(variables)
    for leaf in leaves:
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class ResultCache:
    """Bounded LRU of finished caption token rows.

    ``get`` returns a COPY (callers hand tokens to response paths that
    may hold them indefinitely); ``put`` returns how many entries were
    evicted to make room, so the engine can count evictions into its
    declared-at-0 counter.  ``capacity`` <= 0 builds a cache that never
    stores (every lookup misses) — prefer passing ``None`` to the engine
    instead to skip the lookup entirely (counted as bypass there).
    """

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._lock = named_lock("serving.result_cache")
        self._entries: "OrderedDict[Tuple, np.ndarray]" = \
            OrderedDict()  # cstlint: guarded_by=self._lock

    def get(self, key: Tuple) -> Optional[np.ndarray]:
        with self._lock:
            row = self._entries.get(key)
            if row is None:
                return None
            self._entries.move_to_end(key)
            return row.copy()

    def put(self, key: Tuple, tokens: np.ndarray) -> int:
        if self.capacity <= 0:
            return 0
        row = np.asarray(tokens).copy()
        evicted = 0
        with self._lock:
            self._entries[key] = row
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
        return evicted

    def invalidate(self, key: Tuple) -> bool:
        """Drop one entry (a detected-bad hit must not be replayed)."""
        with self._lock:
            return self._entries.pop(key, None) is not None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"size": len(self._entries), "capacity": self.capacity}
